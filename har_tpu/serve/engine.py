"""Fleet serving engine: continuous batching for N concurrent streams.

The single-stream ``StreamingClassifier`` (har_tpu.serving) tops out at
one session per process: every hop pays its own dispatch round-trip, so
a thousand 20 Hz users would mean a thousand tunnel RTTs per second.
The paper's whole point is *continuous monitoring* at population scale
(ROADMAP north star: "serve heavy traffic from millions of users"), so
this module multiplexes N sessions onto the ONE fixed-shape compiled
predict path:

  - per-session ring buffers (the shared ``_WindowAssembler``) turn each
    session's sample deliveries into due windows;
  - a deadline-aware micro-batcher coalesces due windows across sessions
    into power-of-two padded batches — ``StreamingClassifier``'s
    catch-up-burst batching generalized across users, so at most
    log2(target_batch)+1 programs ever compile;
  - admission control (bounded sessions), bounded per-session and global
    queues with backpressure (shed-oldest, never block the producer);
  - a pipelined, mesh-shardable dispatch plane (har_tpu.serve.dispatch):
    windows stage ONCE into a contiguous arena at enqueue, batches
    launch asynchronously (device_put + jitted predict, un-fetched)
    and retire in strict FIFO order, so with pipeline_depth > 1 the
    host assembles batch N+1 while batch N scores on-device — and with
    a >1-device mesh attached the batch rows shard across the mesh
    (pad policy: devices × pow2, the same log2 program budget);
  - per-dispatch retry + SLO tracking with graceful degradation, in
    strict order: shed smoothing first (host-side work, events keep
    flowing with raw labels), then shed scoring by dropping the STALEST
    queued windows — the batch never blocks on one slow stream;
  - a fault-injection hook on the dispatch path (see
    ``har_tpu.serve.faults``) so every one of those paths is provable
    under test, not hoped at.

Correctness is pinned, not hoped: with the same delivery chunks, a
fleet-multiplexed session emits bit-identical ``StreamEvent``s to a
standalone ``StreamingClassifier`` (tests/test_fleet_serving.py) —
guaranteed by construction, because window assembly, smoothing and
drift monitoring are the same shared objects, and scoring is row-
independent under any batch composition.

Single-threaded by design: at 20 Hz × thousands of sessions the host
work (ring rolls + EWMAs) is microseconds per delivery; the scarce
resource is dispatches, which is exactly what the micro-batcher
amortizes.  ``push`` ingests, ``poll`` dispatches what is due,
``flush`` drains — the caller owns the loop (CLI, bench lane, or an
async transport shim).
"""

from __future__ import annotations

import dataclasses
import gc
import time
import warnings
import zlib
from collections import deque
from typing import Callable, Hashable, Sequence

import numpy as np

from har_tpu.serve.arena import (
    PendingArena,
    SessionArena,
    _ArenaAssembler,
    _SlotSmoother,
)
from har_tpu.serve.dispatch import (
    DispatchTicket,
    HostScorer,
    StagingArena,
    compact_probs,
    make_scorer,
)
from har_tpu.monitoring import DriftMonitor
from har_tpu.serve.journal import (
    FleetJournal,
    JournalConfig,
    monitor_from_state,
    monitor_state,
)
from har_tpu.serve.stats import FleetStats, HostProfile
from har_tpu.utils.backoff import Backoff, retry_call
from har_tpu.serving import (
    StreamEvent,
    finite_rows,
    measure_device_latency,
)


# "mesh unchanged" sentinel for FleetServer.resize — None is a real
# mesh value there (back to single-device), so absence needs its own
_MESH_UNSET = object()


def _mesh_devices(mesh) -> int:
    """Data-shard count of a dispatch mesh (1 for no mesh) — the
    capacity-direction arithmetic's device factor."""
    if mesh is None:
        return 1
    from har_tpu.parallel.mesh import data_shard_count

    return data_shard_count(mesh)


class AdmissionError(RuntimeError):
    """Session refused: fleet at max_sessions, or duplicate/unknown id."""


class DispatchError(RuntimeError):
    """A batched predict failed after all retries; its windows were
    dropped (reason ``dispatch_failed``) and the engine kept serving."""


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Scheduling, bounding and degradation knobs for a FleetServer."""

    # admission control: sessions beyond this are refused, not queued —
    # a fleet that silently over-admits degrades everyone
    max_sessions: int = 4096
    # bounded per-session queue: a session whose consumer stalls sheds
    # its own oldest windows instead of growing without bound
    max_pending_per_session: int = 64
    # global bound: total live queued windows before backpressure sheds
    # the stalest queued windows fleet-wide
    max_queue_windows: int = 65536
    # micro-batcher: dispatch when this many windows are due ...
    target_batch: int = 256
    # ... or when the oldest queued window has waited this long — the
    # deadline that bounds event latency at light load (a lone session
    # must not wait forever for 255 peers)
    max_delay_ms: float = 50.0
    # SLO for one batched dispatch (e2e, through the tunnel); breaches
    # drive the degradation ladder
    dispatch_timeout_ms: float = 1000.0
    # transparent re-dispatches of a FAILED (raised) transform before
    # the batch's windows are dropped
    retries: int = 1
    # consecutive SLO breaches before degrading, and consecutive
    # within-SLO dispatches before stepping back up
    degrade_after_breaches: int = 2
    recover_after_ok: int = 2
    # fraction of the live queue shed (stalest first) at degradation
    # level 2 — scoring shed, the last resort before unbounded latency
    shed_fraction: float = 0.5
    # ingest guard: sample rows that are non-finite or exceed this
    # magnitude are rejected per-session (counted, never raised) before
    # they can poison a micro-batch; None disables the range check but
    # never the NaN/Inf one (serving.finite_rows)
    max_abs_sample: float | None = 1e6
    # dispatch pipelining: batches in flight on-device before the host
    # blocks on a retire — a ring of up to ``depth`` launched
    # DispatchTickets.  1 = the synchronous engine (launch then retire
    # back-to-back, operation-identical to PR-2); 2 = classic double
    # buffering; >= 3 keeps the device busy across a SLOW host round
    # (up to depth-1 tickets carry between polls, so one long delivery
    # round no longer drains the pipe).  Retire order stays strictly
    # FIFO, so events, smoothing and journal acks are emitted in the
    # exact synchronous order at any depth (test-pinned bit-identical
    # at N=64; chaos matrix green at depths 1-4).
    pipeline_depth: int = 1
    # fused on-device hot loop (har_tpu.serve.dispatch): collapse the
    # host-scaler → device_put → jitted-logits → host-fetch → argmax
    # chain into ONE jitted program per padded shape — scale, score,
    # argmax and top-prob all on device, batches staged through
    # preallocated pooled slabs (zero per-dispatch allocation), retire
    # fetching only the small (labels, top_probs) pair.  Applies when
    # the scorer is device-backed AND smoothing is fused-ELIGIBLE
    # (vote/none — decisions need only labels; EMA needs the full
    # probability vector and always serves unfused).  Event
    # probabilities on the fused path are the compact decision-
    # confidence surrogate (dispatch.compact_probs): labels, raw
    # labels, drift and the decision confidence are unchanged — the
    # fused contract is LABEL equality with the unfused path
    # (test-pinned at N=64 under FakeClock+DispatchFaults), which is
    # why it is opt-in rather than the default.
    fused: bool = False
    # per-poll host-time breakdown (ingest / due-select / gather /
    # retire / journal) recorded into stage histograms and stamped
    # into ``stats_snapshot()["host_profile"]`` — the observability
    # hook the sessions-per-worker ceiling curve and future host-plane
    # regressions read (``har serve --profile-host``).  Off by default:
    # the clock reads it adds are per dispatch/poll, cheap but not
    # free, and the profile measures THIS process (never journaled).
    profile_host: bool = False

    @classmethod
    def for_sessions(cls, n_sessions: int, **overrides) -> "FleetConfig":
        """A config sized for ``n_sessions`` concurrent sessions:
        ``max_sessions`` auto-raises to at least that many unless an
        explicit override says otherwise — the CLI path (`har serve
        --sessions N`) builds its config here, so a 10k-session run no
        longer dies at admission against the 4096 default (test-pinned;
        an explicit ``max_sessions=`` override still wins)."""
        overrides.setdefault("max_sessions", max(int(n_sessions), 1))
        return cls(**overrides)

    def __post_init__(self):
        if self.max_sessions <= 0 or self.target_batch <= 0:
            raise ValueError("max_sessions and target_batch must be positive")
        if not (0.0 < self.shed_fraction <= 1.0):
            raise ValueError("shed_fraction must be in (0, 1]")
        if self.pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")


@dataclasses.dataclass(frozen=True)
class FleetEvent:
    """One session's StreamEvent as emitted by the fleet.

    ``event`` is bit-identical to what a standalone StreamingClassifier
    would emit for the same delivery chunks (latency fields excepted —
    they measure this engine's dispatches).  ``degraded=True`` marks an
    event emitted while smoothing was shed: label == raw_label and the
    session's smoothing state was left frozen.
    """

    session_id: Hashable
    event: StreamEvent
    degraded: bool = False


# The per-window ``_Pending`` Python object is gone (PR 14): a queued
# window is a SLOT into ``har_tpu.serve.arena.PendingArena`` — parallel
# arrays for (session slot, t_index, staging slot, t_enqueue, drift)
# plus ``dropped``/``launched`` bitmasks, the global FIFO as an index
# ring, and each session's pending view as a ``next_idx`` linked list
# hung off the session arena's ``pend_head``/``pend_tail`` columns.
# The semantics are byte-for-byte the per-object queue's: flagging a
# drop leaves the entry in place in both views (launched windows ride
# their in-flight dispatch to retire, which skips the flagged rows),
# and the slot recycles only when both the queue-side and the
# session-list references are released.


def _arena_counter(name: str, doc: str):
    """A _FleetSession counter living in the session arena's int
    arrays: attribute reads/writes go through the slot, so the
    sequential code paths keep their ``sess.n_scored += 1`` shape
    while the batched ingest/retire paths update whole delivery
    rounds with one scatter-add."""

    def fget(self):
        return int(getattr(self.arena, name)[self.slot])

    def fset(self, value):
        getattr(self.arena, name)[self.slot] = value

    return property(fget, fset, doc=doc)


class _FleetSession:
    """Per-session handle: slot into the SoA arena + façades.

    The heavy per-session state (ring, smoother arrays, counters) lives
    in the server's ``SessionArena``; this object carries the slot and
    the shared-code façades (``asm``/``smoother``).  The session's
    pending view is the ``PendingArena`` linked list anchored at the
    session arena's ``pend_head``/``pend_tail`` columns for this slot
    — no per-session queue object at all.  The counter properties read
    through to the arena so every pre-SoA code path (sheds, replay,
    export, cluster hand-off) works unchanged."""

    __slots__ = ("sid", "asm", "smoother", "arena", "slot")

    def __init__(self, sid, asm, smoother, arena, slot):
        self.sid = sid
        self.asm = asm
        self.smoother = smoother
        self.arena = arena
        self.slot = slot

    n_live = _arena_counter("n_live", "live (queued or in-flight) windows")
    n_enqueued = _arena_counter("n_enqueued", "windows enqueued")
    n_scored = _arena_counter("n_scored", "windows scored")
    n_dropped = _arena_counter("n_dropped", "windows dropped")
    # samples delivered by the transport INCLUDING rows the ingest
    # guard rejected — the watermark must speak the transport's raw
    # stream coordinates, or one rejected NaN row would shift every
    # post-crash re-delivery by one sample
    raw_seen = _arena_counter("raw_seen", "raw transport watermark")
    # cluster hand-off generation: bumped every time this session is
    # ADOPTED onto a worker (har_tpu.serve.cluster).  A crash mid-
    # hand-off can leave the session on both the source and the
    # target journal; the copy with the higher generation is the
    # adopted one and wins the dual-ownership resolution.
    handoffs = _arena_counter("handoffs", "cluster hand-off generation")


class FleetServer:
    """Session-multiplexing scheduler over one compiled predict path.

    Parameters mirror ``StreamingClassifier`` (window geometry,
    smoothing) plus a ``FleetConfig`` for scheduling/bounding knobs.

    ``fault_hook(windows)`` — called before every dispatch attempt with
    the padded batch; may raise (simulated dispatch failure → retry
    path) or stall (simulated slow tunnel → SLO/degradation path).

    ``clock`` — injectable monotonic-seconds source; every deadline,
    SLO and histogram measurement reads it, so tests drive the
    scheduler deterministically with a fake clock.
    """

    def __init__(
        self,
        model,
        *,
        window: int = 200,
        hop: int = 20,
        channels: int = 3,
        smoothing: str = "ema",
        ema_alpha: float = 0.4,
        vote_depth: int = 5,
        class_names: Sequence[str] | None = None,
        config: FleetConfig | None = None,
        fault_hook: Callable[[np.ndarray], None] | None = None,
        clock: Callable[[], float] | None = None,
        model_version: str = "v0",
        journal: FleetJournal | str | None = None,
        journal_config: JournalConfig | None = None,
        mesh=None,
    ):
        if window <= 0 or hop <= 0:
            raise ValueError("window and hop must be positive")
        if smoothing not in ("ema", "vote", "none"):
            raise ValueError(f"unknown smoothing {smoothing!r}")
        # same construction-time guards as StreamingClassifier: a bad
        # smoothing knob must fail HERE, not crash inside poll() after
        # windows are already queued
        if smoothing == "ema" and not (0.0 < ema_alpha <= 1.0):
            raise ValueError("ema_alpha must be in (0, 1]")
        if smoothing == "vote" and vote_depth < 1:
            raise ValueError("vote_depth must be >= 1")
        self.model = model
        self.model_version = str(model_version)
        self.window = int(window)
        self.hop = int(hop)
        self.channels = int(channels)
        self.smoothing = smoothing
        self.ema_alpha = float(ema_alpha)
        self.vote_depth = int(vote_depth)
        self.class_names = list(class_names) if class_names else None
        self.config = config or FleetConfig()
        self.stats = FleetStats()
        self._fault_hook = fault_hook
        self._clock = clock or time.monotonic
        self._sessions: dict[Hashable, _FleetSession] = {}
        # admitted sessions carrying a DriftMonitor — when zero (the
        # common unmonitored fleet), the batched ingest skips the
        # whole per-row monitor plumbing
        self._n_monitors = 0
        # session arena slot -> live _FleetSession handle: how the
        # array-indexed pending queue gets back to a session object
        # (sid for journal records, the smoother façade for fallback
        # smoothing).  Only LIVE pending entries are ever looked up —
        # a removed session's entries are flagged dropped first and
        # every queue path skips flagged entries before touching
        # session state — so a recycled slot is never read stale.
        self._sess_by_slot: list = []
        # the structure-of-arrays session estate (har_tpu.serve.arena):
        # ring buffers, ring heads/fills, smoother state and per-session
        # counters live in ONE contiguous arena; a session is a slot
        # index, admission allocates and removal/hand-off recycles.
        # Sized small and grown geometrically: a 64-session fleet must
        # not pay a max_sessions-sized allocation up front.
        self._session_arena = SessionArena(
            self.window, self.channels, self.vote_depth,
            capacity=min(self.config.max_sessions, 1024),
        )
        self._ema_kernel = self._session_arena.ema_block_for(
            self.ema_alpha
        )
        # per-poll host-time breakdown (FleetConfig.profile_host)
        self.host_profile = (
            HostProfile() if self.config.profile_host else None
        )
        # the SoA pending queue (har_tpu.serve.arena.PendingArena):
        # queued windows as slot-indexed parallel arrays, the global
        # FIFO as an index ring — zero per-window Python objects on
        # the enqueue→retire path
        self._pending = PendingArena(
            capacity=max(2 * self.config.target_batch, 64)
        )
        self._n_live = 0
        # live windows still IN the queue (not yet launched on-device):
        # what the micro-batcher's due() reasons over.  _n_live keeps
        # counting launched-but-unretired windows too — those are still
        # "pending" in the conservation law until their ack.
        self._n_unlaunched = 0
        # contiguous staging for queued windows: the assembler writes
        # each completed window here ONCE at enqueue; batch assembly is
        # a gather (har_tpu.serve.dispatch.StagingArena)
        self._arena = StagingArena(
            self.window, self.channels,
            capacity=max(2 * self.config.target_batch, 64),
        )
        # fused hot-loop staging: preallocated slabs keyed by padded
        # batch shape, recycled at retire — a fused launch gathers
        # straight into a pooled slab (one copy, zero per-dispatch
        # allocation) instead of gather + pad.  At most pipeline_depth
        # slabs per shape are ever live; process-local by design (the
        # staged windows themselves still ride the snapshot's pending
        # array, like the arena)
        self._slab_pool: dict[int, list[np.ndarray]] = {}
        # dispatch backend: built lazily from (model, mesh) — a >1-device
        # mesh shards the batch, a jitted model launches async, anything
        # else scores synchronously through model.transform
        self._mesh = mesh
        self._scorer = None
        # launched-but-not-retired dispatch tickets, FIFO.  With
        # pipeline_depth > 1 up to depth-1 tickets survive BETWEEN
        # polls, so the device crunches a batch while the host ingests
        # the next delivery round; snapshots serialize their windows as
        # pending (they are un-acked by construction), so a crash with
        # a ticket in flight loses nothing
        self._inflight: deque[DispatchTicket] = deque()
        # degradation ladder state
        self._smoothing_shed = False
        self._breaches = 0
        self._ok_streak = 0
        # device calibration results keyed by padded batch size
        self._device_ms: dict[int, dict] = {}
        # hot-swap state (har_tpu.adapt): a staged swap applies at the
        # next dispatch BOUNDARY, so an in-flight batch always completes
        # on the model that started scoring it
        self._staged_swap: tuple | None = None
        # elastic resize state (har_tpu.serve.traffic): same boundary
        # discipline as the swap — a staged resize applies at the next
        # dispatch boundary, and in-flight tickets retire on the OLD
        # scorer/placement (each ticket carries its own scorer)
        self._staged_resize: dict | None = None
        self._in_dispatch = False
        # dispatch tap (shadow evaluation): called AFTER a batch's
        # events are finalized, off the per-event latency path
        self._dispatch_tap: Callable | None = None
        # retry pacing (har_tpu.utils.backoff): the ONE policy the
        # dispatch retry loop and the cluster control plane share.  The
        # hot path never sleeps on it (retry_call gets sleep=None) but
        # consuming/resetting the schedule here keeps the two retry
        # surfaces on the same accounting
        self._retry_backoff = Backoff(seed=0)
        # durability (har_tpu.serve.journal): an attached journal makes
        # every mutation below crash-recoverable; _replaying suppresses
        # re-journaling while recovery replays the suffix through these
        # same code paths
        self._journal: FleetJournal | None = None
        self._replaying = False
        # storage-fault containment: True while the last journal write/
        # fsync FAILED (ENOSPC, a dying disk) — the serving loop keeps
        # running as a counted, declared degradation (acks in the
        # failed window are not durable), and snapshots are refused
        # until a flush succeeds (a rotation would prune the segments
        # the un-flushed suffix still needs)
        self._journal_degraded = False
        # extra snapshot state registered by controllers riding this
        # server (the AdaptationEngine persists its episode/probation
        # state here), and what recovery read back for them
        self.snapshot_providers: dict[str, Callable[[], dict]] = {}
        self.recovered_extra: dict = {}
        # arena sizing rides the provider hook for observability; the
        # staged windows themselves ride the snapshot's existing
        # ``pending`` array (format unchanged — pre-arena journals
        # restore cleanly, test-pinned)
        self.snapshot_providers["staging_arena"] = self._arena.state
        # SoA estate sizing (observability only: per-session state
        # serializes back to the per-session snapshot layout, so the
        # on-disk format predates — and outlives — the arena)
        self.snapshot_providers["session_arena"] = (
            self._session_arena.state
        )
        # pending-queue sizing (observability only, same stance: the
        # queued windows themselves serialize back to the snapshot's
        # stacked ``pending`` array in global FIFO order)
        self.snapshot_providers["pending_arena"] = self._pending.state
        if journal is not None:
            self.attach_journal(journal, journal_config)

    # ----------------------------------------------------- durability

    def attach_journal(
        self,
        journal: FleetJournal | str,
        config: JournalConfig | None = None,
        *,
        snapshot: bool = True,
        require_fresh: bool = True,
    ) -> FleetJournal:
        """Attach a write-ahead journal (a FleetJournal or a directory
        path) and write the attach-time snapshot — from then on every
        fleet mutation is crash-recoverable via ``FleetServer.restore``.
        The snapshot makes recovery unconditional: a journal directory
        always holds at least one complete state to replay from.

        A FRESH attach onto a directory that already holds a journal is
        refused (``require_fresh``): the attach snapshot's rotation
        would silently destroy the crashed fleet's recovery data —
        restore first (``FleetServer.restore`` / ``--resume``) or point
        at an empty directory.  ``FleetServer.restore`` re-attaches
        with ``require_fresh=False`` after it has replayed the state."""
        if isinstance(journal, str):
            journal = FleetJournal(journal, config)
        if require_fresh and journal.has_state():
            from har_tpu.serve.journal import JournalError

            raise JournalError(
                f"journal directory {journal.root} already holds a "
                "fleet journal; attaching fresh would destroy its "
                "crash-recovery data — resume it (FleetServer.restore "
                "/ `har serve --resume`) or use an empty directory"
            )
        self._journal = journal
        self._journal_degraded = False
        if snapshot:
            self.write_snapshot()
        return journal

    @property
    def journal(self) -> FleetJournal | None:
        return self._journal

    def _chaos(self, point: str) -> None:
        """Kill-point hook: no-op in production, raises a simulated
        crash at the chaos harness's chosen stage boundary."""
        if self._journal is not None:
            self._journal.chaos_point(point)

    def _note_journal_error(self, what: str, exc: OSError) -> None:
        """One storage failure absorbed: count it, warn loudly, mark
        the journal degraded.  The records stay buffered (FleetJournal
        keeps a failed flush retry-safe), so a later successful flush
        restores full durability with nothing lost — the degradation
        window is exactly the crash-reemission risk the warning
        declares."""
        self._journal_degraded = True
        self.stats.journal_write_errors += 1
        warnings.warn(
            f"journal {what} failed ({exc}): serving continues, but "
            "acks in this window are NOT durable — a crash now may "
            "re-emit already-delivered events; snapshots are refused "
            "until a flush succeeds (journal_write_errors="
            f"{self.stats.journal_write_errors})",
            RuntimeWarning,
            stacklevel=3,
        )

    def _contained_flush(self, what: str) -> bool:
        """Flush the journal, absorbing a storage failure as the
        declared degradation above instead of killing the serving
        loop.  Returns True when everything appended so far is
        durable."""
        try:
            self._journal.flush()
        except OSError as exc:
            self._note_journal_error(what, exc)
            return False
        self._journal_degraded = False
        return True

    def _jappend(self, meta: dict, payload: bytes = b"") -> None:
        if self._journal is not None and not self._replaying:
            try:
                self._journal.append(meta, payload)
            except OSError as exc:
                # the record itself is safely buffered — only the
                # flush_every auto-flush can raise here
                self._note_journal_error("append", exc)

    def write_snapshot(self) -> None:
        """Persist full fleet state to the journal (atomic; rotates the
        journal segment).  Called automatically at the snapshot cadence
        (JournalConfig.snapshot_every) from poll().

        REFUSED while the journal is degraded (a preceding write/fsync
        failed): the rotation would prune segments while the un-flushed
        suffix is still the only durable record of delivered events —
        the acks-not-durable refusal.  A snapshot whose own write fails
        is absorbed the same way; the pre-failure snapshot + segments
        stay authoritative (write_snapshot is atomic)."""
        if self._journal is None:
            return
        if self._journal_degraded and not self._contained_flush(
            "pre-snapshot flush"
        ):
            warnings.warn(
                "snapshot refused: journal degraded (acks not "
                "durable); retrying the flush at the next poll",
                RuntimeWarning,
                stacklevel=2,
            )
            return
        state, arrays = self._snapshot_state()
        try:
            self._journal.write_snapshot(state, arrays)
        except OSError as exc:
            self._note_journal_error("snapshot", exc)

    def _snapshot_state(self) -> tuple[dict, dict]:
        """Everything a dead process needs restated: geometry + config,
        per-session assembler/smoother/monitor state, the live queue in
        global FIFO order, stats counters, and controller extras."""
        sids = list(self._sessions)
        sessions = []
        arrays: dict[str, np.ndarray] = {}
        for i, sid in enumerate(sids):
            sess = self._sessions[sid]
            asm = sess.asm
            arrays[f"ring{i}"] = asm._ring
            sm = sess.smoother
            if sm._ema is not None:
                arrays[f"ema{i}"] = np.asarray(sm._ema, np.float64)
            sessions.append(
                {
                    "sid": sid,
                    "n_seen": asm._n_seen,
                    "raw_seen": sess.raw_seen,
                    "next_emit": asm._next_emit,
                    "n_enqueued": sess.n_enqueued,
                    "n_scored": sess.n_scored,
                    "n_dropped": sess.n_dropped,
                    "handoffs": sess.handoffs,
                    "votes": list(sm._votes),
                    "monitor": monitor_state(asm.monitor),
                }
            )
        # the live queue in global FIFO order: in-flight tickets FIRST
        # (they left the queue before anything still in it — an
        # un-retired batch is un-acked by construction, so its windows
        # are snapshot as ordinary pending and a crash with a ticket
        # in flight recovers them for re-scoring), then the FIFO ring;
        # dropped-but-unpopped entries are skipped, exactly like the
        # per-object serializer skipped flagged objects
        pq = self._pending
        parts = [t.batch for t in self._inflight]
        parts.append(pq.ring_indices())
        order = np.concatenate(parts) if len(parts) > 1 else parts[0]
        order = order[~pq.dropped[order]]
        slot_to_i = np.full(self._session_arena.capacity, -1, np.int64)
        for i, sid in enumerate(sids):
            slot_to_i[self._sessions[sid].slot] = i
        pending_meta = [
            [int(si), int(ti), bool(dr)]
            for si, ti, dr in zip(
                slot_to_i[pq.sess_slot[order]].tolist(),
                pq.t_index[order].tolist(),
                pq.drift[order].tolist(),
            )
        ]
        if len(order):
            # gathered OUT of the arena at snapshot time: the on-disk
            # layout is the same stacked array pre-arena snapshots used
            arrays["pending"] = self._arena.gather(pq.stage_slot[order])
        state = {
            "geometry": {
                "window": self.window,
                "hop": self.hop,
                "channels": self.channels,
                "smoothing": self.smoothing,
                "ema_alpha": self.ema_alpha,
                "vote_depth": self.vote_depth,
                "class_names": self.class_names,
                "model_version": self.model_version,
            },
            "config": dataclasses.asdict(self.config),
            "ladder": {
                "smoothing_shed": self._smoothing_shed,
                "breaches": self._breaches,
                "ok_streak": self._ok_streak,
            },
            "stats": self.stats.state(),
            "sessions": sessions,
            "pending": pending_meta,
            "extra": {
                name: fn() for name, fn in self.snapshot_providers.items()
            },
        }
        return state, arrays

    @classmethod
    def restore(cls, journal_dir: str, model, **kwargs) -> "FleetServer":
        """Recover a crashed fleet: load the newest snapshot, replay the
        journal suffix, re-attach the journal.  See
        ``har_tpu.serve.recover.restore_server`` for the full contract
        (``model`` may be one model object or a ``version -> model``
        loader callable)."""
        from har_tpu.serve.recover import restore_server

        return restore_server(journal_dir, model, **kwargs)

    def _enqueue_pending(
        self, sess, t_index: int, stage_slot, drift: bool, now: float
    ) -> int:
        """Scalar enqueue: claim a pending slot, append it to the
        global FIFO ring and link it onto the session's pending list —
        the sequential ``push``/replay/flush path (the batched rounds
        do the same in one vectorized block, ``PendingArena.add_block``
        + ``_link_pending_block``)."""
        pq = self._pending
        i = pq.add(sess.slot, t_index, stage_slot, drift, now)
        arena = self._session_arena
        tail = arena.pend_tail[sess.slot]
        if tail >= 0:
            pq.next_idx[tail] = i
        else:
            arena.pend_head[sess.slot] = i
        arena.pend_tail[sess.slot] = i
        return i

    def _link_pending_block(self, sess_slots, idx) -> None:
        """Vectorized tail-link of one enqueued block onto its
        sessions' pending lists (sessions DISTINCT within the block —
        the delivery-round shape)."""
        pq = self._pending
        arena = self._session_arena
        prev = arena.pend_tail[sess_slots]
        has = prev >= 0
        if has.any():
            pq.next_idx[prev[has]] = idx[has]
        fresh = ~has
        if fresh.any():
            arena.pend_head[sess_slots[fresh]] = idx[fresh]
        arena.pend_tail[sess_slots] = idx

    def _session_pop_head(self, sess) -> None:
        """Pop the head of the session's pending list, releasing its
        session-list reference (the queue-side reference — ring or
        ticket — is tracked separately)."""
        pq = self._pending
        arena = self._session_arena
        h = arena.pend_head[sess.slot]
        nxt = pq.next_idx[h]
        arena.pend_head[sess.slot] = nxt
        if nxt < 0:
            arena.pend_tail[sess.slot] = -1
        pq.release(h)

    def _restore_pending(self, sess, t_index, window, drift, now) -> int:
        """Recovery path (har_tpu.serve.recover): re-stage one pending
        window into the arena and re-enqueue it in global FIFO order."""
        i = self._enqueue_pending(
            sess, int(t_index), self._arena.put(window), bool(drift), now
        )
        sess.n_live += 1
        self._n_live += 1
        self._n_unlaunched += 1
        return i

    def _release_pending(self, i: int) -> None:
        """Recovery path: a replayed ack/drop consumed this recovered
        window — flag it, free its staging slot and take it off the
        live queue counters (the record's own accounting and the
        session-list pop are the caller's job)."""
        pq = self._pending
        pq.dropped[i] = True
        self._arena.free(pq.stage_slot[i])
        self._session_arena.n_live[pq.sess_slot[i]] -= 1
        self._n_live -= 1
        self._n_unlaunched -= 1

    def watermark(self, session_id: Hashable) -> int:
        """Samples durably delivered for this session, in the
        TRANSPORT's raw stream coordinates (rows the ingest guard
        rejected included) — where a resuming transport should restart
        delivery after a crash.  Re-delivering from here makes recovery
        lossless (windows_lost == 0): the assembler applies the same
        guard to the same rows, so its state is deterministic in the
        raw stream."""
        return self._sessions[session_id].raw_seen

    def declare_lost(self, session_id: Hashable, stream_position: int) -> int:
        """A resuming transport that CANNOT replay declares the gap:
        samples between the recovered watermark and ``stream_position``
        are gone.  The assembler fast-forwards (the next window needs a
        full fresh fill — no window may silently mix pre-gap zeros with
        post-gap samples), and every window an uninterrupted run would
        have emitted from the gap is counted as enqueued AND
        lost_in_crash, extending the conservation law to
        ``enqueued == scored + dropped + pending + lost_in_crash``.
        Returns the number of windows lost; bounded by the journal
        flush interval times the push rate."""
        sess = self._sessions.get(session_id)
        if sess is None:
            raise AdmissionError(f"unknown session {session_id!r}")
        asm = sess.asm
        pos = int(stream_position)
        gap = pos - sess.raw_seen  # transport coordinates
        if gap <= 0:
            return 0
        # the gap is applied in ACCEPTED-sample space assuming the lost
        # rows were clean (what the guard would have rejected in them
        # is unknowable); boundaries b (grid next_emit, next_emit+hop,
        # ...) need samples (b-window, b] — any b < end+window would
        # include lost samples
        end = asm._n_seen + gap
        first_ok = end + self.window
        lost = max(
            0, -(-(first_ok - asm._next_emit) // self.hop)  # ceil div
        )
        asm._next_emit += lost * self.hop
        asm._ring[:] = 0.0
        asm._n_seen = end
        sess.raw_seen = pos
        if lost:
            sess.n_enqueued += lost
            self.stats.enqueued += lost
            self.stats.lost_in_crash += lost
            self._jappend(
                {"t": "lost", "sid": session_id, "pos": pos, "n": lost}
            )
        return lost

    # ------------------------------------------------------- sessions

    def _new_session(self, session_id: Hashable, monitor) -> _FleetSession:
        """Allocate an arena slot and build the session handle with its
        shared-code façades (har_tpu.serve.arena) — the one constructor
        behind admission and cluster adoption, so slot recycling cannot
        diverge between the two."""
        arena = self._session_arena
        before = arena.grows
        slot = arena.alloc()
        if arena.grows != before:
            # growth reallocated the ring block: re-point every live
            # assembler's ring view at the new storage (rare, amortized
            # — the scalars read through properties and need no fix-up)
            for s in self._sessions.values():
                s.asm._ring = arena.rings[s.slot]
        if slot >= len(self._sess_by_slot):
            self._sess_by_slot.extend(
                [None] * (arena.capacity - len(self._sess_by_slot))
            )
        sess = _FleetSession(
            session_id,
            _ArenaAssembler(
                arena, slot, self.window, self.hop, self.channels,
                monitor=monitor,
            ),
            _SlotSmoother(
                arena, slot, self.smoothing, self.ema_alpha,
                self.vote_depth,
            ),
            arena,
            slot,
        )
        self._sess_by_slot[slot] = sess
        return sess

    def add_session(self, session_id: Hashable, *, monitor=None) -> None:
        """Admit a session (optionally with its own DriftMonitor, whose
        verdicts then flow into the multiplexed event stream).  Raises
        AdmissionError at max_sessions — bounded by construction."""
        if session_id in self._sessions:
            raise AdmissionError(f"session {session_id!r} already admitted")
        if len(self._sessions) >= self.config.max_sessions:
            self.stats.admission_rejections += 1
            raise AdmissionError(
                f"fleet full ({self.config.max_sessions} sessions); "
                "remove a session or raise FleetConfig.max_sessions"
            )
        self._sessions[session_id] = self._new_session(
            session_id, monitor
        )
        if monitor is not None:
            self._n_monitors += 1
        self.stats.sessions = len(self._sessions)
        # the add record carries the monitor's full state so a session
        # admitted after the last snapshot recovers WITH its monitor
        self._jappend(
            {"t": "add", "sid": session_id, "mon": monitor_state(monitor)}
        )

    def remove_session(self, session_id: Hashable) -> None:
        """Evict a session; its queued windows are dropped (reason
        ``session_removed``)."""
        sess = self._sessions.pop(session_id, None)
        if sess is None:
            raise AdmissionError(f"unknown session {session_id!r}")
        if sess.asm.monitor is not None:
            self._n_monitors -= 1
        pq = self._pending
        arena = self._session_arena
        n = 0
        n_unlaunched = 0
        # walk the session's pending list: flag live entries dropped
        # and clear the list, releasing every session-list reference
        # (flagged entries stay in the ring / their in-flight ticket,
        # whose pop/retire skips them and releases the other ref)
        i = arena.pend_head[sess.slot]
        while i >= 0:
            nxt = pq.next_idx[i]
            if not pq.dropped[i]:
                pq.dropped[i] = True
                n += 1
                if not pq.launched[i]:
                    # launched windows already left the un-launched
                    # count at their dispatch; retire skips their
                    # flagged rows (no event, no ack, no double free)
                    # — and, because a launched window's staged bytes
                    # may back a zero-copy in-flight view, retire is
                    # also where their staging slot is freed
                    n_unlaunched += 1
                    self._arena.free(pq.stage_slot[i])
            pq.release(i)
            i = nxt
        arena.pend_head[sess.slot] = -1
        arena.pend_tail[sess.slot] = -1
        sess.n_dropped += n
        self._n_live -= n
        self._n_unlaunched -= n_unlaunched
        if n:
            self.stats.drop(n, "session_removed")
        self.stats.sessions = len(self._sessions)
        self.stats.note_queue_depth(self._n_live)
        # replay re-derives the dropped windows from the same queue
        # state, so the record carries only the eviction itself
        self._jappend({"t": "remove", "sid": session_id})
        # recycle the arena slot (scrubbed at the next alloc).  Safe
        # while flagged windows of this session still ride an in-flight
        # ticket: every retire/shed path skips dropped entries before
        # touching session state, so a recycled slot is never read
        # through a dead session's handle.
        self._sess_by_slot[sess.slot] = None
        self._session_arena.release(sess.slot)

    def disconnect_session(self, session_id: Hashable) -> list[FleetEvent]:
        """Graceful disconnect — the load plane's churn counterpart of
        ``remove_session`` (which is a hard evict that DROPS the queue).

        A real session that hangs up mid-stream still owns data the
        fleet has accepted: queued windows waiting for a batch, and the
        tail samples in its assembler's ring that never reached a hop
        boundary.  The steady-state loadgen never saw either (every
        recording ends exactly on the grid and the final ``flush``
        drains the queue); session churn hits both constantly.  So a
        disconnect (1) flushes the assembler's partial window — one
        final window covering the last ``window`` samples, emitted at
        ``t_index = n_seen`` (off the hop grid by construction, so it
        can never collide with a grid ack) — (2) SETTLES the pending
        queue through a forced poll, so every accepted window scores
        and its ack is durable, and only then (3) journals the
        ``remove`` eviction.  Returns the events the settle produced
        (the drain is fleet-wide: a forced poll retires every queued
        window, not only this session's — all of them are returned).

        Replay order matches: the ``disc`` record re-derives the flush
        window from the recovered ring bit-identically, the acks
        consume it, the ``remove`` record evicts — so a crash anywhere
        inside a disconnect recovers without dropping or double-scoring
        a window (the re-issued disconnect is idempotent: a flushed
        assembler never flushes twice)."""
        return self.disconnect_sessions((session_id,))

    def disconnect_sessions(self, session_ids) -> list[FleetEvent]:
        """Batched graceful disconnect: flush every leaver's partial
        window, settle ONCE, then evict.  A churn round that evicts a
        whole cohort (the overnight storm) pays one forced poll, not
        one per session — and the settle's forced drain is the reason
        the traffic driver applies disconnects AFTER the round's
        regular poll: the capacity controller's backlog signal and the
        micro-batcher's coalescing both survive churn."""
        sessions = []
        for sid in session_ids:
            sess = self._sessions.get(sid)
            if sess is None:
                raise AdmissionError(f"unknown session {sid!r}")
            sessions.append(sess)
        for sess in sessions:
            self._flush_partial(sess)
        events: list[FleetEvent] = []
        if any(sess.n_live for sess in sessions):
            # settle: acks (and any dispatch-failure drop records) are
            # durable before the remove records are even buffered
            events = self.poll(force=True)
        for sess in sessions:
            self.remove_session(sess.sid)
        return events

    def _flush_partial(self, sess: _FleetSession) -> int:
        """Enqueue the disconnecting session's final partial window (the
        last ``window`` samples, ending at the stream position) when one
        exists: the session has seen a full window's worth of samples
        AND some of them arrived after the last emitted hop boundary.
        Advancing ``next_emit`` past the flushed position makes the
        flush idempotent — a crash-resumed disconnect re-issues it as a
        no-op.  Shared verbatim by the live path and the ``disc``
        journal replay, so the recovered window is bit-identical by
        construction."""
        asm = sess.asm
        if (
            asm._n_seen < self.window
            or asm._n_seen <= asm._next_emit - self.hop
        ):
            return 0
        self._jappend({"t": "disc", "sid": sess.sid})
        self._enqueue_pending(
            sess,
            asm._n_seen,
            self._arena.put(asm._ring),
            bool(
                asm.drift_report is not None and asm.drift_report.drifting
            ),
            self._clock(),
        )
        sess.n_live += 1
        sess.n_enqueued += 1
        self._n_live += 1
        self._n_unlaunched += 1
        self.stats.enqueued += 1
        # the session is leaving: future grid boundaries are moot, and
        # parking next_emit one hop past the flush position is what
        # guarantees a second _flush_partial finds nothing to flush
        asm._next_emit = asm._n_seen + self.hop
        # the flush honors the same global bound push enforces: a mass
        # cohort's partials must not balloon the queue past
        # max_queue_windows — overflow sheds stalest fleet-wide (a
        # DECLARED backpressure shed, the documented overload
        # behavior).  The check lives HERE, not in the cohort loop,
        # because this function is shared verbatim with the ``disc``
        # journal replay: the shed re-derives on recovery exactly like
        # push-time sheds do, keeping replay bit-identical to the live
        # run (record=False — never journaled, by the same design)
        overflow = self._n_live - self.config.max_queue_windows
        if overflow > 0:
            self._shed_stalest(overflow, "backpressure")
        self.stats.note_queue_depth(self._n_live)
        return 1

    # ------------------------------------------- cluster hand-off
    # (har_tpu.serve.cluster: live session migration between workers.
    # The protocol is adopt-first: the target journals the session's
    # full exported state durably BEFORE the source evicts it, so a
    # crash anywhere in between leaves the session on at least one
    # journal — dual ownership resolves by the higher `handoffs`
    # generation, never by losing the stream.)

    def export_session(self, session_id: Hashable) -> dict:
        """Serialize one session's complete live state for a hand-off:
        ring buffer, watermark, smoother, drift monitor, per-session
        counters and the hand-off generation.  Refuses while the
        session has live (queued or in-flight) windows — the cluster
        drains first (``flush``); moving an un-scored window between
        journals would fork its ack trail across two recovery logs."""
        sess = self._sessions.get(session_id)
        if sess is None:
            raise AdmissionError(f"unknown session {session_id!r}")
        if sess.n_live:
            raise AdmissionError(
                f"session {session_id!r} has {sess.n_live} live "
                "window(s); drain (flush) before hand-off"
            )
        sm = sess.smoother
        return {
            "sid": session_id,
            "ring": sess.asm._ring.copy(),
            "n_seen": sess.asm._n_seen,
            "raw_seen": sess.raw_seen,
            "next_emit": sess.asm._next_emit,
            "n_enqueued": sess.n_enqueued,
            "n_scored": sess.n_scored,
            "n_dropped": sess.n_dropped,
            "handoffs": sess.handoffs,
            "votes": list(sm._votes),
            # np.array, not asarray: the smoother's EMA is a VIEW into
            # the session arena, and the hand-off recycles this slot —
            # the export must own its bytes (asarray would alias)
            "ema": (
                None if sm._ema is None
                else np.array(sm._ema, np.float64)
            ),
            "monitor": monitor_state(sess.asm.monitor),
        }

    def adopt_session(self, export: dict) -> None:
        """Admit a migrated session WITH its exported live state — the
        receiving half of a cluster hand-off.  The stream continues
        exactly where the source froze it: same ring, same smoother,
        same drift episode, same watermark — so the transport resumes
        delivery at ``watermark(sid)`` and the event stream is
        bit-identical to one that never moved (test-pinned).  Journaled
        as an ``adopt`` record carrying the full state, so THIS
        worker's own crash recovery rebuilds the migrated session.
        Bumps the session's ``handoffs`` generation (dual-ownership
        tie-break) and ``stats.migrations``."""
        sid = export["sid"]
        if sid in self._sessions:
            raise AdmissionError(f"session {sid!r} already admitted")
        if len(self._sessions) >= self.config.max_sessions:
            self.stats.admission_rejections += 1
            raise AdmissionError(
                f"fleet full ({self.config.max_sessions} sessions); "
                "cannot adopt — raise FleetConfig.max_sessions"
            )
        monitor = monitor_from_state(export.get("monitor"))
        sess = self._new_session(sid, monitor)
        ring = np.asarray(export["ring"], np.float32)
        if ring.shape != sess.asm._ring.shape:
            # refused adoption must not leak the freshly claimed slot
            self._sess_by_slot[sess.slot] = None
            self._session_arena.release(sess.slot)
            raise ValueError(
                f"exported ring shape {ring.shape} does not match this "
                f"fleet's geometry {sess.asm._ring.shape} — sessions "
                "migrate only between geometry-identical workers"
            )
        sess.asm._ring[:] = ring
        sess.asm._n_seen = int(export["n_seen"])
        sess.asm._next_emit = int(export["next_emit"])
        sess.raw_seen = int(export["raw_seen"])
        sess.n_enqueued = int(export.get("n_enqueued", 0))
        sess.n_scored = int(export.get("n_scored", 0))
        sess.n_dropped = int(export.get("n_dropped", 0))
        sess.handoffs = int(export.get("handoffs", 0)) + 1
        ema = export.get("ema")
        if ema is not None:
            sess.smoother._ema = np.asarray(ema, np.float64)
        sess.smoother._votes = deque(
            (int(v) for v in export.get("votes") or []),
            maxlen=self.vote_depth,
        )
        self._sessions[sid] = sess
        if monitor is not None:
            self._n_monitors += 1
        self.stats.sessions = len(self._sessions)
        self.stats.migrations += 1
        payload = ring.tobytes()
        if ema is not None:
            payload += np.asarray(ema, np.float64).tobytes()
        self._jappend(
            {
                "t": "adopt",
                "sid": sid,
                "n_seen": sess.asm._n_seen,
                "raw_seen": sess.raw_seen,
                "next_emit": sess.asm._next_emit,
                "n_enqueued": sess.n_enqueued,
                "n_scored": sess.n_scored,
                "n_dropped": sess.n_dropped,
                "handoffs": sess.handoffs,
                "votes": [int(v) for v in sess.smoother._votes],
                "ema": ema is not None,
                "mon": monitor_state(monitor),
            },
            payload,
        )

    def handoff_session(self, session_id: Hashable) -> dict:
        """The source half of a hand-off: export the session's state
        and evict it WITHOUT dropping anything (``export_session``'s
        drain guarantee means there is nothing live to drop — unlike
        ``remove_session`` this is a move, not a tear-down).  Journaled
        as a ``handoff`` record so the source's own recovery re-applies
        the eviction; returns the export for the adopter."""
        export = self.export_session(session_id)
        self._apply_handoff(session_id)
        self._jappend({"t": "handoff", "sid": session_id})
        return export

    def _apply_handoff(self, session_id: Hashable) -> None:
        """Shared by the live hand-off and its journal replay: pop the
        session off the fleet, checking the drain guarantee held."""
        sess = self._sessions.get(session_id)
        if sess is None:
            raise AdmissionError(f"unknown session {session_id!r}")
        if sess.n_live:  # pragma: no cover - export_session guards this
            raise AdmissionError(
                f"hand-off of {session_id!r} with {sess.n_live} live "
                "window(s)"
            )
        del self._sessions[session_id]
        if sess.asm.monitor is not None:
            self._n_monitors -= 1
        self._sess_by_slot[sess.slot] = None
        self._session_arena.release(sess.slot)
        self.stats.sessions = len(self._sessions)

    @property
    def sessions(self) -> tuple:
        return tuple(self._sessions)

    def drift_report(self, session_id: Hashable):
        """The session's latest DriftReport (None without a monitor)."""
        return self._sessions[session_id].asm.drift_report

    def reset_monitors(self) -> None:
        """Re-arm every session's DriftMonitor (post-swap: the replaced
        model's drift episodes must not re-alert against the model that
        was just trained on that drifted data).  Each monitor restarts
        at its reference state and the next episode gets a fresh
        ``DriftReport.onset``."""
        for sess in self._sessions.values():
            if sess.asm.monitor is not None:
                sess.asm.monitor.reset()
                sess.asm.drift_report = None

    # ------------------------------------------------------- ingestion

    def push(self, session_id: Hashable, samples: np.ndarray) -> int:
        """Feed ``(n, channels)`` samples for one session; windows they
        complete are QUEUED (not scored — that's ``poll``).  Returns the
        number of windows enqueued.  Never blocks: queue overflow sheds
        the stalest windows instead (counted in stats.dropped)."""
        sess = self._sessions.get(session_id)
        if sess is None:
            raise AdmissionError(
                f"unknown session {session_id!r}; add_session first"
            )
        now = self._clock()
        # ingest guard (serving.finite_rows — the same guard a
        # standalone StreamingClassifier applies, so equivalence holds
        # on poisoned streams too): one NaN row must never ride a
        # window into a 256-session micro-batch
        if (
            not isinstance(samples, np.ndarray)
            or samples.ndim != 2
            or samples.dtype != np.float32
        ):
            samples = np.atleast_2d(np.asarray(samples, np.float32))
        if samples.shape[-1] != self.channels:
            # validate BEFORE journaling or advancing the watermark: a
            # malformed push must raise to its caller, never write a
            # record replay cannot reshape (which would poison the
            # journal and make the whole fleet unrecoverable)
            raise ValueError(
                f"expected (n, {self.channels}) samples, got "
                f"{samples.shape}"
            )
        raw_len = len(samples)
        sess.raw_seen += raw_len
        samples, n_bad = finite_rows(samples, self.config.max_abs_sample)
        self.stats.rejected_samples += n_bad
        # journal the CLEAN samples before consuming them: replay feeds
        # exactly these rows back through the same assembler, so the
        # recovered ring/monitor state is bit-identical by construction.
        # ``rn`` records the RAW delivered length (rejected rows
        # included) so the recovered watermark stays in transport
        # coordinates.  (Journal presence checked HERE, not only in
        # _jappend: the record dict and the tobytes copy are per-push
        # hot-path allocations a journal-less fleet must not pay.)
        if (
            self._journal is not None
            and not self._replaying
            and (len(samples) or n_bad)
        ):
            try:
                self._journal.append(
                    {
                        "t": "push",
                        "sid": session_id,
                        "n": len(samples),
                        "rn": raw_len,
                    },
                    samples.tobytes(),
                )
            except OSError as exc:
                # flush_every auto-flush hit a storage fault: contained
                # (the record stays buffered; push-loss is bounded by
                # the transport's watermark re-delivery either way)
                self._note_journal_error("push append", exc)
        # the assembler stages every completed window straight into the
        # arena (one copy, contiguous storage; multi-window bursts stage
        # in one vectorized block write) — batch assembly later is a
        # gather, not a stack of scattered per-window arrays
        completed = sess.asm.consume(samples, sink=self._arena)
        n_completed = len(completed)
        for t_index, slot, drift in completed:
            self._enqueue_pending(sess, t_index, slot, drift, now)
            sess.n_live += 1
        if n_completed:
            sess.n_enqueued += n_completed
            self._n_live += n_completed
            self._n_unlaunched += n_completed
            self.stats.enqueued += n_completed
        # bounded per-session queue: this session sheds ITS OWN oldest
        # windows — one stalled consumer must not push the fleet around
        # (in-flight windows are not sheddable; the bound re-applies
        # once their dispatch retires)
        while sess.n_live > self.config.max_pending_per_session:
            if not self._drop_oldest_of(sess, "session_queue"):
                break
        # global backpressure: shed the stalest queued windows fleet-
        # wide (FIFO head = oldest enqueue = stalest session data)
        overflow = self._n_live - self.config.max_queue_windows
        if overflow > 0:
            self._shed_stalest(overflow, "backpressure")
        self.stats.note_queue_depth(self._n_live)
        self._chaos("post_enqueue")
        if self.host_profile is not None:
            self.host_profile.ingest.record((self._clock() - now) * 1e3)
        return len(completed)

    def push_many(self, session_ids, chunks) -> int:
        """Batched ingest for one delivery round: semantically
        ``for sid, c in zip(ids, chunks): push(sid, c)``, but the
        common steady-state shape — clean same-length chunks crossing
        at most ONE emission boundary, wherever in the chunk it falls
        — runs as a handful of vectorized operations over the session
        arena instead of thousands of per-session Python statements:
        ONE ingest-guard reduction over the stacked round, batched
        drift-monitor EWMA steps (``DriftMonitor.update_many``, split
        at the boundary exactly like the sequential consume), ONE
        ring-roll scatter per chunk length, and ONE two-part
        staging-block write per boundary-offset subgroup for the
        completed windows.  Rows that don't fit the shape (multi-window
        catch-up bursts, non-finite samples, non-f32 arrays) fall
        back to ``push`` row by row; wrong-channel chunks raise
        BEFORE any state advances (push's validate-first rule,
        round-wide — a mid-round raise must never strand half an
        ingested round), and
        journaled fleets always take the sequential path (the journal
        record/chaos cadence is per push by contract) — so the batched
        path changes WHERE the work happens, never what any session's
        stream sees.  Per-session state transitions are identical by
        construction (same ring bytes, same boundary arithmetic, same
        monitor recurrence — test-pinned bit-identical at N=64);
        cross-session queue order follows delivery order exactly
        (windows enqueue in the ``session_ids`` order either way).
        Returns the number of windows enqueued."""
        ids = list(session_ids)
        chunks = list(chunks)
        if len(ids) != len(chunks):
            raise ValueError("session_ids and chunks length mismatch")
        if (
            self._journal is not None
            or self._replaying
            or len(set(ids)) != len(ids)
        ):
            return sum(self.push(s, c) for s, c in zip(ids, chunks))
        now = self._clock()
        cfg = self.config
        arena = self._session_arena
        sessions = []
        for sid in ids:
            sess = self._sessions.get(sid)
            if sess is None:
                raise AdmissionError(
                    f"unknown session {sid!r}; add_session first"
                )
            sessions.append(sess)
        # group the fast-eligible rows by chunk length; everything else
        # replays through the sequential push in delivery order.
        # Malformed chunks are validated HERE, before ANY arena
        # mutation: a ValueError mid-round after fast rows had already
        # rolled rings and staged windows would strand the fleet in a
        # state no sequence of pushes can produce (push's own
        # "validate BEFORE advancing" rule, applied round-wide).
        groups: dict[int, list[int]] = {}
        slow = set()
        for j, c in enumerate(chunks):
            if (
                isinstance(c, np.ndarray)
                and c.ndim == 2
                and c.dtype == np.float32
            ):
                if c.shape[1] != self.channels:
                    raise ValueError(
                        f"expected (n, {self.channels}) samples, got "
                        f"{c.shape}"
                    )
                if len(c):
                    groups.setdefault(len(c), []).append(j)
                else:
                    slow.add(j)
            else:
                c = np.atleast_2d(np.asarray(c, np.float32))
                if c.shape[-1] != self.channels:
                    raise ValueError(
                        f"expected (n, {self.channels}) samples, got "
                        f"{c.shape}"
                    )
                chunks[j] = c  # normalized once; push re-checks cheaply
                slow.add(j)
        # per-subgroup column accumulators: row index (delivery order),
        # arena slot, staging token, t_index, post-increment n_live and
        # drift flag for every emitted window — concatenated and
        # delivery-order-sorted into ONE block enqueue when no slow row
        # interleaves (the dominant round shape), exploded into the
        # per-row interleave loop otherwise
        fast_rows: list[np.ndarray] = []
        fast_slots: list[np.ndarray] = []
        fast_toks: list[np.ndarray] = []
        fast_tidx: list[np.ndarray] = []
        fast_nl: list[np.ndarray] = []
        fast_flags: list[np.ndarray] = []
        fleet_monitored = self._n_monitors > 0
        max_abs = cfg.max_abs_sample
        for n, rows in groups.items():
            block = np.stack([chunks[j] for j in rows])
            # the whole group's ingest guard, fastest case first: ONE
            # scalar reduction clears the all-clean round (finite_rows'
            # chunk-level stance, applied to the whole fleet's round);
            # only a misbehaving round pays the per-row maxima, and a
            # row whose abs-max misbehaves (NaN/Inf compare False)
            # re-runs through push, which applies the per-row guard
            ab = np.abs(block)  # one pass; reused by the dirty branch
            group_max = float(ab.max())
            if (
                group_max <= max_abs
                if max_abs is not None
                else np.isfinite(group_max)
            ):
                clean = None  # every row clean
            else:
                rowmax = ab.max(axis=(1, 2))
                clean = (
                    rowmax <= max_abs
                    if max_abs is not None
                    else np.isfinite(rowmax)
                )
            del ab
            slots = np.fromiter(
                (sessions[j].slot for j in rows), np.intp, len(rows)
            )
            # boundary arithmetic, vectorized: gap = samples until the
            # next emission boundary.  Fast rows cross at most ONE
            # boundary inside the chunk (``gap > n - hop`` — the
            # following boundary lands past the end), wherever in the
            # chunk it falls: real transports deliver at arbitrary
            # phase, so the mid-chunk completion is the steady state,
            # not the exception.  Multi-window chunks (catch-up
            # bursts) replay through the sequential split loop.
            gap = arena.next_emit[slots] - arena.n_seen[slots]
            fast = (
                gap > n - self.hop
                if clean is None
                else clean & (gap > n - self.hop)
            )
            if not fast.all():
                for j in np.asarray(rows)[~fast]:
                    slow.add(int(j))
                rows = [j for j, f in zip(rows, fast) if f]
                if not rows:
                    continue
                block = block[fast]
                slots = slots[fast]
                gap = gap[fast]
            rows_arr = np.asarray(rows)
            w = self.window
            em_idx = np.flatnonzero(gap <= n)
            no_em = (
                rows
                if not len(em_idx)
                else rows_arr[gap > n].tolist()
            )
            # batched drift observers for rows that complete nothing:
            # one whole-chunk EWMA step, exactly the chunk the
            # sequential consume would have fed (emitting rows split
            # their update at the boundary — handled per subgroup
            # below, same cadence as the sequential path).  The whole
            # monitor plumbing is skipped when NO admitted session
            # carries a monitor (the engine counts them at admission)
            # — the per-row monitor-list builds are pure waste then.
            if fleet_monitored and no_em:
                monitors = [sessions[j].asm.monitor for j in no_em]
                if any(mon is not None for mon in monitors):
                    reports = DriftMonitor.update_many(
                        monitors, block if not len(em_idx) else
                        block[gap > n]
                    )
                    for j, rep in zip(no_em, reports):
                        if rep is not None:
                            sessions[j].asm.drift_report = rep
            # emitting rows, subgrouped by the boundary offset k: every
            # subgroup's window snapshots build in ONE two-part staging
            # write — ``ring[k:] ++ chunk[:k]``, the last `window`
            # samples at the boundary, identical bytes to the
            # sequential ring roll's snapshot by construction
            if len(em_idx):
                # reserve the group's staging slots up front, assigned
                # in DELIVERY order (ascending row index — the order
                # the windows will enqueue and later launch), so the
                # batch-assembly gather stays one contiguous run and
                # zero-copy even across boundary-offset subgroups
                blk = self._arena.reserve(len(em_idx))
                slots_by_em = np.empty(len(em_idx), np.int64)
                slots_by_em[np.argsort(rows_arr[em_idx])] = blk
                em_pos = np.empty(len(rows_arr), np.int64)
                em_pos[em_idx] = np.arange(len(em_idx))
                ks = gap[em_idx]
                order = np.argsort(ks, kind="stable")
                em_sorted = em_idx[order]
                ks_sorted = ks[order]
                uniq, starts = np.unique(ks_sorted, return_index=True)
                bounds = list(starts) + [len(em_sorted)]
                for u, (a, b) in zip(uniq, zip(bounds, bounds[1:])):
                    k = int(u)
                    sub = em_sorted[a:b]
                    sub_slots = slots[sub]
                    monitored = False
                    if fleet_monitored:
                        sub_rows = rows_arr[sub].tolist()
                        sub_mons = [
                            sessions[j].asm.monitor for j in sub_rows
                        ]
                        monitored = any(
                            mon is not None for mon in sub_mons
                        )
                    if monitored:
                        # first sub-chunk, up to the boundary — the
                        # report the emitted window's drift flag reads
                        reports = DriftMonitor.update_many(
                            sub_mons, block[sub, :k]
                        )
                        for j, rep in zip(sub_rows, reports):
                            if rep is not None:
                                sessions[j].asm.drift_report = rep
                        # capture the emitted windows' drift flags NOW
                        # — exactly the sequential cadence, where the
                        # emit happens between the head and tail
                        # monitor updates; reading after the tail
                        # update would hand the window the NEXT
                        # sub-chunk's verdict
                        sub_flags = np.fromiter(
                            (
                                sessions[j].asm.drift_report is not None
                                and bool(
                                    sessions[j].asm.drift_report.drifting
                                )
                                for j in sub_rows
                            ),
                            bool,
                            len(sub_rows),
                        )
                    else:
                        # no monitor in the subgroup: only monitors
                        # ever set a drift report, so every flag is
                        # structurally False
                        sub_flags = np.zeros(len(sub), bool)
                    toks = self._arena.put_block_pair(
                        arena.rings[sub_slots, k:], block[sub, :k],
                        slots=slots_by_em[em_pos[sub]],
                    )
                    t_idx_arr = arena.next_emit[sub_slots].copy()
                    arena.next_emit[sub_slots] += self.hop
                    arena.n_enqueued[sub_slots] += 1
                    arena.n_live[sub_slots] += 1
                    n_lives_arr = arena.n_live[sub_slots]
                    if monitored and k < n:
                        # the tail past the boundary, after the flags
                        reports = DriftMonitor.update_many(
                            sub_mons, block[sub, k:]
                        )
                        for j, rep in zip(sub_rows, reports):
                            if rep is not None:
                                sessions[j].asm.drift_report = rep
                    fast_rows.append(rows_arr[sub])
                    fast_slots.append(sub_slots)
                    fast_toks.append(np.asarray(toks))
                    fast_tidx.append(t_idx_arr)
                    fast_nl.append(n_lives_arr)
                    fast_flags.append(sub_flags)
            # ring roll for the whole group in two scatters (one when
            # the chunk covers the window) — AFTER the snapshots above,
            # which read the pre-roll ring tail
            self._roll_rings(arena, slots, block, n, w)
        if not slow:
            # the whole round was fast (the dominant shape): ONE block
            # enqueue in delivery order — concatenate the subgroup
            # columns and sort by row index, which IS delivery order
            if not fast_rows:
                self.stats.note_queue_depth(self._n_live)
                if self.host_profile is not None:
                    self.host_profile.ingest.record(
                        (self._clock() - now) * 1e3
                    )
                return 0
            if len(fast_rows) == 1:
                rows_cat = fast_rows[0]
                parts = (
                    fast_slots[0], fast_toks[0], fast_tidx[0],
                    fast_nl[0], fast_flags[0],
                )
            else:
                rows_cat = np.concatenate(fast_rows)
                parts = tuple(
                    np.concatenate(p)
                    for p in (
                        fast_slots, fast_toks, fast_tidx, fast_nl,
                        fast_flags,
                    )
                )
            order = np.argsort(rows_cat, kind="stable")
            return self._finish_fast_round(
                sessions, rows_cat[order].tolist(),
                parts[0][order], parts[1][order], parts[2][order],
                parts[3][order], parts[4][order], now,
            )
        # slow-interleaved finish: enqueue in DELIVERY order (slow rows
        # run their whole push here, so the global FIFO interleaves
        # exactly as sequential pushes would), with the sequential
        # path's own per-row global counters and backpressure check —
        # a slow push mid-loop must observe the true queue depth.
        # Per-session n_live was batch-incremented above; the bound
        # check reads the pre-gathered value, so only the rare
        # over-bound session touches the arena again.
        emitted: dict[int, tuple] = {}
        for g in range(len(fast_rows)):
            for j, slot, tok, ti, nl, flag in zip(
                fast_rows[g].tolist(), fast_slots[g].tolist(),
                fast_toks[g].tolist(), fast_tidx[g].tolist(),
                fast_nl[g].tolist(), fast_flags[g].tolist(),
            ):
                emitted[j] = (ti, tok, nl, flag)
        enqueued = 0
        max_pending = cfg.max_pending_per_session
        for j, sid in enumerate(ids):
            if j in slow:
                # per-row global counters above keep this push's own
                # queue-depth gauge samples and backpressure check
                # honest about the fast windows already appended
                enqueued += self.push(sid, chunks[j])  # counts its own
                continue
            em = emitted.get(j)
            if em is None:
                continue
            ti, tok, nl, drift = em
            sess = sessions[j]
            self._enqueue_pending(sess, ti, tok, drift, now)
            enqueued += 1
            self._n_live += 1
            self._n_unlaunched += 1
            self.stats.enqueued += 1
            if nl > max_pending:
                while sess.n_live > max_pending:
                    if not self._drop_oldest_of(sess, "session_queue"):
                        break
            overflow = self._n_live - cfg.max_queue_windows
            if overflow > 0:
                self._shed_stalest(overflow, "backpressure")
        self.stats.note_queue_depth(self._n_live)
        if self.host_profile is not None:
            self.host_profile.ingest.record((self._clock() - now) * 1e3)
        return enqueued

    @staticmethod
    def _roll_rings(arena, slots, block, n: int, w: int) -> None:
        """Group-level ring roll + head/watermark advance: two scatters
        (one when the chunk covers the whole window) absorb the round's
        chunks into every ring at once — the final ring is the last
        ``w`` stream rows, exactly the sequential roll's result.  When
        the group's arena slots form one ascending run (admission
        order — the whole-fleet round), the scatters degenerate to
        basic-slice writes (numpy buffers the overlapping shift).
        Run detection is the staging arena's own predicate — one
        eligibility rule for every contiguous fast path."""
        k = len(slots)
        s0 = StagingArena._run_start(slots)
        if s0 is not None:
            rows = arena.rings[s0: s0 + k]
            if n >= w:
                rows[:] = block[:, -w:]
            else:
                rows[:, : w - n] = rows[:, n:]
                rows[:, w - n:] = block
            arena.n_seen[s0: s0 + k] += n
            arena.raw_seen[s0: s0 + k] += n
            return
        if n >= w:
            arena.rings[slots] = block[:, -w:]
        else:
            arena.rings[slots, : w - n] = arena.rings[slots, n:]
            arena.rings[slots, w - n:] = block
        arena.n_seen[slots] += n
        arena.raw_seen[slots] += n

    def _finish_fast_round(
        self, sessions, em_rows, sess_slots, toks, t_idx, n_lives,
        drifts, now
    ) -> int:
        """Enqueue a fully-fast delivery round (the steady state at
        fleet scale, boundary offsets mixed or not): ONE vectorized
        block enqueue in delivery order — claim a block of pending
        slots, fill their columns, extend the FIFO ring, tail-link
        every session's list in three scatters — with bounds identical
        to ``push``'s (only the rare over-bound session walks its
        list).  The global counters and backpressure shed are applied
        ONCE after the block: with no slow push interleaved there is
        no mid-round observer, and shedding the total overflow
        stalest-first lands the exact end state per-row incremental
        sheds produce (same count, same FIFO head)."""
        cfg = self.config
        max_pending = cfg.max_pending_per_session
        idx = self._pending.add_block(
            sess_slots, t_idx, toks, drifts, now
        )
        self._link_pending_block(sess_slots, idx)
        over = np.flatnonzero(n_lives > max_pending)
        for j in over.tolist():
            sess = sessions[em_rows[j]]
            while sess.n_live > max_pending:
                if not self._drop_oldest_of(sess, "session_queue"):
                    break
        n_emitted = len(em_rows)
        self._n_live += n_emitted
        self._n_unlaunched += n_emitted
        self.stats.enqueued += n_emitted
        overflow = self._n_live - cfg.max_queue_windows
        if overflow > 0:
            self._shed_stalest(overflow, "backpressure")
        self.stats.note_queue_depth(self._n_live)
        if self.host_profile is not None:
            self.host_profile.ingest.record((self._clock() - now) * 1e3)
        return n_emitted

    def _drop_oldest_of(self, sess: _FleetSession, reason: str) -> bool:
        # walk, don't pop: entries must keep their position for the
        # retire-time FIFO unlink; windows already launched on-device
        # are skipped (shedding them saves nothing — their dispatch is
        # in flight — so the session's oldest UN-launched window goes)
        pq = self._pending
        i = self._session_arena.pend_head[sess.slot]
        while i >= 0:
            if not pq.dropped[i] and not pq.launched[i]:
                pq.dropped[i] = True
                self._arena.free(pq.stage_slot[i])
                sess.n_live -= 1
                sess.n_dropped += 1
                self._n_live -= 1
                self._n_unlaunched -= 1
                self.stats.drop(1, reason)
                return True
            i = pq.next_idx[i]
        return False

    def _shed_stalest(self, n: int, reason: str, record: bool = False) -> int:
        """Drop up to n live windows from the global FIFO head (the
        stalest enqueued data) — one vectorized sweep over the index
        ring.  The queue entries are left in place with their flags
        set; scoring and session lists skip flagged entries.
        ``record`` journals each drop — needed for dispatch-time sheds
        (slo_shed), whose trigger (wall-clock SLO breaches) a journal
        replay cannot re-derive; push-time sheds are deterministic in
        the record stream and re-derive instead."""
        pq = self._pending
        # early-stopping head walk: shedding k windows off a deep queue
        # is O(k + dropped prefix), never O(queue) — the sequential
        # push path sheds per overflowing window
        chosen = pq.head_live(n)
        shed = len(chosen)
        if not shed:
            return 0
        if record:
            for i in chosen.tolist():
                self._jappend(
                    {
                        "t": "drop",
                        "sid": self._sess_by_slot[
                            pq.sess_slot[i]
                        ].sid,
                        "ti": int(pq.t_index[i]),
                        "reason": reason,
                    }
                )
        pq.dropped[chosen] = True
        self._arena.free_block(pq.stage_slot[chosen])
        arena = self._session_arena
        slots = pq.sess_slot[chosen]
        np.add.at(arena.n_live, slots, -1)
        np.add.at(arena.n_dropped, slots, 1)
        self._n_live -= shed
        self._n_unlaunched -= shed
        self.stats.drop(shed, reason)
        return shed

    # ------------------------------------------------------ scheduling

    def due(self, now: float | None = None) -> bool:
        """Would poll() dispatch right now?  True when a full batch is
        queued or the oldest queued window has passed its deadline.
        Reasoned over the UN-LAUNCHED queue: windows already in flight
        on-device (pipeline_depth > 1) no longer wait for a batch."""
        if self._n_unlaunched >= self.config.target_batch:
            return True
        oldest = self._pending.oldest_live_enqueue()
        if oldest is None:
            return False
        now = self._clock() if now is None else now
        return (now - oldest) * 1e3 >= self.config.max_delay_ms

    def poll(self, *, force: bool = False) -> list[FleetEvent]:
        """Dispatch every due batch; return the events they produced.

        ``force=True`` dispatches regardless of deadlines (drain).  A
        dispatch that fails after retries drops its own windows and
        keeps the engine serving — the error is counted, not raised.

        Pipelined dispatch (``FleetConfig.pipeline_depth``): up to
        ``depth`` launched tickets ride in flight on-device while the
        host assembles the next batch, and up to ``depth - 1`` of them
        survive BETWEEN polls — the device scores a batch while the
        host ingests the next delivery round, the overlap a depth-1
        engine structurally cannot have.  Retire order is strictly
        FIFO, so events, smoothing steps and journal acks happen in the
        exact order the synchronous (depth-1) engine produces them (a
        carried ticket's events are simply returned by the poll that
        retires it).  The ack flush below covers every event this call
        hands to the consumer; a ticket still in flight at a crash is
        un-acked by construction and its windows recover as pending
        (see docs/serving.md's ticket lifecycle).

        Garbage collection is suspended for the duration of the poll
        (restored on exit, even on error): a cyclic-GC pass landing
        mid-dispatch would (1) bill its pause to ``dispatch_ms`` and
        can breach the SLO ladder spuriously, and (2) repeatedly
        re-scan the growing event batch while it is still being built,
        promoting every event into the old generation and triggering
        full collections that re-walk the whole (static) session
        estate every poll — measured at ~57 ms/poll of pure GC at 20k
        sessions.  Deferring collection to the caller's side of the
        boundary lets short-lived events die young; callers that
        retain events simply pay the (identical) promotion cost in
        their own time, outside the latency-sensitive dispatch loop.
        """
        gc_was_enabled = gc.isenabled()
        if gc_was_enabled:
            gc.disable()
        try:
            return self._poll_inner(force)
        finally:
            if gc_was_enabled:
                gc.enable()

    def _poll_inner(self, force: bool) -> list[FleetEvent]:
        if (
            self._journal is not None
            and not self._replaying
            and self._journal.snapshot_due()
        ):
            # snapshot at the START of a poll, BEFORE carried tickets
            # retire: no not-yet-returned acks are in the buffer, and
            # in-flight windows are serialized as ordinary pending —
            # a kill inside the snapshot can only lose re-scorable
            # pending windows, never an acked-but-undelivered event
            self.write_snapshot()
        self._chaos("pre_dispatch")
        events: list[FleetEvent] = []
        inflight = self._inflight
        # pending-queue depth per poll (HostProfile): the un-launched
        # backlog the due-selection reasons over, sampled at poll entry
        # and again before every launch this poll performs — what makes
        # due-selection cost attributable in --profile-host output
        depths = (
            [float(self._n_unlaunched)]
            if self.host_profile is not None
            else None
        )
        # tickets carried from the previous poll crunched on-device
        # through the delivery phase; their results are due now.  The
        # inter-poll span is one shared wall-clock interval: credit it
        # to overlap_pct ONCE (not per ticket), and stamp it on every
        # carried ticket as deliberate idle so the SLO ladder never
        # reads the pipeline's own buffering as a slow tunnel.
        if inflight:
            now0 = self._clock()
            credited = False
            for t in inflight:
                if t.t_carried0 is not None:
                    span = (now0 - t.t_carried0) * 1e3
                    t.idle_ms += span
                    if not credited:
                        self.stats.overlap_host_ms += span
                        credited = True
        while inflight:
            events.extend(self._retire_ticket(inflight.popleft()))
        while self._n_unlaunched and (force or self.due()):
            # depth read live: an elastic resize applied at a launch
            # boundary inside this poll re-bounds the pipe immediately
            while len(inflight) >= self.config.pipeline_depth:
                events.extend(self._retire_ticket(inflight.popleft()))
            if depths is not None:
                depths.append(float(self._n_unlaunched))
            t_h0 = self._clock()
            ticket = self._launch_batch()
            if ticket is None:
                break
            if inflight:
                # host assembly that ran UNDER an in-flight device batch
                # — the intra-poll half of overlap_pct
                self.stats.overlap_host_ms += (
                    self._clock() - t_h0
                ) * 1e3
            ticket.t_inflight0 = self._clock()
            inflight.append(ticket)
            self.stats.note_inflight_depth(len(inflight))
        # drain down to the carry allowance: nothing on a forced drain
        # (flush/shutdown), up to depth-1 tickets otherwise
        keep = 0 if force else self.config.pipeline_depth - 1
        while len(inflight) > keep:
            events.extend(self._retire_ticket(inflight.popleft()))
        now = self._clock()
        for t in inflight:
            t.t_carried0 = now
        if self._staged_swap is not None:
            # a completed dispatch IS a boundary: a swap staged from a
            # dispatch tap applies as soon as its batch has finished
            self._apply_swap()
        if self._staged_resize is not None:
            self._apply_resize()  # same boundary rule as the swap
        if depths is not None:
            self.host_profile.pending_depth.record_many(
                np.asarray(depths, np.float64)
            )
        self.stats.note_queue_depth(self._n_live)
        if self._journal is not None and not self._replaying:
            # THE ack boundary: every event about to be returned has its
            # ack durable first, so a consumer can never see an event
            # that recovery would emit again (zero double-scored).  A
            # storage failure here (fsync error, ENOSPC) is contained —
            # counted + warned, the records stay buffered for the next
            # flush, events still deliver — instead of an uncaught
            # exception killing the serving loop; the declared cost is
            # the re-emission window the warning names.
            prof = self.host_profile
            t_j0 = self._clock() if prof is not None else 0.0
            self._contained_flush("ack flush")
            if prof is not None:
                prof.journal.record((self._clock() - t_j0) * 1e3)
        return events

    def flush(self) -> list[FleetEvent]:
        """Drain the queue completely (end of stream / shutdown)."""
        return self.poll(force=True)

    # ------------------------------------------------------ dispatch

    def swap_model(self, model, *, version: str | None = None) -> str:
        """Stage a zero-drop hot-swap of the serving model.

        The swap applies at the next dispatch BOUNDARY: queued windows
        are never dropped, and a batch that has started scoring always
        completes on the model that started it (calling this from a
        dispatch tap defers to the end of that dispatch; calling it
        between polls applies immediately — the engine is idle then).
        Device calibration is cleared with the old model: its padded-
        batch programs are not the new model's.  Returns the version
        label the swap was recorded under (``stats.model_swaps``,
        ``scored_by_version``).
        """
        if version is None:
            version = f"swap{self.stats.model_swaps + 1}"
        self._staged_swap = (model, str(version))
        if not self._in_dispatch:
            self._apply_swap()
        return str(version)

    def _apply_swap(self) -> None:
        model, version = self._staged_swap
        self._staged_swap = None
        self.model = model
        self.model_version = version
        self._device_ms.clear()
        # the scorer wraps the OLD model's jitted predict (in-flight
        # tickets keep their own reference and complete on it); the new
        # model gets a fresh scorer at its first launch
        self._scorer = None
        self.stats.model_swaps += 1
        # journaled swap boundary: the record is appended, the chaos
        # hook may kill here (record buffered, NOT durable — recovery
        # then serves the pre-swap version and the controller re-issues
        # the swap), then the flush makes it durable
        self._jappend({"t": "swap", "ver": version})
        self._chaos("mid_swap")
        if self._journal is not None and not self._replaying:
            self._contained_flush("swap flush")

    def resize(
        self,
        *,
        target_batch: int | None = None,
        pipeline_depth: int | None = None,
        mesh=_MESH_UNSET,
    ) -> dict:
        """Stage an online capacity resize; returns the normalized
        request.  ``target_batch`` and ``pipeline_depth`` replace the
        corresponding ``FleetConfig`` knobs; ``mesh`` re-shards the
        scorer (None = back to single-device; omitted = unchanged).

        Same boundary discipline as ``swap_model``: the resize applies
        at the next dispatch BOUNDARY (a call from a dispatch tap
        defers to the end of that dispatch; a call between polls
        applies immediately — the engine is idle then), queued windows
        are never dropped, and in-flight tickets retire on the OLD
        scorer/placement — each ticket carries its own scorer, so a
        mesh resize can never re-tile a batch that already launched.
        The pad policy follows the new scorer (pow2 single-device,
        devices × pow2 sharded), keeping the log2 program budget.

        Journaled as a ``resize`` record (target_batch /
        pipeline_depth / device count / capacity direction) so a
        journal-suffix replay recovers the post-resize schedule; the
        mesh OBJECT itself is a runtime resource and is never journaled
        — recovery re-shards onto whatever mesh ``restore`` was given,
        the same stance the restore path takes for the model.

        Staged resizes COMPOSE: a second call before the boundary
        reads its unspecified knobs from the already-staged request,
        so ``resize(target_batch=32)`` then ``resize(pipeline_depth=2)``
        from the same dispatch tap lands as one 32/2 resize — never a
        silent revert of the first."""
        cfg = self.config
        staged = self._staged_resize
        base_tb = staged["target_batch"] if staged else cfg.target_batch
        base_depth = (
            staged["pipeline_depth"] if staged else cfg.pipeline_depth
        )
        base_mesh = staged["mesh"] if staged else self._mesh
        tb = base_tb if target_batch is None else int(target_batch)
        depth = base_depth if pipeline_depth is None else int(pipeline_depth)
        if tb <= 0:
            raise ValueError("target_batch must be positive")
        if depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        new_mesh = base_mesh if mesh is _MESH_UNSET else mesh
        mesh_changed = new_mesh is not self._mesh
        old_devices = _mesh_devices(self._mesh)
        new_devices = _mesh_devices(new_mesh)
        # capacity direction judged against the APPLIED config: the
        # composed request resolves at one boundary as one resize
        old_cap = cfg.target_batch * cfg.pipeline_depth * old_devices
        new_cap = tb * depth * new_devices
        req = {
            "target_batch": tb,
            "pipeline_depth": depth,
            "mesh": new_mesh,
            "mesh_changed": mesh_changed,
            "devices": new_devices,
            "dir": (new_cap > old_cap) - (new_cap < old_cap),
        }
        self._staged_resize = req
        if not self._in_dispatch:
            self._apply_resize()
        return {k: req[k] for k in ("target_batch", "pipeline_depth",
                                    "devices", "dir")}

    def _apply_resize(self) -> None:
        req = self._staged_resize
        self._staged_resize = None
        self.config = dataclasses.replace(
            self.config,
            target_batch=req["target_batch"],
            pipeline_depth=req["pipeline_depth"],
        )
        if req["mesh_changed"]:
            # re-shard: the next launch builds a scorer over the new
            # mesh; tickets already in flight keep their old scorer and
            # retire on the old placement.  Device calibration belongs
            # to the old placement's programs — cleared with it.
            self._mesh = req["mesh"]
            self._scorer = None
            self._device_ms.clear()
        self.stats.resizes += 1
        if req["dir"] > 0:
            self.stats.scale_ups += 1
        elif req["dir"] < 0:
            self.stats.scale_downs += 1
        # journaled resize boundary, mirroring the swap: record
        # appended, the chaos hook may kill here (record buffered, NOT
        # durable — recovery then serves the pre-resize capacity and
        # the controller re-issues), then the flush makes it durable
        self._jappend(
            {
                "t": "resize",
                "tb": req["target_batch"],
                "depth": req["pipeline_depth"],
                "devices": req["devices"],
                "dir": req["dir"],
            }
        )
        self._chaos("mid_resize")
        if self._journal is not None and not self._replaying:
            self._contained_flush("resize flush")

    def set_dispatch_tap(self, tap: Callable | None) -> None:
        """Install (or clear, with None) the mirrored-dispatch consumer.

        ``tap(session_ids, windows, probs) -> bool`` receives every
        dispatched batch's unpadded windows and incumbent probabilities
        AFTER the batch's events are finalized — per-event latencies
        never include it.  A True return means the tap actually scored
        the mirror (shadow accounting + stage timing recorded); False
        means it sampled past the batch.  A raising tap is counted
        (``shadow_errors``) and never interrupts serving.
        """
        self._dispatch_tap = tap

    def _get_scorer(self):
        if self._scorer is None:
            self._scorer = make_scorer(
                self.model, self._mesh,
                window=self.window, channels=self.channels,
            )
        return self._scorer

    def _fused_active(self, scorer) -> bool:
        """Is the fused hot loop in effect for the next dispatch?
        Requires the opt-in knob, a device-backed scorer that can build
        the fused program, a fused-ELIGIBLE smoothing mode (vote/none —
        EMA needs the full probability vector the fused retire never
        fetches), and a model that declares its class count (the
        compact decision distribution needs the width)."""
        return bool(
            self.config.fused
            and self.smoothing != "ema"
            and getattr(scorer, "supports_fused", False)
            and getattr(scorer.model, "num_classes", None)
        )

    def _acquire_slab(self, pad_k: int) -> np.ndarray:
        pool = self._slab_pool.get(pad_k)
        if pool:
            return pool.pop()
        return np.empty(
            (pad_k, self.window, self.channels), np.float32
        )

    def _recycle_slab(self, ticket: DispatchTicket) -> None:
        """Return a fused ticket's staging slab to the pool — called
        once per retired ticket, AFTER the dispatch tap has run (tap
        consumers receive views of the slab; anything holding windows
        past the tap must copy, which ReplayBuffer does).

        Retire-order recycling is also a CORRECTNESS constraint, not
        just bookkeeping: on the CPU backend ``jax.device_put`` ALIASES
        a contiguous f32 numpy buffer (zero-copy), so the in-flight
        device array and the slab share memory — the slab may only be
        rewritten once its ticket's fetch has blocked on the result,
        which is exactly what retire guarantees."""
        if ticket.slab is not None:
            self._slab_pool.setdefault(ticket.pad_k, []).append(
                ticket.slab
            )
            ticket.slab = None

    @property
    def scorer(self):
        """The active dispatch backend (HostScorer / DeviceScorer /
        ShardedScorer — har_tpu.serve.dispatch); rebuilt on model swap."""
        return self._get_scorer()

    def _launch_batch(self) -> DispatchTicket | None:
        """LAUNCH half of a dispatch: pop the next FIFO batch, gather
        its windows out of the staging arena, and start it on-device
        (device_put + jitted predict, un-fetched).  Returns the ticket
        the retire half later blocks on — or None when nothing is live."""
        if self._staged_resize is not None:
            self._apply_resize()  # the dispatch boundary (capacity)
        cfg = self.config
        if self._staged_swap is not None:
            self._apply_swap()  # the dispatch boundary (model)
        prof = self.host_profile
        t_prof0 = self._clock() if prof is not None else 0.0
        pq = self._pending
        # one vectorized FIFO pop: up to target_batch live entries off
        # the index ring, launched flags set in a scatter, dropped
        # entries skipped (their queue-side reference released) — the
        # per-object pop loop as array ops
        batch = pq.pop_batch(cfg.target_batch)
        k = len(batch)
        if not k:
            return None
        self._n_unlaunched -= k
        # live fill gauge: how full this dispatch ran relative to the
        # configured capacity — the capacity controller's scale-down
        # evidence (har_tpu.serve.traffic.autoscale)
        self.stats.utilization = k / cfg.target_batch
        self._chaos("mid_dispatch")
        t_assembled = self._clock()
        if prof is not None:
            prof.due_select.record((t_assembled - t_prof0) * 1e3)
        # one vectorized histogram record for the whole batch's queue
        # wait (one column gather instead of a per-window fromiter)
        self.stats.queue_wait.record_many(
            (t_assembled - pq.t_enqueue[batch]) * 1e3
        )
        scorer = self._get_scorer()
        # batch assembly: the staged windows come straight out of the
        # contiguous arena, and the pad policy is the scorer's: pow2
        # single-device, devices × pow2 sharded — either way a
        # log2-bounded program ladder.  Staging recycles slots FIFO,
        # so in steady state the batch's slots are one ascending run
        # and assembly is ZERO-copy: the fused hot loop hands the
        # device the staging slice itself on an exact pad fit (no slab
        # fill, no np.take; safe because launched windows' slots are
        # only freed at retire, after the fetch), and the unfused path
        # gets a slice view from gather whose exact-fit pad passes it
        # through unchanged.  Fragmented rounds (drops/churn punched
        # holes in the recycle order) fall back to the pooled-slab /
        # fancy-index copy paths — test-pinned both ways.
        fused = self._fused_active(scorer)
        slab = None
        stage_slots = pq.stage_slot[batch]
        if fused:
            windows = None
            if scorer.pad_size(k) == k:
                windows = self._arena.gather_view(stage_slots)
            if windows is None:
                slab = self._acquire_slab(scorer.pad_size(k))
                windows = self._arena.gather_into(stage_slots, slab)
        else:
            windows = scorer.pad(self._arena.gather(stage_slots))
        if prof is not None:
            prof.gather.record((self._clock() - t_assembled) * 1e3)
        ticket = DispatchTicket(
            batch, windows, scorer, self.model_version, self._clock(),
            fused=fused, slab=slab,
        )
        if self._dispatch_tap is not None:
            # the tap hands session ids for every batch row, dropped
            # ones included — captured at launch, while every row's
            # session is still admitted (a remove_session mid-flight
            # recycles the slot, so retire could no longer resolve it)
            by_slot = self._sess_by_slot
            ticket.sids = [
                by_slot[s].sid for s in pq.sess_slot[batch].tolist()
            ]
        for label in scorer.device_labels:
            self.stats.note_device_windows(
                label, ticket.pad_k // scorer.devices
            )
        # launch attempts (fault hook + async dispatch), paced by the
        # shared retry loop (har_tpu.utils.backoff.retry_call) with
        # sleep=None: the dispatch hot path NEVER blocks on a backoff
        # delay — the schedule advances for accounting only
        def _attempt_launch():
            if self._fault_hook is not None:
                self._fault_hook(ticket.windows)
            if ticket.fused:
                return scorer.launch_fused(ticket.windows)
            return scorer.launch(ticket.windows)

        def _note_retry(attempt, exc):
            ticket.last_error = exc
            ticket.attempts += 1
            self.stats.dispatch_retries += 1

        try:
            ticket.handle = retry_call(
                _attempt_launch,
                retries=cfg.retries,
                backoff=self._retry_backoff,
                on_retry=_note_retry,
            )
        except Exception as exc:
            ticket.last_error = exc
            ticket.attempts += 1
            ticket.failed = True
        self._chaos("mid_launch")
        return ticket

    def _retire_ticket(self, ticket: DispatchTicket) -> list[FleetEvent]:
        """RETIRE half: block on the ticket's device result, then run
        everything that must happen in FIFO order — SLO ladder, event
        smoothing, acks, the dispatch tap.  Strict FIFO retire is what
        keeps pipelined event streams bit-identical to the synchronous
        engine's, and the ack here is the SAME ack boundary: a ticket
        that never reaches retire (crash mid-flight) is un-acked by
        construction and its windows recover as pending."""
        cfg = self.config
        batch, k = ticket.batch, ticket.k
        prof = self.host_profile
        t_retire0 = self._clock() if prof is not None else 0.0
        self._chaos("pre_retire")

        def _fetch(handle):
            """One retire fetch, tier-blind: the fused path retrieves
            the small (labels, top_probs) pair and rebuilds the compact
            decision distribution on host; the unfused path fetches the
            full probabilities.  Everything downstream — smoothing,
            events, acks, the tap — consumes the same (k, C) shape."""
            if ticket.fused:
                labels, top = ticket.scorer.fetch_fused(handle, k)
                return compact_probs(
                    labels, top, int(ticket.scorer.model.num_classes)
                )
            return ticket.scorer.fetch(handle, k)

        probs = None
        if not ticket.failed:
            try:
                probs = _fetch(ticket.handle)
            except Exception as exc:
                ticket.last_error = exc
                ticket.attempts += 1
        # fetch-time failures (async dispatch surfaces errors at the
        # blocking read) re-run the whole attempt synchronously with
        # whatever retry budget the launch left unused — the same
        # shared retry loop as the launch side, sleep=None (hot path)
        if probs is None and ticket.attempts <= cfg.retries:

            def _attempt_sync():
                self.stats.dispatch_retries += 1
                if self._fault_hook is not None:
                    self._fault_hook(ticket.windows)
                if ticket.fused:
                    return _fetch(
                        ticket.scorer.launch_fused(ticket.windows)
                    )
                return _fetch(ticket.scorer.launch(ticket.windows))

            def _note_retry(attempt, exc):
                ticket.last_error = exc
                ticket.attempts += 1

            try:
                probs = retry_call(
                    _attempt_sync,
                    retries=cfg.retries - ticket.attempts,
                    backoff=self._retry_backoff,
                    on_retry=_note_retry,
                )
            except Exception as exc:
                ticket.last_error = exc
                ticket.attempts += 1
        pq = self._pending
        if probs is None:
            # graceful degradation: this batch's windows are shed, the
            # engine keeps serving every other stream.  Journaled per
            # window: unlike push-side sheds, a dispatch failure is not
            # derivable from the replayed record stream.  Rows already
            # dropped mid-flight (eviction) are skipped — their drop
            # was counted at the eviction.
            live_idx = batch[~pq.dropped[batch]]
            n_failed = len(live_idx)
            if n_failed:
                by_slot = self._sess_by_slot
                for i in live_idx.tolist():
                    self._jappend(
                        {
                            "t": "drop",
                            "sid": by_slot[pq.sess_slot[i]].sid,
                            "ti": int(pq.t_index[i]),
                            "reason": "dispatch_failed",
                        }
                    )
                    self._unlink_scored(by_slot[pq.sess_slot[i]], i)
                pq.dropped[live_idx] = True
                arena = self._session_arena
                fslots = pq.sess_slot[live_idx]
                np.add.at(arena.n_live, fslots, -1)
                np.add.at(arena.n_dropped, fslots, 1)
                self._n_live -= n_failed
            self.stats.drop(n_failed, "dispatch_failed")
            self.stats.dispatch_failures += 1
            self._note_slo(breached=True)
            # every batch row's staging slot frees HERE, in retire
            # order — launched windows (dropped-mid-flight included)
            # defer their frees to retire so an in-flight zero-copy
            # view is never re-staged under the device
            self._arena.free_block(pq.stage_slot[batch])
            self._recycle_slab(ticket)
            pq.release_block(batch)
            if prof is not None:
                prof.retire.record((self._clock() - t_retire0) * 1e3)
            return []
        # deliberate carry idle excluded: a ticket parked across polls
        # by design must not read as a slow dispatch (it would breach
        # the SLO and shed smoothing, diverging the pipelined event
        # stream from the synchronous engine's under real-time pacing)
        dispatch_ms = max(
            0.0, (self._clock() - ticket.t0) * 1e3 - ticket.idle_ms
        )
        self.stats.inflight_ms += (self._clock() - ticket.t_inflight0) * 1e3
        self.stats.dispatches += 1
        self.stats.note_batch(ticket.pad_k)
        self.stats.dispatch.record(dispatch_ms)
        # fetch-byte attribution: the unfused retire materializes the
        # full padded logits matrix on host (pad_k × C × 4 bytes); the
        # fused retire moves only pad_k × (int32 label + f32 top) = 8
        # bytes per padded row — the saving the 2× windows/s claim is
        # evidenced with (device_ms attribution rides calibration).
        # HostScorer retires count nothing: the whole score ran in host
        # memory, and fetch_bytes means bytes that crossed the device
        # boundary, not bytes that merely existed.
        if ticket.scorer.kind != "host":
            n_classes = probs.shape[1]
            full_bytes = ticket.pad_k * n_classes * 4
            if ticket.fused:
                self.stats.fused_dispatches += 1
                self.stats.fetch_bytes += ticket.pad_k * 8
                self.stats.fetch_bytes_saved += max(
                    0, full_bytes - ticket.pad_k * 8
                )
            else:
                self.stats.fetch_bytes += full_bytes
        # the ladder is driven by PRIOR evidence: the batch that records
        # a breach is still emitted at the pre-breach degradation level
        # (its windows were scored under the old regime), the next one
        # reflects the step
        shed = self._smoothing_shed
        self._note_slo(breached=dispatch_ms > cfg.dispatch_timeout_ms)

        # calibrated device share for this padded program, amortized
        # per window — the per-event tunnel-vs-chip attribution
        dev = self._device_ms.get(ticket.pad_k)
        dev_share = None if dev is None else round(dev["p50_ms"] / k, 4)
        lat_share = dispatch_ms / k

        journal_live = self._journal is not None and not self._replaying
        t_smooth0 = self._clock()
        self._chaos("post_score_pre_ack")
        # rows whose window was dropped mid-flight (a remove_session
        # while the ticket was carried) are scored by the device but
        # never emitted — their drop was already counted (their staging
        # slot frees with the batch below)
        live_pos = np.flatnonzero(~pq.dropped[batch])
        live_idx = batch[live_pos]
        m = len(live_pos)
        # decisions, vectorized: raw argmax for the whole batch in one
        # reduction; stateful smoothing as one BATCHED arena recurrence
        # over the live rows when every live session appears once in
        # the batch (the dominant shape at fleet scale — the
        # micro-batcher mixes sessions, it rarely repeats one), the
        # per-session sequential recurrence otherwise.  Both paths are
        # the same elementwise math (har_tpu.serve.arena), so the
        # decision columns are bit-identical either way — test-pinned
        # at N=64 under FakeClock+DispatchFaults across smoothing
        # modes, churn and ring depths 1-4.
        raw_all = probs.argmax(axis=1) if m else None
        labels = raws = None
        dec_rows = None  # (m, C)-ish block; row i is event i's decision
        slots_all = (
            pq.sess_slot[live_idx].astype(np.intp) if m else None
        )
        # one live session per batch row is the dominant shape at
        # fleet scale — the gate for BOTH the batched smoothing
        # kernels and the vectorized FIFO unlink below
        distinct = bool(m) and len(np.unique(slots_all)) == m
        if not m:
            decided = {}
        elif shed:
            raws = labels = raw_all[live_pos]
            dec_rows = probs[live_pos]  # fancy-index: a fresh copy
            decided = None
            self.stats.degraded_events += m
        else:
            decided = None
            if self.smoothing == "none":
                raws = labels = raw_all[live_pos]
                dec_rows = probs[live_pos]
            elif self.smoothing == "ema" and distinct:
                block = self._ema_kernel(slots_all, probs[live_pos])
                if block is not None:
                    raws = raw_all[live_pos]
                    labels = block.argmax(axis=1)
                    dec_rows = block
            elif self.smoothing == "vote" and distinct:
                out = self._session_arena.vote_block(
                    slots_all, raw_all[live_pos], probs.shape[1]
                )
                if out is not None:
                    raws = raw_all[live_pos]
                    labels, dec_rows = out
            if dec_rows is None:
                # sequential fallback (duplicate sessions in one batch,
                # EMA width mismatch after a swap, stale wide votes):
                # the per-session recurrence, grouped like PR-10 did
                # (grouped by arena slot — live sessions are slot-
                # unique, and the slot resolves the session handle)
                rows_by_sess: dict = {}
                for pos, slot in zip(
                    live_pos.tolist(), slots_all.tolist()
                ):
                    rows_by_sess.setdefault(slot, []).append(pos)
                decided = {}
                for slot, rows in rows_by_sess.items():
                    outs = self._sess_by_slot[slot].smoother.update_many(
                        probs[rows]
                    )
                    for pos, out in zip(rows, outs):
                        decided[pos] = out
        self.stats.note_scored(m, ticket.version)
        events: list[FleetEvent] = []
        if m:
            # per-session accounting for the whole batch in two
            # scatter-adds (np.add.at handles a session scored twice)
            arena = self._session_arena
            np.add.at(arena.n_scored, slots_all, 1)
            np.add.at(arena.n_live, slots_all, -1)
            self._n_live -= m
            # the whole batch's event latencies in one column gather —
            # what the per-event loop used to collect sample by sample
            self.stats.event.record_many(
                (t_smooth0 - pq.t_enqueue[live_idx]) * 1e3
            )
        if labels is not None:
            # one bulk conversion instead of 2 numpy-scalar casts per
            # event in the loop below
            labels = labels.tolist()
            raws = raws.tolist()
        # the per-event loop below is THE host-plane retire hot path:
        # events are assembled from per-dispatch COLUMN gathers off the
        # pending arena (t_index / drift / session slot — no per-window
        # object to poke), with the two frozen dataclasses built by
        # direct ``__dict__`` assignment — same instances, same fields,
        # but without paying frozen ``__setattr__`` seven times per
        # event (measured ~1 µs/event at fleet scale, the difference
        # between a 10k-session round fitting its poll budget or not)
        new = object.__new__
        emit = events.append
        by_slot = self._sess_by_slot
        pend_head = self._session_arena.pend_head
        pend_tail = self._session_arena.pend_tail
        next_idx = pq.next_idx
        release = pq.release
        fast_unlinked = False
        if m:
            t_idx_col = pq.t_index[live_idx].tolist()
            drift_col = pq.drift[live_idx].tolist()
            slot_col = slots_all.tolist()
            pos_col = live_pos.tolist()
            idx_col = live_idx.tolist()
            if distinct:
                # the vectorized FIFO unlink: when every live row sits
                # at its session list's head (no dropped leftovers in
                # front, no session twice in the batch — the steady
                # state), the whole batch's head pops are three
                # scatters + one block release instead of per-event
                # walks; any mismatch falls back to the per-event path
                heads = pend_head[slots_all]
                if (heads == live_idx).all():
                    nxt = next_idx[live_idx]
                    pend_head[slots_all] = nxt
                    ended = nxt < 0
                    if ended.any():
                        # head had no successor: it was the tail too
                        pend_tail[slots_all[ended]] = -1
                    pq.release_block(live_idx)
                    fast_unlinked = True
        for j in range(m):
            i = pos_col[j]  # batch position == probs row
            if decided is not None:
                label, raw_label, decision = decided[i]
                decision = decision.copy()
            else:
                label = labels[j]
                raw_label = raws[j]
                # dec_rows is a fresh per-dispatch block (a gather or
                # the probs fancy-index copy): its rows are this
                # event's own — no second per-event copy needed
                decision = dec_rows[j]
            sess = by_slot[slot_col[j]]
            ev = new(StreamEvent)
            # .update on the instance dict, NOT attribute assignment:
            # rebinding __dict__ itself would route through the frozen
            # dataclass __setattr__ and raise
            ev.__dict__.update(
                t_index=t_idx_col[j],
                label=label,
                raw_label=raw_label,
                probability=decision,
                latency_ms=lat_share,
                drift=drift_col[j],
                device_ms=dev_share,
            )
            # FIFO unlink (skipped when the vectorized block unlink
            # above already popped the whole batch), head-popped
            # inline: the common case is this window at the session
            # list's head; flagged-dropped heads fall back to the
            # shared walking helper
            if not fast_unlinked:
                pi = idx_col[j]
                slot = slot_col[j]
                if pend_head[slot] == pi:
                    nxt = next_idx[pi]
                    pend_head[slot] = nxt
                    if nxt < 0:
                        pend_tail[slot] = -1
                    release(pi)
                else:
                    self._unlink_scored(sess, pi)
            fe = new(FleetEvent)
            fe.__dict__.update(
                session_id=sess.sid, event=ev, degraded=shed
            )
            emit(fe)
        # the scored-event acks, group-committed: ONE batched journal
        # record per retire instead of m per-event records — session
        # ids in the meta, the raw probability rows (float64,
        # pre-smoothing, so replay re-steps each smoother itself)
        # packed back-to-back in the payload.  The per-entry t_indices
        # are NOT stored: replay re-derives each one from the pending
        # queue the push records rebuilt (the session's oldest live
        # window), and "tic" — one crc32 over the int64 column — is
        # the divergence guard, 4 bytes per record instead of 8 per
        # entry.  One meta dict, one CRC frame, one buffered write;
        # entry order is the emit-loop order above, so replay consumes
        # them through the same per-event _consume_ack sequence
        # bit-identically.  The flush/fsync cadence is untouched: acks
        # are durable at the end-of-poll flush BEFORE the consumer can
        # observe the events, so the ack boundary and the conservation
        # law hold verbatim.
        if journal_live and m:
            try:
                self._journal.append(
                    {
                        "t": "acks",
                        "n": m,
                        "sids": [by_slot[s].sid for s in slot_col],
                        "ver": ticket.version,
                        "shed": shed,
                        "tic": zlib.crc32(
                            np.asarray(t_idx_col, np.int64).tobytes()
                        )
                        & 0xFFFFFFFF,
                    },
                    np.ascontiguousarray(
                        probs[pos_col], np.float64
                    ).tobytes(),
                )
            except OSError as exc:
                # contained like the push append: the record stays
                # buffered; the end-of-poll flush (or a later one)
                # lands it, and the degradation is declared
                self._note_journal_error("ack append", exc)
        self.stats.smooth.record((self._clock() - t_smooth0) * 1e3)
        if self._dispatch_tap is not None:
            # mirrored sample for shadow evaluation — after the events
            # are finalized (their latencies are already recorded), and
            # never able to take the engine down.  _in_dispatch makes a
            # swap_model() called from inside the tap defer to the next
            # dispatch boundary.  Session ids ride the ticket's launch-
            # time snapshot (see _launch_batch); a tap installed while
            # this ticket was already in flight resolves best-effort
            # through the live slot map instead.
            self._in_dispatch = True
            t_tap = self._clock()
            try:
                sids = ticket.sids
                if sids is None:
                    sids = [
                        None if by_slot[s] is None else by_slot[s].sid
                        for s in pq.sess_slot[batch].tolist()
                    ]
                scored = self._dispatch_tap(
                    sids,
                    ticket.windows[:k],
                    probs,
                )
            except Exception:
                self.stats.shadow_errors += 1
            else:
                if scored:
                    self.stats.note_shadow(
                        k, (self._clock() - t_tap) * 1e3
                    )
            finally:
                self._in_dispatch = False
        # staging slots free in retire order, the whole batch in one
        # ring write (dropped-mid-flight rows included — launched
        # windows defer their staging free to HERE so an in-flight
        # zero-copy view is never re-staged under the device), then the
        # ticket's queue-side references release and fully-unlinked
        # slots recycle
        self._arena.free_block(pq.stage_slot[batch])
        self._recycle_slab(ticket)
        pq.release_block(batch)
        if prof is not None:
            prof.retire.record((self._clock() - t_retire0) * 1e3)
        return events

    def _unlink_scored(self, sess: _FleetSession, i: int) -> None:
        """Remove pending index ``i`` from its session's linked list,
        discarding (and releasing) any flagged-dropped entries ahead
        of it.  The global FIFO preserves per-session order, so ``i``
        is that session's leftmost LIVE entry — anything in front of
        it must be a dropped leftover."""
        pq = self._pending
        arena = self._session_arena
        slot = sess.slot
        h = arena.pend_head[slot]
        while h >= 0:
            nxt = pq.next_idx[h]
            if h != i and not pq.dropped[h]:  # pragma: no cover
                raise AssertionError("fleet queue order violated")
            arena.pend_head[slot] = nxt
            if nxt < 0:
                arena.pend_tail[slot] = -1
            pq.release(h)
            if h == i:
                return
            h = nxt

    def _note_slo(self, *, breached: bool) -> None:
        """The degradation ladder, in the order the docstring promises:
        smoothing shed first (events keep flowing), then scoring shed
        (stalest windows dropped) — and recovery in reverse."""
        cfg = self.config
        if breached:
            self.stats.slo_breaches += 1
            self._breaches += 1
            self._ok_streak = 0
            if self._breaches >= cfg.degrade_after_breaches:
                if not self._smoothing_shed:
                    self._smoothing_shed = True
                    self.stats.smoothing_shed_transitions += 1
                    self._jappend({"t": "shed", "on": True})
                else:
                    self._shed_stalest(
                        max(1, int(self._n_live * cfg.shed_fraction)),
                        "slo_shed",
                        record=True,
                    )
                self._breaches = 0  # each ladder step needs fresh evidence
        else:
            self._breaches = 0
            self._ok_streak += 1
            if (
                self._smoothing_shed
                and self._ok_streak >= cfg.recover_after_ok
            ):
                self._smoothing_shed = False
                self._ok_streak = 0
                self._jappend({"t": "shed", "on": False})

    @property
    def smoothing_shed(self) -> bool:
        """True while the engine is in degradation level >= 1."""
        return self._smoothing_shed

    # ---------------------------------------------------- calibration

    def calibrate_device(
        self, batch_sizes: Sequence[int] | None = None, iters: int = 16
    ) -> dict[int, dict]:
        """Measure DEVICE execution p50 for the padded batch programs
        THIS ENGINE ACTUALLY EMITS: every requested size is rounded up
        through the active scorer's pad policy (pow2 single-device,
        ``devices × pow2`` when a mesh is attached) and measured with
        the scorer's own placement — a sharded dispatch is timed against
        the sharded program on sharded input, not a single-device
        stand-in, so ``StreamEvent.device_ms`` attribution stays honest
        under sharding.  Defaults to the padded sizes this engine has
        dispatched (plus the smallest emitted shape).  ValueError for
        models without a jitted predict propagates — callers that serve
        host stubs skip calibration."""
        scorer = self._get_scorer()
        if batch_sizes is None:
            batch_sizes = sorted(
                {scorer.pad_size(1), *self.stats.batch_sizes}
            )
        # a fused engine dispatches the FUSED program (scale + logits +
        # softmax + argmax + top-prob), so that is what calibration
        # times at the emitted shapes — otherwise device_ms would
        # under-report the fused tier's on-device work and the p99
        # attribution would blame the tunnel for chip time
        fused = self._fused_active(scorer)
        for b in batch_sizes:
            b = scorer.pad_size(int(b))
            if isinstance(scorer, HostScorer):
                # host fallback: keep the shared single-program
                # measurement (raises ValueError for models with no
                # jitted predict at all — trees, numpy stubs)
                self._device_ms[b] = measure_device_latency(
                    self.model,
                    window=self.window,
                    channels=self.channels,
                    batch=b,
                    iters=iters,
                )
            else:
                self._device_ms[b] = scorer.measure(
                    b, iters=iters, fused=fused
                )
        return dict(self._device_ms)

    # ------------------------------------------------------ reporting

    def stats_snapshot(self) -> dict:
        """FleetStats snapshot + device calibration + p99 attribution."""
        # memory-footprint gauges (live, recomputed per snapshot): the
        # resident bytes of the three SoA estates — the visibility the
        # ROADMAP's "20k point is partially memory-bound" note asked
        # for, stamped into the host_plane gate entry and the scaling
        # artifact rows
        self.stats.arena_bytes = self._session_arena.nbytes
        self.stats.staging_bytes = self._arena.nbytes
        self.stats.pending_bytes = self._pending.nbytes
        snap = self.stats.snapshot()
        snap["smoothing_shed"] = self._smoothing_shed
        snap["model_version"] = self.model_version
        snap["session_arena"] = self._session_arena.state()
        snap["pending_arena"] = self._pending.state()
        # per-poll host-time breakdown (FleetConfig.profile_host):
        # ingest / due-select / gather / retire / journal stage
        # histograms + the pending-depth distribution — what the
        # sessions-per-worker ceiling curve and host-plane regression
        # checks read.  The footprint gauges ride the same block
        # unconditionally (they cost three property reads, not a
        # clock), so capacity checks see them without --profile-host.
        host_profile = (
            {}
            if self.host_profile is None
            else self.host_profile.snapshot()
        )
        host_profile["arena_bytes"] = self.stats.arena_bytes
        host_profile["staging_bytes"] = self.stats.staging_bytes
        host_profile["pending_bytes"] = self.stats.pending_bytes
        snap["host_profile"] = host_profile
        # dispatch-plane shape: reported only once the first dispatch
        # has built the scorer (building it here could cold-start a jax
        # backend from a pure stats read)
        snap["pipeline_depth"] = self.config.pipeline_depth
        snap["fused"] = (
            False
            if self._scorer is None
            else self._fused_active(self._scorer)
        )
        snap["dispatch_backend"] = (
            None if self._scorer is None else self._scorer.kind
        )
        snap["devices"] = (
            None if self._scorer is None else self._scorer.devices
        )
        snap["model_axis_shards"] = (
            None
            if self._scorer is None
            else getattr(self._scorer, "model_axis_shards", 1)
        )
        if self._device_ms:
            snap["device_ms"] = {
                str(b): d["p50_ms"]
                for b, d in sorted(self._device_ms.items())
            }
            # attribute the dispatch p99 spike: if the worst calibrated
            # device time can't explain it, the spike is host/transfer/
            # tunnel — the share a co-located deployment would shed
            p99 = self.stats.dispatch.percentile(99)
            worst_dev = max(d["p50_ms"] for d in self._device_ms.values())
            if p99 is not None:
                snap["dispatch_p99_attribution"] = {
                    "p99_ms": round(p99, 3),
                    "device_p50_ms": worst_dev,
                    "host_overhead_ms": round(max(0.0, p99 - worst_dev), 3),
                    "dominated_by": (
                        "host_tunnel" if p99 > 2.0 * worst_dev else "device"
                    ),
                }
        return snap
