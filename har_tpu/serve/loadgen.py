"""Seeded synthetic fleet load: thousands of phase-offset 20 Hz sessions.

Builds per-session recordings from the calibrated synthetic generator
family (``data/raw_windows.synthetic_raw_stream``) and drives a
``FleetServer`` with a deterministic round-robin delivery schedule:
each session delivers hop-sized chunks, phase-offset so hop boundaries
stagger across the fleet instead of all landing in the same
micro-batch slot (the realistic arrival pattern — users don't
synchronize their sensors).  Transport faults (drop / delay / burst,
``har_tpu.serve.faults.DeliveryFaults``) are applied per chunk from the
same seed.

Also home of ``AnalyticDemoModel`` — a deterministic, training-free
classifier over the synthetic stream's own class dynamics.  It is
row-independent numpy end-to-end, so its per-window outputs are
bit-identical under ANY batch composition: the property the
fleet-vs-independent equivalence test (and the release gate's SLO
smoke) pins without spending a model fit.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from har_tpu.data.raw_windows import synthetic_raw_stream
from har_tpu.serve.engine import FleetServer
from har_tpu.serve.faults import DeliveryFaults


class AnalyticDemoModel:
    """Nearest-centroid activity classifier on (per-axis mean, std).

    Centroids are computed once from a fixed-seed draw of the synthetic
    generator itself — self-calibrating to the exact class dynamics the
    load generator emits, no training step.  transform() is plain
    per-row numpy: deterministic, batch-composition-independent, and
    fast enough to score a thousand sessions' windows in microseconds —
    the engine-overhead measurement baseline (a real model adds device
    dispatch on top; this model isolates the scheduler's own cost).
    """

    def __init__(self, tau: float = 2.0):
        cal = synthetic_raw_stream(n_windows=240, seed=1729)
        feats = self._features(cal.windows)
        self.num_classes = len(cal.class_names)
        self.class_names = cal.class_names
        self._centroids = np.stack(
            [
                feats[cal.labels == c].mean(axis=0)
                for c in range(self.num_classes)
            ]
        )
        self._tau = float(tau)

    @staticmethod
    def _features(x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float64)
        return np.concatenate(
            [x.mean(axis=1), x.std(axis=1)], axis=-1
        )  # (n, 6)

    def transform(self, x):
        from har_tpu.models.base import Predictions

        f = self._features(np.asarray(x))
        d2 = ((f[:, None, :] - self._centroids[None]) ** 2).sum(-1)
        raw = -d2 / self._tau
        e = np.exp(raw - raw.max(axis=-1, keepdims=True))
        return Predictions.from_raw(
            raw, e / e.sum(axis=-1, keepdims=True)
        )


class JitDemoModel:
    """A jitted, training-free MLP over raw windows — the DEVICE-path
    counterpart of ``AnalyticDemoModel``.

    Fixed-seed random dense weights (window·channels → hidden →
    classes): deterministic, row-independent (per-row matmul + tanh —
    batch composition can never change a row's logits), and backed by a
    real jitted program, so it exercises everything the host-side demo
    model cannot: async launch (un-fetched device arrays), device_put
    placement, batch sharding over a mesh, per-shape compilation, and
    device calibration.  The labels mean nothing — fleet benchmarks and
    pipeline smokes measure the serving engine, and this model gives
    the engine a genuine device workload to overlap against.

    Exposes the ``params`` + ``_predict`` pair the NeuralModel family
    exposes, so ``serve.dispatch._split_predict``,
    ``serving.device_predict_fn`` and device calibration all treat it
    exactly like a trained checkpoint.
    """

    def __init__(
        self,
        window: int = 200,
        channels: int = 3,
        hidden: int = 256,
        num_classes: int = 6,
        seed: int = 1729,
        tunnel_rtt_ms: float = 0.0,
    ):
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng((seed, 0x11D3))
        d_in = window * channels
        scale = 1.0 / np.sqrt(d_in)
        # emulated remote-tunnel dispatch RTT, honored by the async
        # scorer (serve.dispatch.DeviceScorer): fetch blocks until
        # launch + RTT.  The dry-run stand-in for the documented
        # production tunnel (~250 ms e2e per dispatch, BENCH_r04) —
        # what the pipelined grid's overlap claim is measured against
        # on hosts where the local device finishes in microseconds.
        self.tunnel_rtt_ms = float(tunnel_rtt_ms)
        self.window = int(window)
        self.channels = int(channels)
        self.num_classes = int(num_classes)
        self.class_names = tuple(
            f"class{i}" for i in range(self.num_classes)
        )
        self.params = {
            "w1": jnp.asarray(
                rng.normal(0, scale, size=(d_in, hidden)), jnp.float32
            ),
            "b1": jnp.zeros((hidden,), jnp.float32),
            "w2": jnp.asarray(
                rng.normal(0, 1.0 / np.sqrt(hidden),
                           size=(hidden, num_classes)),
                jnp.float32,
            ),
        }

        def forward(p, x):
            h = jnp.tanh(
                x.reshape(x.shape[0], -1) @ p["w1"] + p["b1"]
            )
            return h @ p["w2"]

        self._jax = jax
        self._predict = jax.jit(forward)

    def transform(self, x):
        """The synchronous reference path — same ops, same order, as
        the async scorer's launch+fetch (dispatch.DeviceScorer), so
        pipelined and synchronous runs of this model are bit-identical."""
        import jax
        import jax.numpy as jnp

        from har_tpu.models.base import Predictions

        x = np.asarray(x, np.float32)
        logits = np.asarray(self._predict(self.params, jax.device_put(x)))
        probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        return Predictions.from_raw(logits, probs)


def run_pipeline_cell(
    pipeline_depth: int = 1,
    devices: int = 1,
    *,
    target_batch: int = 256,
    n_sessions: int = 1000,
    windows_per_session: int = 2,
    tunnel_rtt_ms: float = 30.0,
    n_runs: int = 3,
    hidden: int = 256,
    seed: int = 3,
    fused: bool = False,
    tier: str = "f32",
    smoothing: str = "ema",
    collect_labels: bool = False,
) -> dict:
    """One cell of the pipelined-dispatch grid: drive the standard
    synthetic fleet load through a FleetServer at the given pipeline
    depth / device count and report windows/s (median+std over n_runs,
    after a compile warmup) plus the dispatch-plane stats.

    THE shared measurement behind ``bench.py``'s ``fleet_pipeline_grid``
    lane — the mesh cell runs in a subprocess with a forced dry-run
    device count (an in-process force would reshape every OTHER lane's
    mesh), and sharing this function is what keeps the in-process and
    subprocess cells comparable.  Raises ValueError when ``devices``
    exceeds the visible device count.

    ``fused=True`` serves through the fused on-device hot loop (needs a
    fused-eligible ``smoothing`` — vote/none); ``tier="int8"`` serves
    the weight-only int8 quantization of the demo model
    (har_tpu.quantize.quantize_serving).  ``collect_labels=True`` adds
    the final run's ``(session, t_index, label)`` stream to the result
    — what the grid's int8-agreement key is computed from.
    """
    import jax

    from har_tpu.parallel.mesh import create_mesh

    if devices > len(jax.devices()):
        raise ValueError(
            f"cell needs {devices} devices, {len(jax.devices())} visible"
        )
    mesh = create_mesh(dp=devices, tp=1) if devices > 1 else None
    model = JitDemoModel(hidden=hidden, tunnel_rtt_ms=tunnel_rtt_ms)
    if tier == "int8":
        from har_tpu.quantize import quantize_serving

        model = quantize_serving(model)
    elif tier != "f32":
        raise ValueError(f"unknown tier {tier!r}")
    recordings, _ = synthetic_sessions(
        n_sessions, windows_per_session=windows_per_session, seed=seed
    )

    def one_run():
        from har_tpu.serve.engine import FleetConfig, FleetServer

        server = FleetServer(
            model,
            window=200,
            hop=200,
            smoothing=smoothing,
            config=FleetConfig(
                max_sessions=n_sessions,
                pipeline_depth=pipeline_depth,
                target_batch=target_batch,
                fused=fused,
            ),
            mesh=mesh,
        )
        for i in range(n_sessions):
            server.add_session(i)
        events, report = drive_fleet(server, recordings, seed=seed)
        return server, report, events

    one_run()  # warmup: compile the padded programs
    wps, server, events = [], None, None
    for _ in range(int(n_runs)):
        server, report, events = one_run()
        acct = server.stats.accounting()
        wps.append(
            acct["scored"] / report.duration_s if report.duration_s else 0.0
        )
    snap = server.stats_snapshot()
    scored = snap["accounting"]["scored"]
    # device-ms attribution: calibrate the program the cell actually
    # dispatched (the FUSED program when fused — satellite contract) at
    # the emitted padded shapes, so the artifact's speedup claim rides
    # with per-shape device-time evidence, not just wall clocks
    try:
        device_ms = {
            str(b): d["p50_ms"]
            for b, d in sorted(server.calibrate_device(iters=4).items())
        }
    except ValueError:  # host-only model: no device program to time
        device_ms = None
    out = {
        "pipeline_depth": int(pipeline_depth),
        "devices": int(devices),
        "target_batch": int(target_batch),
        "device_ms": device_ms,
        "fused": bool(fused),
        "tier": tier,
        "smoothing": smoothing,
        "windows_per_sec_median": round(float(np.median(wps)), 1),
        "windows_per_sec_std": round(float(np.std(wps)), 1),
        "event_p99_ms_median": snap["stages"]["event_ms"].get("p99_ms"),
        "overlap_pct": snap["overlap_pct"],
        "inflight_depth": snap["inflight_depth"],
        "device_windows": snap["device_windows"],
        "dispatch_backend": snap["dispatch_backend"],
        "dispatches": snap["dispatches"],
        "fused_dispatches": snap["fused_dispatches"],
        "fetch_bytes_per_window": (
            round(snap["fetch_bytes"] / scored, 1) if scored else None
        ),
        "fetch_bytes_saved": snap["fetch_bytes_saved"],
        "dropped_windows": snap["accounting"]["dropped"],
        "accounting_balanced": snap["accounting"]["balanced"],
    }
    if collect_labels:
        out["labels"] = [
            [fe.session_id, fe.event.t_index, int(fe.event.label)]
            for fe in events
        ]
    return out


def run_fused_grid_cells(tb_base: int, common: dict) -> tuple[dict, object]:
    """The fused depth-3 cells of the pipeline grid — f32 and int8
    through the same fused hot loop — plus the int8 LIVE-label
    agreement between them.  Shared by ``bench.py``'s
    ``fleet_pipeline_grid`` lane and ``scripts/pipeline_grid_bench.py``
    so the committed artifact and the round bench cannot compute the
    agreement statistic differently.

    tb doubles vs the grid's base cells: the depth-3 ring then
    pipelines full dispatches while exposing half the serial tunnel
    RTTs — a different dispatch-plane configuration by design, exactly
    like the mesh cell's devices-scaled batch.  Returns
    ``({"3x1_fused": ..., "3x1_fused_int8": ...}, int8_agreement)``
    with the label streams consumed (popped) into the agreement."""
    cells = {
        "3x1_fused": run_pipeline_cell(
            3, 1, target_batch=tb_base * 2, fused=True,
            smoothing="vote", collect_labels=True, **common
        ),
        "3x1_fused_int8": run_pipeline_cell(
            3, 1, target_batch=tb_base * 2, fused=True, tier="int8",
            smoothing="vote", collect_labels=True, **common
        ),
    }
    f32_labels = cells["3x1_fused"].pop("labels", [])
    int8_labels = cells["3x1_fused_int8"].pop("labels", [])
    agreement = None
    if f32_labels and len(f32_labels) == len(int8_labels):
        agreement = round(
            sum(a == b for a, b in zip(f32_labels, int8_labels))
            / len(f32_labels),
            4,
        )
    return cells, agreement


def run_pipeline_cell_subprocess(
    pipeline_depth: int,
    devices: int,
    kwargs: dict,
    *,
    timeout_s: float = 600.0,
) -> dict:
    """Run one grid cell in a fresh interpreter with the dry-run device
    count forced — THE one subprocess wrapper shared by ``bench.py``'s
    ``fleet_pipeline_grid`` lane and ``scripts/pipeline_grid_bench.py``
    (an in-process device-count force would reshape the parent's
    backend for every other lane).  The flag only affects the CPU
    platform: a host already exposing >= ``devices`` real devices
    shards those and the force is inert.  Raises on failure or timeout
    — callers that must survive a dead cell catch and record."""
    import os
    import subprocess
    import sys

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={devices}"
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import json; from har_tpu.serve.loadgen import "
            "run_pipeline_cell; print(json.dumps(run_pipeline_cell("
            f"{int(pipeline_depth)}, {int(devices)}, **{dict(kwargs)!r})))",
        ],
        capture_output=True,
        text=True,
        timeout=timeout_s,
        env={**os.environ, "XLA_FLAGS": flags},
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"pipeline grid cell failed (rc={proc.returncode}): "
            f"{proc.stderr[-500:]}"
        )
    import json

    return json.loads(proc.stdout.strip().splitlines()[-1])


class WideTransformerDemoModel:
    """A WIDE Transformer1D-shaped serving checkpoint — the
    bigger-than-one-device star of the ``model_parallel_grid``
    artifact.

    The param tree carries the exact unscanned-encoder paths the
    ``transformer`` rule table keys on (``EncoderBlock_i/{qkv, proj,
    Dense_0, Dense_1, LayerNorm_*}`` plus a replicated ``embed`` input
    projection and ``head``), so ``rules_for_params`` auto-selects
    TRANSFORMER_RULES and a ``ModelParallelScorer`` places it
    head-parallel over the ``tp`` axis with no per-model plumbing.  At
    the default width (embed 768, 3 blocks) the f32 checkpoint is
    ~85 MB — past the grid's 64 MiB emulated-device budget, so
    batch-only sharding (full replica per device) is declared
    impossible and only the 2D placement serves it within budget.

    Like ``JitDemoModel``: fixed-seed weights, training-free,
    row-independent (attention never crosses batch rows), and a real
    jitted program behind the ``params`` + ``_predict`` contract.  The
    forward pass strides the 200-sample window to ``window // stride``
    tokens so the attention cost stays CPU-affordable; the labels mean
    nothing — the cell measures placement, not accuracy.
    """

    def __init__(
        self,
        embed_dim: int = 768,
        num_layers: int = 3,
        num_heads: int = 8,
        window: int = 200,
        channels: int = 3,
        num_classes: int = 6,
        seed: int = 1729,
        stride: int = 8,
        tunnel_rtt_ms: float = 0.0,
    ):
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng((seed, 0xA77))
        e = int(embed_dim)
        self.tunnel_rtt_ms = float(tunnel_rtt_ms)
        self.window = int(window)
        self.channels = int(channels)
        self.num_classes = int(num_classes)
        self.class_names = tuple(
            f"class{i}" for i in range(self.num_classes)
        )

        def dense(d_in, d_out):
            return {
                "kernel": jnp.asarray(
                    rng.normal(0, 1.0 / np.sqrt(d_in), size=(d_in, d_out)),
                    jnp.float32,
                ),
                "bias": jnp.zeros((d_out,), jnp.float32),
            }

        def norm():
            return {
                "scale": jnp.ones((e,), jnp.float32),
                "bias": jnp.zeros((e,), jnp.float32),
            }

        # "embed"/"head" (NOT in_proj/out_proj): `proj/kernel$` is a
        # row-parallel rule and re.search would claim any path ending
        # in proj — the reference-tree names keep these replicated
        params = {"embed": dense(channels, e)}
        for i in range(int(num_layers)):
            params[f"EncoderBlock_{i}"] = {
                "LayerNorm_0": norm(),
                "qkv": dense(e, 3 * e),
                "proj": dense(e, e),
                "LayerNorm_1": norm(),
                "Dense_0": dense(e, 4 * e),
                "Dense_1": dense(4 * e, e),
            }
        params["head"] = dense(e, num_classes)
        self.params = params

        heads, head_dim, st = int(num_heads), e // int(num_heads), int(stride)

        def layer_norm(x, p):
            mu = x.mean(axis=-1, keepdims=True)
            var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
            return (x - mu) / jnp.sqrt(var + 1e-6) * p["scale"] + p["bias"]

        def forward(p, x):
            x = x[:, ::st, :]
            b, t = x.shape[0], x.shape[1]
            h = x @ p["embed"]["kernel"] + p["embed"]["bias"]
            for i in range(int(num_layers)):
                blk = p[f"EncoderBlock_{i}"]
                y = layer_norm(h, blk["LayerNorm_0"])
                qkv = y @ blk["qkv"]["kernel"] + blk["qkv"]["bias"]
                q, k, v = jnp.split(qkv, 3, axis=-1)
                q = q.reshape(b, t, heads, head_dim).transpose(0, 2, 1, 3)
                k = k.reshape(b, t, heads, head_dim).transpose(0, 2, 1, 3)
                v = v.reshape(b, t, heads, head_dim).transpose(0, 2, 1, 3)
                scores = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(head_dim)
                attn = jax.nn.softmax(scores, axis=-1) @ v
                a = attn.transpose(0, 2, 1, 3).reshape(b, t, e)
                h = h + a @ blk["proj"]["kernel"] + blk["proj"]["bias"]
                y = layer_norm(h, blk["LayerNorm_1"])
                m = jax.nn.gelu(
                    y @ blk["Dense_0"]["kernel"] + blk["Dense_0"]["bias"]
                )
                h = h + m @ blk["Dense_1"]["kernel"] + blk["Dense_1"]["bias"]
            pooled = h.mean(axis=1)
            return pooled @ p["head"]["kernel"] + p["head"]["bias"]

        self._jax = jax
        self._predict = jax.jit(forward)

    def transform(self, x):
        """Synchronous reference path — same ops, same order, as the
        async scorer's launch+fetch, so mesh and single-device runs of
        this model are comparable at the 1e-6 GSPMD tolerance."""
        import jax
        import jax.numpy as jnp

        from har_tpu.models.base import Predictions

        x = np.asarray(x, np.float32)
        logits = np.asarray(self._predict(self.params, jax.device_put(x)))
        probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        return Predictions.from_raw(logits, probs)


def run_model_parallel_cell(
    dp: int,
    tp: int,
    *,
    target_batch: int = 256,
    n_sessions: int = 1000,
    windows_per_session: int = 2,
    tunnel_rtt_ms: float = 30.0,
    n_runs: int = 3,
    pipeline_depth: int = 2,
    seed: int = 3,
    smoothing: str = "ema",
    model: str = "mlp",
    check_single_device: bool = False,
) -> dict:
    """One cell of the model-parallel grid: drive the standard
    synthetic fleet load through a FleetServer on a ``dp × tp``
    (batch × model) mesh and report windows/s (median+std over n_runs,
    after a compile warmup) plus the placement evidence — scorer kind,
    model-axis extent, and the per-device vs total parameter bytes the
    ``fits_one_device`` claim is judged against.

    THE shared measurement behind ``bench.py``'s ``model_parallel_grid``
    lane and ``scripts/model_parallel_grid_bench.py`` — multi-device
    cells run in a subprocess with the dry-run device count forced
    (``run_model_parallel_cell_subprocess``), exactly like the pipeline
    grid's mesh cell.  ``model`` picks the checkpoint: ``"mlp"`` (the
    h256 JitDemoModel — the small-model speedup cells) or
    ``"wide_transformer"`` (the ~85 MB WideTransformerDemoModel — the
    bigger-than-one-device headline cell).  ``check_single_device=True``
    additionally replays the load on a single device and pins the
    tentpole equivalence contract (label-equal, probability vectors to
    1e-6) into the cell as ``single_device_equivalent``.  Raises
    ValueError when ``dp*tp`` exceeds the visible device count."""
    import jax

    from har_tpu.parallel.mesh import create_mesh

    n_dev = int(dp) * int(tp)
    if n_dev > len(jax.devices()):
        raise ValueError(
            f"cell needs {n_dev} devices, {len(jax.devices())} visible"
        )
    mesh = (
        create_mesh(dp=dp, tp=tp, devices=jax.devices()[:n_dev])
        if n_dev > 1
        else None
    )
    if model == "mlp":
        served = JitDemoModel(tunnel_rtt_ms=tunnel_rtt_ms)
    elif model == "wide_transformer":
        served = WideTransformerDemoModel(tunnel_rtt_ms=tunnel_rtt_ms)
    else:
        raise ValueError(f"unknown model {model!r}")
    recordings, _ = synthetic_sessions(
        n_sessions, windows_per_session=windows_per_session, seed=seed
    )

    def one_run(run_mesh, depth):
        from har_tpu.serve.engine import FleetConfig, FleetServer

        server = FleetServer(
            served,
            window=200,
            hop=200,
            smoothing=smoothing,
            config=FleetConfig(
                max_sessions=n_sessions,
                pipeline_depth=depth,
                target_batch=target_batch,
            ),
            mesh=run_mesh,
        )
        for i in range(n_sessions):
            server.add_session(i)
        events, report = drive_fleet(server, recordings, seed=seed)
        return server, report, events

    one_run(mesh, pipeline_depth)  # warmup: compile the padded programs
    wps, server, events = [], None, None
    for _ in range(int(n_runs)):
        server, report, events = one_run(mesh, pipeline_depth)
        acct = server.stats.accounting()
        wps.append(
            acct["scored"] / report.duration_s if report.duration_s else 0.0
        )
    snap = server.stats_snapshot()
    pb = server.scorer.params_bytes()
    out = {
        "mesh": f"{int(dp)}x{int(tp)}",
        "dp": int(dp),
        "tp": int(tp),
        "devices": n_dev,
        "model": model,
        "pipeline_depth": int(pipeline_depth),
        "target_batch": int(target_batch),
        "scorer": type(server.scorer).__name__,
        "model_axis_shards": snap["model_axis_shards"],
        "dispatch_backend": snap["dispatch_backend"],
        "windows_per_sec_median": round(float(np.median(wps)), 1),
        "windows_per_sec_std": round(float(np.std(wps)), 1),
        "event_p99_ms_median": snap["stages"]["event_ms"].get("p99_ms"),
        "params_bytes_total": pb["total"],
        "params_bytes_per_device": pb["per_device"],
        "dropped_windows": snap["accounting"]["dropped"],
        "accounting_balanced": snap["accounting"]["balanced"],
    }
    if check_single_device:
        _, _, ref_events = one_run(None, 1)
        by_sid: dict[int, list] = {i: [] for i in range(n_sessions)}
        ref_sid: dict[int, list] = {i: [] for i in range(n_sessions)}
        for fe in events:
            by_sid[fe.session_id].append(fe.event)
        for fe in ref_events:
            ref_sid[fe.session_id].append(fe.event)
        equivalent = True
        for i in range(n_sessions):
            a, b = ref_sid[i], by_sid[i]
            if len(a) != len(b) or not all(
                x.t_index == y.t_index
                and x.label == y.label
                and x.raw_label == y.raw_label
                and np.allclose(x.probability, y.probability, atol=1e-6)
                for x, y in zip(a, b)
            ):
                equivalent = False
                break
        out["single_device_equivalent"] = equivalent
    return out


def run_model_parallel_cell_subprocess(
    dp: int,
    tp: int,
    kwargs: dict,
    *,
    timeout_s: float = 600.0,
) -> dict:
    """Run one model-parallel grid cell in a fresh interpreter with the
    dry-run device count forced to ``dp*tp`` — the 2D twin of
    ``run_pipeline_cell_subprocess`` and shared by the bench lane and
    the committed-artifact script for the same reason (an in-process
    force would reshape every other lane's mesh; on a host already
    exposing enough real devices the flag is inert).  Raises on failure
    or timeout — callers that must survive a dead cell catch and
    record."""
    import os
    import subprocess
    import sys

    n_dev = max(1, int(dp) * int(tp))
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={n_dev}"
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import json; from har_tpu.serve.loadgen import "
            "run_model_parallel_cell; print(json.dumps("
            f"run_model_parallel_cell({int(dp)}, {int(tp)}, "
            f"**{dict(kwargs)!r})))",
        ],
        capture_output=True,
        text=True,
        timeout=timeout_s,
        env={**os.environ, "XLA_FLAGS": flags},
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"model-parallel grid cell failed (rc={proc.returncode}): "
            f"{proc.stderr[-500:]}"
        )
    import json

    return json.loads(proc.stdout.strip().splitlines()[-1])


class HostPlaneStubModel:
    """Near-zero-cost row-deterministic scorer for the host-plane
    scaling curve: per-channel window means through one fixed seeded
    ``(3, C)`` projection + softmax — about a microsecond per window,
    so the sessions-per-worker measurement is dominated by the Python
    host plane it exists to size, not by model arithmetic (the
    AnalyticDemoModel's feature pipeline costs ~13 µs/window, which
    would flatten any host-plane ratio toward 1).  Row-independent
    like every fleet-equivalence stub: batch composition can never
    change a row's scores."""

    num_classes = 6
    class_names = tuple(f"class{i}" for i in range(6))

    def __init__(self, seed: int = 1729, taps: int = 5):
        rng = np.random.default_rng((seed, 0x50A))
        self._taps = int(taps)
        self._w = rng.normal(0, 1.0, size=(3 * self.taps, self.num_classes))

    @property
    def taps(self) -> int:
        return self._taps

    def transform(self, x):
        from har_tpu.models.base import Predictions

        x = np.asarray(x)
        # a handful of evenly-spaced sample taps per window instead of
        # a full strided mean: the scores are equally meaningless for a
        # load benchmark, and the strided (k, T, C) mean alone costs
        # ~4 µs/window — which would be 40% of the whole host-plane
        # budget this harness exists to measure
        step = max(1, x.shape[1] // self._taps)
        f = x[:, :: step, :][:, : self._taps, :].reshape(len(x), -1)
        raw = np.asarray(f, np.float64) @ self._w
        e = np.exp(raw - raw.max(axis=-1, keepdims=True))
        return Predictions.from_raw(raw, e / e.sum(axis=-1, keepdims=True))


def host_plane_rounds(
    recordings, hop: int, offsets
) -> list[tuple[list, list]]:
    """THE phase-staggered delivery schedule of the host-plane
    measurement: per round, one hop-sized chunk per still-active
    session, the first chunk shortened by the session's seeded offset
    so hop boundaries stagger across the fleet (``drive_fleet``'s
    stance).  ONE builder shared by ``host_plane_benchmark`` and the
    release gate's ``host_plane_smoke`` — the gate's equivalence check
    must exercise the exact cadence the benchmark measures."""
    n = len(recordings)
    rounds: list[tuple[list, list]] = []
    cursors = [0] * n
    while True:
        ids, chunks = [], []
        for i in range(n):
            take = hop if cursors[i] else max(1, hop - int(offsets[i]))
            part = recordings[i][cursors[i]: cursors[i] + take]
            cursors[i] += take
            if len(part):
                ids.append(i)
                chunks.append(part)
        if not ids:
            break
        rounds.append((ids, chunks))
    return rounds


def host_plane_benchmark(
    session_counts,
    n_runs: int = 3,
    *,
    windows_per_session: int = 21,
    window: int = 200,
    hop: int = 20,
    target_batch: int = 256,
    seed: int = 3,
) -> list[dict]:
    """THE sessions-per-worker host-plane measurement shared by
    ``bench.py``'s ``host_plane_scaling`` lane and
    ``scripts/host_plane_bench.py`` (the committed-artifact path): per
    session count, drive the paper's serving cadence — 20 Hz streams,
    one hop-sized delivery per session per round, one decision per
    second (window=200, hop=20, the ``StreamingClassifier`` defaults),
    hop boundaries phase-staggered across the fleet exactly like
    ``drive_fleet``'s schedule — through a FleetServer on the
    near-free ``HostPlaneStubModel`` (no device program, no tunnel,
    ~1 µs/window of model arithmetic), so every measured millisecond
    is the Python host plane the SoA refactor targets.  Reports
    windows/s, host-ms-per-poll (the per-round push+poll wall time —
    one round = one second of stream time, so the per-round budget IS
    the real-time bound) and event p99, median+std over ``n_runs``.
    One implementation so the lane and the artifact cannot silently
    diverge; it runs unchanged against the pre-SoA engine (the PR-10
    baseline rows in the artifact were captured with exactly this
    harness), using ``push_many`` batched ingest when the engine
    provides it and per-session ``push`` otherwise.
    """
    from har_tpu.serve.engine import FleetConfig, FleetServer

    model = HostPlaneStubModel()
    rows = []
    for n_sessions in session_counts:
        n_sessions = int(n_sessions)
        n_samples = window + hop * (max(int(windows_per_session), 1) - 1)
        rng = np.random.default_rng((seed, 0xB0B))
        recordings = [
            np.asarray(r, np.float32)
            for r in np.split(
                rng.normal(
                    0.0, 1.0, size=(n_sessions * n_samples, 3)
                ).astype(np.float32),
                n_sessions,
            )
        ]
        # the delivery schedule is precomputed OUTSIDE the timed
        # region: the harness measures the ENGINE's host plane (push +
        # poll), not the synthetic transport's chunk slicing.  The
        # seeded phase offsets stagger hop boundaries across the fleet
        # (drive_fleet's stance): window completions spread over every
        # round instead of synchronizing into one.
        offsets = rng.integers(0, hop, size=n_sessions)
        rounds = host_plane_rounds(recordings, hop, offsets)
        wps, poll_ms, p99s, p50s = [], [], [], []
        balanced = True
        footprint = {}
        for run in range(int(n_runs) + 1):  # +1 warmup
            server = FleetServer(
                model, window=window, hop=hop, smoothing="ema",
                config=FleetConfig(
                    max_sessions=n_sessions, target_batch=target_batch
                ),
            )
            for i in range(n_sessions):
                server.add_session(i)
            push_many = getattr(server, "push_many", None)
            round_ms = []
            t_start = time.perf_counter()
            for ids, chunks in rounds:
                t0 = time.perf_counter()
                if push_many is not None:
                    push_many(ids, chunks)
                else:
                    for sid, part in zip(ids, chunks):
                        server.push(sid, part)
                server.poll(force=True)
                round_ms.append((time.perf_counter() - t0) * 1e3)
            server.flush()
            duration = time.perf_counter() - t_start
            if run == 0:
                continue  # warmup run: first-touch allocation + compile
            acct = server.stats.accounting()
            balanced = balanced and acct["balanced"] and acct["pending"] == 0
            wps.append(acct["scored"] / duration if duration else 0.0)
            poll_ms.append(float(np.median(round_ms)) if round_ms else 0.0)
            ev = server.stats.event
            p99s.append(ev.percentile(99) or 0.0)
            p50s.append(ev.percentile(50) or 0.0)
            # memory-footprint gauges (PR 14): resident bytes of the
            # SoA estates at end of run — the "partially memory-bound"
            # visibility the scaling artifact rows carry (identical
            # across runs at a given N: capacities are load-determined)
            prof = server.stats_snapshot().get("host_profile") or {}
            footprint = {
                key: prof[key]
                for key in (
                    "arena_bytes", "staging_bytes", "pending_bytes"
                )
                if key in prof  # absent on pre-SoA baseline trees
            }
        rows.append(
            {
                "n_sessions": n_sessions,
                "windows": n_sessions * windows_per_session,
                "n_runs": int(n_runs),
                **footprint,
                "windows_per_sec_median": round(float(np.median(wps)), 1),
                "windows_per_sec_std": round(float(np.std(wps)), 1),
                "host_ms_per_poll_median": round(
                    float(np.median(poll_ms)), 3
                ),
                "host_ms_per_poll_std": round(float(np.std(poll_ms)), 3),
                "event_p50_ms_median": round(float(np.median(p50s)), 3),
                "event_p99_ms_median": round(float(np.median(p99s)), 3),
                "event_p99_ms_std": round(float(np.std(p99s)), 3),
                "accounting_balanced": balanced,
            }
        )
    return rows


def host_plane_ceiling(rows: list[dict], p99_budget_ms: float) -> float | None:
    """Sessions-per-worker ceiling at equal p99: the largest session
    count whose median event p99 stays inside the budget, interpolated
    linearly between grid points (p99 grows monotonically with N on
    this workload — each poll round does O(N) host work).  None when
    even the smallest measured count blows the budget."""
    pts = sorted(
        (r["n_sessions"], r["event_p99_ms_median"]) for r in rows
    )
    ceiling = None
    for i, (n, p99) in enumerate(pts):
        if p99 <= p99_budget_ms:
            ceiling = float(n)
            continue
        if ceiling is not None and i > 0:
            n0, p0 = pts[i - 1]
            if p99 > p0:  # interpolate into the over-budget segment
                frac = (p99_budget_ms - p0) / (p99 - p0)
                ceiling = round(n0 + frac * (n - n0), 1)
        break
    return ceiling


def host_plane_summary(
    rows: list[dict],
    n_runs: int,
    *,
    baseline_rows: list[dict] | None = None,
    p99_budget_ms: float | None = None,
) -> dict:
    """The one summary shape both consumers of ``host_plane_benchmark``
    publish.  The p99 budget defaults to the BASELINE's median p99 at
    its smallest measured session count (the PR-10 operating point its
    bench notes are stated at) — "equal p99" in the ceiling claim means
    both generations are judged against that same budget."""
    out = {
        "model": "host_plane_stub",
        "n_runs": int(n_runs),
        "rows": rows,
        "host_ms_per_poll": rows[-1]["host_ms_per_poll_median"],
        "contract_ok": all(r["accounting_balanced"] for r in rows),
    }
    if baseline_rows:
        if p99_budget_ms is None:
            base0 = min(baseline_rows, key=lambda r: r["n_sessions"])
            p99_budget_ms = base0["event_p99_ms_median"]
        base_ceiling = host_plane_ceiling(baseline_rows, p99_budget_ms)
        soa_ceiling = host_plane_ceiling(rows, p99_budget_ms)
        out["p99_budget_ms"] = round(float(p99_budget_ms), 3)
        out["baseline_rows"] = baseline_rows
        out["baseline_sessions_ceiling"] = base_ceiling
        out["host_sessions_ceiling"] = soa_ceiling
        out["ceiling_ratio"] = (
            round(soa_ceiling / base_ceiling, 2)
            if base_ceiling and soa_ceiling
            else None
        )
        # per-N host-time ratio at matching grid points — the
        # budget-independent view of the same claim (the p99 ceiling
        # interpolation is the headline; this is its cross-check)
        base_by_n = {
            r["n_sessions"]: r["host_ms_per_poll_median"]
            for r in baseline_rows
        }
        out["ms_per_poll_speedups"] = {
            str(r["n_sessions"]): round(
                base_by_n[r["n_sessions"]]
                / r["host_ms_per_poll_median"],
                2,
            )
            for r in rows
            if base_by_n.get(r["n_sessions"])
            and r["host_ms_per_poll_median"]
        }
    else:
        out["host_sessions_ceiling"] = (
            host_plane_ceiling(rows, p99_budget_ms)
            if p99_budget_ms is not None
            else rows[-1]["n_sessions"]
        )
    return out


def synthetic_sessions(
    n_sessions: int,
    *,
    windows_per_session: int = 2,
    window: int = 200,
    seed: int = 0,
) -> tuple[list[np.ndarray], tuple[str, ...]]:
    """Per-session ``(n_samples, 3)`` recordings cut from one seeded
    synthetic stream draw (each session = windows_per_session
    contiguous windows of one draw; sessions differ in content and in
    activity mix).  Returns (recordings, class_names)."""
    pool = synthetic_raw_stream(
        n_windows=n_sessions * windows_per_session, seed=seed,
        window=window,
    )
    recordings = [
        pool.windows[
            i * windows_per_session : (i + 1) * windows_per_session
        ].reshape(-1, 3)
        for i in range(n_sessions)
    ]
    return recordings, pool.class_names


@dataclasses.dataclass(frozen=True)
class LoadReport:
    """What the drive actually delivered (faults included)."""

    sessions: int
    samples_delivered: int
    deliveries: int
    dropped_deliveries: int
    delayed_deliveries: int
    burst_deliveries: int
    windows_enqueued: int
    duration_s: float


def drive_fleet(
    server: FleetServer,
    recordings: list[np.ndarray],
    *,
    chunk: int | None = None,
    seed: int = 0,
    faults: DeliveryFaults | None = None,
    poll_every: int = 1,
    session_ids: list | None = None,
    delivery_log: list | None = None,
    on_poll=None,
) -> tuple[list, LoadReport]:
    """Deliver every recording through the fleet engine; return
    (events, LoadReport).

    ``on_poll(server, round_index)`` — optional hook invoked after each
    scheduler poll (and once after the final flush): where a controller
    that must run from the serving loop lives — e.g. an
    ``AdaptationEngine.step`` driving drift-triggered retraining while
    the fleet serves (``har serve --adapt``).

    Delivery is round-robin over sessions in hop-sized chunks (override
    with ``chunk``), with a seeded per-session phase offset on the
    first chunk so hop boundaries stagger across the fleet.  Sessions
    must already be admitted (ids default to range(len(recordings))).
    ``poll_every`` controls how many delivery rounds pass between
    scheduler polls; the queue is flushed at the end, so at nominal
    load nothing is left pending.

    ``delivery_log`` (a list, appended with ``(session_index, payload)``
    in delivery order) records the exact post-fault chunk sequence —
    what an equivalence check replays through independent
    StreamingClassifiers, since drift EWMAs are chunk-cadence-dependent.
    """
    n = len(recordings)
    ids = list(range(n)) if session_ids is None else list(session_ids)
    if len(ids) != n:
        raise ValueError("session_ids length must match recordings")
    chunk = server.hop if chunk is None else int(chunk)
    faults = faults or DeliveryFaults()
    rng = np.random.default_rng((seed, 31337))
    # phase offsets: session i's first chunk is shorter by a seeded,
    # deterministic amount, so window completions stagger across rounds
    offsets = rng.integers(0, chunk, size=n)
    cursors = [0] * n
    held: list[list[np.ndarray]] = [[] for _ in range(n)]
    events: list = []
    delivered = deliveries = dropped_d = delayed_d = burst_d = 0
    enqueued = 0
    t0 = time.perf_counter()
    rounds = 0
    # batched ingest (the SoA host plane, har_tpu.serve.arena): the
    # whole round's deliveries go through ONE push_many call — the
    # engine vectorizes the steady-state rows and replays the rest
    # through the sequential push, with identical per-session
    # semantics either way (see FleetServer.push_many)
    push_many = getattr(server, "push_many", None)
    while True:
        active = False
        round_ids: list = []
        round_payloads: list[np.ndarray] = []
        for i in range(n):
            rec = recordings[i]
            if cursors[i] >= len(rec) and not held[i]:
                continue
            active = True
            take = chunk if cursors[i] else max(1, chunk - int(offsets[i]))
            n_chunks = 1
            if faults.burst_prob and rng.random() < faults.burst_prob:
                n_chunks = faults.burst_rounds
                burst_d += 1
            parts = list(held[i])
            held[i] = []
            for _ in range(n_chunks):
                part = rec[cursors[i] : cursors[i] + take]
                cursors[i] += take
                take = chunk  # only the first chunk carries the offset
                if not len(part):
                    break
                if faults.drop_prob and rng.random() < faults.drop_prob:
                    dropped_d += 1
                    continue
                if faults.delay_prob and rng.random() < faults.delay_prob:
                    # held in order, delivered with the next round: a
                    # catch-up burst, never a reorder
                    held[i].append(part)
                    delayed_d += 1
                    continue
                parts.append(part)
            if parts:
                payload = (
                    parts[0] if len(parts) == 1 else np.concatenate(parts)
                )
                if delivery_log is not None:
                    delivery_log.append((i, payload))
                round_ids.append(ids[i])
                round_payloads.append(payload)
                delivered += len(payload)
                deliveries += 1
        if round_ids:
            if push_many is not None:
                enqueued += push_many(round_ids, round_payloads)
            else:
                for sid, payload in zip(round_ids, round_payloads):
                    enqueued += server.push(sid, payload)
        rounds += 1
        if rounds % poll_every == 0:
            events.extend(server.poll())
            if on_poll is not None:
                on_poll(server, rounds)
        if not active:
            break
    # end of stream: anything still held was delayed past the end —
    # deliver it (the transport finally caught up), then drain
    for i in range(n):
        if held[i]:
            payload = np.concatenate(held[i])
            if delivery_log is not None:
                delivery_log.append((i, payload))
            enqueued += server.push(ids[i], payload)
            delivered += len(payload)
            deliveries += 1
            held[i] = []
    events.extend(server.flush())
    if on_poll is not None:
        on_poll(server, rounds + 1)
    report = LoadReport(
        sessions=n,
        samples_delivered=delivered,
        deliveries=deliveries,
        dropped_deliveries=dropped_d,
        delayed_deliveries=delayed_d,
        burst_deliveries=burst_d,
        windows_enqueued=enqueued,
        duration_s=round(time.perf_counter() - t0, 4),
    )
    return events, report
