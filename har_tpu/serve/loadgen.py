"""Seeded synthetic fleet load: thousands of phase-offset 20 Hz sessions.

Builds per-session recordings from the calibrated synthetic generator
family (``data/raw_windows.synthetic_raw_stream``) and drives a
``FleetServer`` with a deterministic round-robin delivery schedule:
each session delivers hop-sized chunks, phase-offset so hop boundaries
stagger across the fleet instead of all landing in the same
micro-batch slot (the realistic arrival pattern — users don't
synchronize their sensors).  Transport faults (drop / delay / burst,
``har_tpu.serve.faults.DeliveryFaults``) are applied per chunk from the
same seed.

Also home of ``AnalyticDemoModel`` — a deterministic, training-free
classifier over the synthetic stream's own class dynamics.  It is
row-independent numpy end-to-end, so its per-window outputs are
bit-identical under ANY batch composition: the property the
fleet-vs-independent equivalence test (and the release gate's SLO
smoke) pins without spending a model fit.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from har_tpu.data.raw_windows import synthetic_raw_stream
from har_tpu.serve.engine import FleetServer
from har_tpu.serve.faults import DeliveryFaults


class AnalyticDemoModel:
    """Nearest-centroid activity classifier on (per-axis mean, std).

    Centroids are computed once from a fixed-seed draw of the synthetic
    generator itself — self-calibrating to the exact class dynamics the
    load generator emits, no training step.  transform() is plain
    per-row numpy: deterministic, batch-composition-independent, and
    fast enough to score a thousand sessions' windows in microseconds —
    the engine-overhead measurement baseline (a real model adds device
    dispatch on top; this model isolates the scheduler's own cost).
    """

    def __init__(self, tau: float = 2.0):
        cal = synthetic_raw_stream(n_windows=240, seed=1729)
        feats = self._features(cal.windows)
        self.num_classes = len(cal.class_names)
        self.class_names = cal.class_names
        self._centroids = np.stack(
            [
                feats[cal.labels == c].mean(axis=0)
                for c in range(self.num_classes)
            ]
        )
        self._tau = float(tau)

    @staticmethod
    def _features(x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float64)
        return np.concatenate(
            [x.mean(axis=1), x.std(axis=1)], axis=-1
        )  # (n, 6)

    def transform(self, x):
        from har_tpu.models.base import Predictions

        f = self._features(np.asarray(x))
        d2 = ((f[:, None, :] - self._centroids[None]) ** 2).sum(-1)
        raw = -d2 / self._tau
        e = np.exp(raw - raw.max(axis=-1, keepdims=True))
        return Predictions.from_raw(
            raw, e / e.sum(axis=-1, keepdims=True)
        )


def synthetic_sessions(
    n_sessions: int,
    *,
    windows_per_session: int = 2,
    window: int = 200,
    seed: int = 0,
) -> tuple[list[np.ndarray], tuple[str, ...]]:
    """Per-session ``(n_samples, 3)`` recordings cut from one seeded
    synthetic stream draw (each session = windows_per_session
    contiguous windows of one draw; sessions differ in content and in
    activity mix).  Returns (recordings, class_names)."""
    pool = synthetic_raw_stream(
        n_windows=n_sessions * windows_per_session, seed=seed,
        window=window,
    )
    recordings = [
        pool.windows[
            i * windows_per_session : (i + 1) * windows_per_session
        ].reshape(-1, 3)
        for i in range(n_sessions)
    ]
    return recordings, pool.class_names


@dataclasses.dataclass(frozen=True)
class LoadReport:
    """What the drive actually delivered (faults included)."""

    sessions: int
    samples_delivered: int
    deliveries: int
    dropped_deliveries: int
    delayed_deliveries: int
    burst_deliveries: int
    windows_enqueued: int
    duration_s: float


def drive_fleet(
    server: FleetServer,
    recordings: list[np.ndarray],
    *,
    chunk: int | None = None,
    seed: int = 0,
    faults: DeliveryFaults | None = None,
    poll_every: int = 1,
    session_ids: list | None = None,
    delivery_log: list | None = None,
    on_poll=None,
) -> tuple[list, LoadReport]:
    """Deliver every recording through the fleet engine; return
    (events, LoadReport).

    ``on_poll(server, round_index)`` — optional hook invoked after each
    scheduler poll (and once after the final flush): where a controller
    that must run from the serving loop lives — e.g. an
    ``AdaptationEngine.step`` driving drift-triggered retraining while
    the fleet serves (``har serve --adapt``).

    Delivery is round-robin over sessions in hop-sized chunks (override
    with ``chunk``), with a seeded per-session phase offset on the
    first chunk so hop boundaries stagger across the fleet.  Sessions
    must already be admitted (ids default to range(len(recordings))).
    ``poll_every`` controls how many delivery rounds pass between
    scheduler polls; the queue is flushed at the end, so at nominal
    load nothing is left pending.

    ``delivery_log`` (a list, appended with ``(session_index, payload)``
    in delivery order) records the exact post-fault chunk sequence —
    what an equivalence check replays through independent
    StreamingClassifiers, since drift EWMAs are chunk-cadence-dependent.
    """
    n = len(recordings)
    ids = list(range(n)) if session_ids is None else list(session_ids)
    if len(ids) != n:
        raise ValueError("session_ids length must match recordings")
    chunk = server.hop if chunk is None else int(chunk)
    faults = faults or DeliveryFaults()
    rng = np.random.default_rng((seed, 31337))
    # phase offsets: session i's first chunk is shorter by a seeded,
    # deterministic amount, so window completions stagger across rounds
    offsets = rng.integers(0, chunk, size=n)
    cursors = [0] * n
    held: list[list[np.ndarray]] = [[] for _ in range(n)]
    events: list = []
    delivered = deliveries = dropped_d = delayed_d = burst_d = 0
    enqueued = 0
    t0 = time.perf_counter()
    rounds = 0
    while True:
        active = False
        for i in range(n):
            rec = recordings[i]
            if cursors[i] >= len(rec) and not held[i]:
                continue
            active = True
            take = chunk if cursors[i] else max(1, chunk - int(offsets[i]))
            n_chunks = 1
            if faults.burst_prob and rng.random() < faults.burst_prob:
                n_chunks = faults.burst_rounds
                burst_d += 1
            parts = list(held[i])
            held[i] = []
            for _ in range(n_chunks):
                part = rec[cursors[i] : cursors[i] + take]
                cursors[i] += take
                take = chunk  # only the first chunk carries the offset
                if not len(part):
                    break
                if faults.drop_prob and rng.random() < faults.drop_prob:
                    dropped_d += 1
                    continue
                if faults.delay_prob and rng.random() < faults.delay_prob:
                    # held in order, delivered with the next round: a
                    # catch-up burst, never a reorder
                    held[i].append(part)
                    delayed_d += 1
                    continue
                parts.append(part)
            if parts:
                payload = (
                    parts[0] if len(parts) == 1 else np.concatenate(parts)
                )
                if delivery_log is not None:
                    delivery_log.append((i, payload))
                enqueued += server.push(ids[i], payload)
                delivered += len(payload)
                deliveries += 1
        rounds += 1
        if rounds % poll_every == 0:
            events.extend(server.poll())
            if on_poll is not None:
                on_poll(server, rounds)
        if not active:
            break
    # end of stream: anything still held was delayed past the end —
    # deliver it (the transport finally caught up), then drain
    for i in range(n):
        if held[i]:
            payload = np.concatenate(held[i])
            if delivery_log is not None:
                delivery_log.append((i, payload))
            enqueued += server.push(ids[i], payload)
            delivered += len(payload)
            deliveries += 1
            held[i] = []
    events.extend(server.flush())
    if on_poll is not None:
        on_poll(server, rounds + 1)
    report = LoadReport(
        sessions=n,
        samples_delivered=delivered,
        deliveries=deliveries,
        dropped_deliveries=dropped_d,
        delayed_deliveries=delayed_d,
        burst_deliveries=burst_d,
        windows_enqueued=enqueued,
        duration_s=round(time.perf_counter() - t0, 4),
    )
    return events, report
