"""Fleet equivalence + SLO smoke — the release gate's serving check.

``fleet_slo_smoke()`` runs in a few seconds on the CPU mesh and proves
the two properties the fleet engine ships on:

  1. equivalence — every fleet-multiplexed session's events are
     bit-identical (latency fields excepted) to an independent
     ``StreamingClassifier`` replaying the same delivery chunks;
  2. SLO — at nominal load, zero dropped windows and the accounting
     invariant (enqueued == scored + dropped) holds.

``scripts/release_gate.py`` runs it after a green suite and stamps
``{sessions, p99_ms, dropped}`` into ``artifacts/test_gate.json`` — the
serving counterpart of the published test counts: generated from a run,
never typed.
"""

from __future__ import annotations

import numpy as np

from har_tpu.serve.engine import FleetConfig, FleetServer
from har_tpu.serve.loadgen import (
    AnalyticDemoModel,
    drive_fleet,
    synthetic_sessions,
)
from har_tpu.serving import StreamingClassifier


def events_equal(fleet_event, independent_event) -> bool:
    """Bit-identical on every decision field; latency fields excluded —
    they measure the engines, not the decisions."""
    a, b = fleet_event, independent_event
    return (
        a.t_index == b.t_index
        and a.label == b.label
        and a.raw_label == b.raw_label
        and a.drift == b.drift
        and np.array_equal(a.probability, b.probability)
    )


def fleet_slo_smoke(
    sessions: int = 128,
    *,
    windows_per_session: int = 2,
    hop: int = 200,
    smoothing: str = "ema",
    seed: int = 0,
) -> dict:
    """One JSON-ready verdict: {sessions, p99_ms, dropped, equivalent,
    windows_per_sec, ...}.  Uses the training-free AnalyticDemoModel so
    the gate spends its seconds on the scheduler, not on a model fit."""
    model = AnalyticDemoModel()
    server = FleetServer(
        model, window=200, hop=hop, smoothing=smoothing,
        config=FleetConfig(max_sessions=max(sessions, 1)),
    )
    recordings, _ = synthetic_sessions(
        sessions, windows_per_session=windows_per_session, seed=seed
    )
    for i in range(sessions):
        server.add_session(i)
    log: list = []
    events, report = drive_fleet(
        server, recordings, seed=seed, delivery_log=log
    )

    # replay the exact delivered chunks through independent classifiers
    per_session_events: dict[int, list] = {i: [] for i in range(sessions)}
    for ev in events:
        per_session_events[ev.session_id].append(ev.event)
    equivalent = True
    independent = {
        i: StreamingClassifier(
            model, window=200, hop=hop, smoothing=smoothing
        )
        for i in range(sessions)
    }
    ref_events: dict[int, list] = {i: [] for i in range(sessions)}
    for i, payload in log:
        ref_events[i].extend(independent[i].push(payload))
    for i in range(sessions):
        got, want = per_session_events[i], ref_events[i]
        if len(got) != len(want) or not all(
            events_equal(g, w) for g, w in zip(got, want)
        ):
            equivalent = False
            break

    snap = server.stats_snapshot()
    p99 = snap["stages"]["event_ms"].get("p99_ms")
    return {
        "sessions": sessions,
        "windows": snap["accounting"]["enqueued"],
        "p99_ms": p99,
        "p50_ms": snap["stages"]["event_ms"].get("p50_ms"),
        "dropped": snap["accounting"]["dropped"],
        "equivalent": equivalent,
        "accounting_balanced": (
            snap["accounting"]["balanced"]
            and snap["accounting"]["pending"] == 0
        ),
        "windows_per_sec": (
            round(snap["accounting"]["scored"] / report.duration_s, 1)
            if report.duration_s
            else None
        ),
        "ok": bool(
            equivalent
            and snap["accounting"]["dropped"] == 0
            and snap["accounting"]["pending"] == 0
        ),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(fleet_slo_smoke()))
