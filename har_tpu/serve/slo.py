"""Fleet equivalence + SLO smoke — the release gate's serving check.

``fleet_slo_smoke()`` runs in a few seconds on the CPU mesh and proves
the two properties the fleet engine ships on:

  1. equivalence — every fleet-multiplexed session's events are
     bit-identical (latency fields excepted) to an independent
     ``StreamingClassifier`` replaying the same delivery chunks;
  2. SLO — at nominal load, zero dropped windows and the accounting
     invariant (enqueued == scored + dropped) holds.

``scripts/release_gate.py`` runs it after a green suite and stamps
``{sessions, p99_ms, dropped}`` into ``artifacts/test_gate.json`` — the
serving counterpart of the published test counts: generated from a run,
never typed.
"""

from __future__ import annotations

import numpy as np

from har_tpu.serve.engine import FleetConfig, FleetServer
from har_tpu.serve.loadgen import (
    AnalyticDemoModel,
    drive_fleet,
    synthetic_sessions,
)
from har_tpu.serving import StreamingClassifier


def events_equal(fleet_event, independent_event) -> bool:
    """Bit-identical on every decision field; latency fields excluded —
    they measure the engines, not the decisions."""
    a, b = fleet_event, independent_event
    return (
        a.t_index == b.t_index
        and a.label == b.label
        and a.raw_label == b.raw_label
        and a.drift == b.drift
        and np.array_equal(a.probability, b.probability)
    )


def fleet_slo_smoke(
    sessions: int = 128,
    *,
    windows_per_session: int = 2,
    hop: int = 200,
    smoothing: str = "ema",
    seed: int = 0,
) -> dict:
    """One JSON-ready verdict: {sessions, p99_ms, dropped, equivalent,
    windows_per_sec, ...}.  Uses the training-free AnalyticDemoModel so
    the gate spends its seconds on the scheduler, not on a model fit."""
    model = AnalyticDemoModel()
    server = FleetServer(
        model, window=200, hop=hop, smoothing=smoothing,
        config=FleetConfig(max_sessions=max(sessions, 1)),
    )
    recordings, _ = synthetic_sessions(
        sessions, windows_per_session=windows_per_session, seed=seed
    )
    for i in range(sessions):
        server.add_session(i)
    log: list = []
    events, report = drive_fleet(
        server, recordings, seed=seed, delivery_log=log
    )

    # replay the exact delivered chunks through independent classifiers
    per_session_events: dict[int, list] = {i: [] for i in range(sessions)}
    for ev in events:
        per_session_events[ev.session_id].append(ev.event)
    equivalent = True
    independent = {
        i: StreamingClassifier(
            model, window=200, hop=hop, smoothing=smoothing
        )
        for i in range(sessions)
    }
    ref_events: dict[int, list] = {i: [] for i in range(sessions)}
    for i, payload in log:
        ref_events[i].extend(independent[i].push(payload))
    for i in range(sessions):
        got, want = per_session_events[i], ref_events[i]
        if len(got) != len(want) or not all(
            events_equal(g, w) for g, w in zip(got, want)
        ):
            equivalent = False
            break

    snap = server.stats_snapshot()
    p99 = snap["stages"]["event_ms"].get("p99_ms")
    return {
        "sessions": sessions,
        "windows": snap["accounting"]["enqueued"],
        "p99_ms": p99,
        "p50_ms": snap["stages"]["event_ms"].get("p50_ms"),
        "dropped": snap["accounting"]["dropped"],
        "equivalent": equivalent,
        "accounting_balanced": (
            snap["accounting"]["balanced"]
            and snap["accounting"]["pending"] == 0
        ),
        "windows_per_sec": (
            round(snap["accounting"]["scored"] / report.duration_s, 1)
            if report.duration_s
            else None
        ),
        "ok": bool(
            equivalent
            and snap["accounting"]["dropped"] == 0
            and snap["accounting"]["pending"] == 0
        ),
    }


def fleet_pipeline_smoke(
    sessions: int = 64,
    *,
    windows_per_session: int = 2,
    target_batch: int = 32,
    pipeline_depth: int = 3,
    max_devices: int = 8,
    tunnel_rtt_ms: float = 5.0,
    fused: bool = True,
    seed: int = 0,
) -> dict:
    """The release gate's pipelined-dispatch check: the SAME load run
    once synchronous (depth 1, single device, unfused — the PR-2/5
    reference) and once through the full hot path (depth-3 ticket
    ring, batch-sharded over the dry-run mesh when >1 device is
    visible, FUSED device program), with the decision streams compared
    per session.

    Verdict contract:
      - every session's (t_index, label, raw_label, drift) sequence is
        IDENTICAL across the two runs, and the decision CONFIDENCE
        (probability[label]) matches to 1e-6.  Smoothing is "none"
        (passthrough — fused-eligible) PRECISELY so this check has
        teeth: the unfused event carries the model's true probability
        at the label while the fused event carries the device's
        fetched top-prob, so a fused program returning wrong
        confidences fails the gate (under vote smoothing both sides
        would be label-derived and the comparison vacuous).  Off-label
        probabilities are the documented compact surrogate — full-
        vector equality is the unfused tier's contract, not this
        one's;
      - zero dropped windows and a balanced conservation law in both;
      - the pipelined run actually pipelined (overlap_pct measured)
        and actually fused (every dispatch through the fused program,
        fetch bytes saved > 0 — stamped per window into the gate log).

    Uses ``JitDemoModel`` (jitted, training-free) with a small emulated
    tunnel RTT so the overlap is observable on hosts whose local
    device finishes in microseconds — the gate measures the ENGINE's
    overlap machinery, not this host's chip.
    """
    import jax

    from har_tpu.parallel.mesh import create_mesh
    from har_tpu.serve.loadgen import JitDemoModel

    n_dev = min(int(max_devices), len(jax.devices()))
    mesh = create_mesh(dp=n_dev, tp=1) if n_dev > 1 else None
    model = JitDemoModel(tunnel_rtt_ms=tunnel_rtt_ms)
    recordings, _ = synthetic_sessions(
        sessions, windows_per_session=windows_per_session, seed=seed
    )

    def one_run(depth, run_mesh, run_fused):
        server = FleetServer(
            model, window=200, hop=200, smoothing="none",
            config=FleetConfig(
                max_sessions=sessions,
                target_batch=target_batch,
                pipeline_depth=depth,
                fused=run_fused,
            ),
            mesh=run_mesh,
        )
        for i in range(sessions):
            server.add_session(i)
        events, report = drive_fleet(server, recordings, seed=seed)
        by_sid: dict[int, list] = {i: [] for i in range(sessions)}
        for ev in events:
            by_sid[ev.session_id].append(ev.event)
        return server, report, by_sid

    s1, r1, ref = one_run(1, None, False)
    s2, r2, got = one_run(pipeline_depth, mesh, fused)

    equivalent = True
    for i in range(sessions):
        a, b = ref[i], got[i]
        if len(a) != len(b) or not all(
            x.t_index == y.t_index
            and x.label == y.label
            and x.raw_label == y.raw_label
            and x.drift == y.drift
            and abs(
                x.probability[x.label] - y.probability[y.label]
            ) <= 1e-6
            for x, y in zip(a, b)
        ):
            equivalent = False
            break

    snap1, snap2 = s1.stats_snapshot(), s2.stats_snapshot()
    clean = all(
        s["accounting"]["dropped"] == 0
        and s["accounting"]["pending"] == 0
        and s["accounting"]["balanced"]
        for s in (snap1, snap2)
    )
    overlap = snap2["overlap_pct"]
    fused_ok = (not fused) or (
        snap2["fused_dispatches"] == snap2["dispatches"] > 0
        and snap2["fetch_bytes_saved"] > 0
    )
    scored = snap2["accounting"]["scored"]
    wps1 = (
        round(snap1["accounting"]["scored"] / r1.duration_s, 1)
        if r1.duration_s
        else None
    )
    wps2 = (
        round(scored / r2.duration_s, 1) if r2.duration_s else None
    )
    return {
        "sessions": sessions,
        "devices": 1 if mesh is None else n_dev,
        "pipeline_depth": pipeline_depth,
        "depth": pipeline_depth,
        "fused": bool(fused),
        "fused_dispatches": snap2["fused_dispatches"],
        "fetch_bytes_per_window": (
            round(snap2["fetch_bytes"] / scored, 1) if scored else None
        ),
        "fetch_bytes_saved": snap2["fetch_bytes_saved"],
        "overlap_pct": overlap,
        "p99_ms": snap2["stages"]["event_ms"].get("p99_ms"),
        "dropped": snap2["accounting"]["dropped"],
        "dispatch_backend": snap2["dispatch_backend"],
        "windows_per_sec_depth1": wps1,
        "windows_per_sec": wps2,
        "equivalent": equivalent,
        "ok": bool(
            equivalent and clean and overlap is not None and fused_ok
        ),
    }


def model_parallel_smoke(
    sessions: int = 48,
    *,
    windows_per_session: int = 2,
    target_batch: int = 16,
    pipeline_depth: int = 2,
    dp: int = 2,
    tp: int = 4,
    seed: int = 11,
) -> dict:
    """The release gate's model-parallel check: the SAME load run once
    on a single device and once on the 2D ``dp × tp`` (batch × model)
    dry-run mesh through a ``ModelParallelScorer`` — params placed ONCE
    via the partition-rule table, then served behind the ordinary
    ticket ring.

    Verdict contract:
      - every session's (t_index, label, raw_label, drift) sequence is
        identical across the two runs and the probability vectors match
        to 1e-6 (the GSPMD re-tiling tolerance — this is the unfused
        tier, so the FULL vector is compared, not the label surrogate);
      - the mesh run really is model-parallel: scorer kind
        ``model_parallel``, ``model_axis_shards == tp``, and
        ``params_bytes()["per_device"]`` STRICTLY below the
        single-device scorer's total — the one property that makes a
        bigger-than-one-chip model servable at all;
      - zero dropped windows and balanced accounting in both runs.

    Stamped as ``{sessions, mesh, model_axis_shards,
    params_bytes_per_device, p99_ms, ...}`` in the gate log; the
    release gate forces ``--xla_force_host_platform_device_count=8`` so
    the 2×4 placement is proven on every host.
    """
    import jax

    from har_tpu.parallel.mesh import create_mesh
    from har_tpu.serve.dispatch import ModelParallelScorer
    from har_tpu.serve.loadgen import JitDemoModel

    need = dp * tp
    if len(jax.devices()) < need:
        return {
            "ok": False,
            "error": (
                f"{len(jax.devices())} devices visible, {need} needed "
                "— run under --xla_force_host_platform_device_count"
            ),
        }
    mesh = create_mesh(dp=dp, tp=tp, devices=jax.devices()[:need])
    model = JitDemoModel()
    recordings, _ = synthetic_sessions(
        sessions, windows_per_session=windows_per_session, seed=seed
    )

    def one_run(run_mesh):
        server = FleetServer(
            model, window=200, hop=200, smoothing="ema",
            config=FleetConfig(
                max_sessions=sessions,
                target_batch=target_batch,
                pipeline_depth=pipeline_depth if run_mesh else 1,
            ),
            mesh=run_mesh,
        )
        for i in range(sessions):
            server.add_session(i)
        events, report = drive_fleet(server, recordings, seed=seed)
        by_sid: dict[int, list] = {i: [] for i in range(sessions)}
        for ev in events:
            by_sid[ev.session_id].append(ev.event)
        return server, report, by_sid

    s1, r1, ref = one_run(None)
    s2, r2, got = one_run(mesh)

    equivalent = True
    for i in range(sessions):
        a, b = ref[i], got[i]
        if len(a) != len(b) or not all(
            x.t_index == y.t_index
            and x.label == y.label
            and x.raw_label == y.raw_label
            and x.drift == y.drift
            and np.allclose(x.probability, y.probability, atol=1e-6)
            for x, y in zip(a, b)
        ):
            equivalent = False
            break

    snap1, snap2 = s1.stats_snapshot(), s2.stats_snapshot()
    clean = all(
        s["accounting"]["dropped"] == 0
        and s["accounting"]["pending"] == 0
        and s["accounting"]["balanced"]
        for s in (snap1, snap2)
    )
    placed = isinstance(s2.scorer, ModelParallelScorer)
    shards = s2.scorer.model_axis_shards
    single_bytes = s1.scorer.params_bytes()
    placed_bytes = s2.scorer.params_bytes()
    fits = placed_bytes["per_device"] < single_bytes["total"]
    scored = snap2["accounting"]["scored"]
    return {
        "sessions": sessions,
        "mesh": f"{dp}x{tp}",
        "model_axis_shards": shards,
        "batch_shards": s2.scorer.devices,
        "params_bytes_single": single_bytes["total"],
        "params_bytes_per_device": placed_bytes["per_device"],
        "p99_ms": snap2["stages"]["event_ms"].get("p99_ms"),
        "dropped": snap2["accounting"]["dropped"],
        "windows_per_sec": (
            round(scored / r2.duration_s, 1) if r2.duration_s else None
        ),
        "equivalent": equivalent,
        "ok": bool(
            equivalent
            and clean
            and placed
            and shards == tp
            and fits
        ),
    }


def host_plane_smoke(
    sessions: int = 256, *, check_sessions: int = 64, seed: int = 5
) -> dict:
    """The release gate's host-plane check (PR 12 SoA session estate +
    PR 14 SoA pending queue): three halves, one verdict —

      1. equivalence: the BATCHED ingest path (``push_many`` over the
         session arena, mid-chunk boundaries included) must produce
         per-session event streams bit-identical to the sequential
         ``push`` path at N=64 — phase-staggered 20 Hz chunks, so
         windows complete mid-chunk (the production shape);
      2. pending-queue identity under pressure: the SAME comparison
         with TIGHT queue bounds, so the shed-stalest walk, the
         per-session bound and the FIFO pop all fire constantly — the
         batched and sequential cadences must shed the SAME windows
         and emit bit-identical surviving streams (the per-object
         queue's semantics, re-proven against the slot-indexed
         ``PendingArena`` every gate run), with the conservation law
         balanced and every drop attributed;
      3. capacity: one small ``host_plane_benchmark`` point stamps
         ``{sessions, host_ms_per_poll, p99_ms}`` — plus the PR-14
         footprint gauges (``arena_bytes``/``staging_bytes``/
         ``pending_bytes``) and ``pending_soa: true`` — into the gate
         log: the regression trace the sessions-per-worker ceiling
         curve (artifacts/host_plane_scaling.json) is read against.
    """
    import numpy as np

    from har_tpu.serve.loadgen import (
        HostPlaneStubModel,
        host_plane_benchmark,
        host_plane_rounds,
    )

    model = HostPlaneStubModel()
    window, hop, n = 100, 20, int(check_sessions)
    rng = np.random.default_rng((seed, 0xFACE))
    recs = [
        rng.normal(size=(window + hop * 12, 3)).astype(np.float32)
        for _ in range(n)
    ]
    # THE shared phase-staggered schedule (one builder with the
    # benchmark, so this check exercises the measured cadence)
    rounds = host_plane_rounds(
        recs, hop, rng.integers(0, hop, size=n)
    )

    def one_run(batched: bool, config: FleetConfig, poll_every: int = 1):
        server = FleetServer(
            model, window=window, hop=hop, smoothing="ema",
            config=config,
        )
        for i in range(n):
            server.add_session(i)
        by_sid: dict[int, list] = {i: [] for i in range(n)}
        for r, (ids, chunks) in enumerate(rounds):
            if batched:
                server.push_many(ids, chunks)
            else:
                for sid, part in zip(ids, chunks):
                    server.push(sid, part)
            if (r + 1) % poll_every == 0:
                for fe in server.poll(force=True):
                    by_sid[fe.session_id].append(fe.event)
        for fe in server.flush():
            by_sid[fe.session_id].append(fe.event)
        return server, by_sid

    def streams_equal(seq, bat):
        return all(
            len(seq[i]) == len(bat[i])
            and all(events_equal(a, b) for a, b in zip(seq[i], bat[i]))
            for i in range(n)
        ) and any(len(seq[i]) for i in range(n))

    nominal = FleetConfig(max_sessions=n)
    _, seq = one_run(False, nominal)
    server, bat = one_run(True, nominal)
    equivalent = streams_equal(seq, bat)
    acct = server.stats.accounting()

    # pending-queue identity under pressure: tight bounds make every
    # queue mechanism fire (per-session shed, global shed-stalest,
    # non-full batches); both cadences must agree window for window
    # polls every 5th round so the backlog builds past both bounds
    pressure = FleetConfig(
        max_sessions=n, target_batch=16,
        max_pending_per_session=3, max_queue_windows=48,
    )
    ps, pseq = one_run(False, pressure, poll_every=5)
    pb, pbat = one_run(True, pressure, poll_every=5)
    pending_equivalent = streams_equal(pseq, pbat)
    p_acct = pb.stats.accounting()
    pending_ok = bool(
        pending_equivalent
        and pb.stats.dropped_total > 0  # pressure actually fired
        and pb.stats.dropped == ps.stats.dropped  # same sheds, by reason
        and p_acct["balanced"]
        and p_acct["pending"] == 0
    )

    row = host_plane_benchmark([int(sessions)], n_runs=2)[0]
    return {
        "sessions": int(sessions),
        "host_ms_per_poll": row["host_ms_per_poll_median"],
        "p99_ms": row["event_p99_ms_median"],
        "windows_per_sec": row["windows_per_sec_median"],
        "batched_equivalent": equivalent,
        "pending_soa": True,
        "pending_equivalent": pending_equivalent,
        "pressure_dropped": pb.stats.dropped_total,
        "arena_bytes": row["arena_bytes"],
        "staging_bytes": row["staging_bytes"],
        "pending_bytes": row["pending_bytes"],
        "ok": bool(
            equivalent
            and pending_ok
            and acct["balanced"]
            and acct["pending"] == 0
            and row["accounting_balanced"]
        ),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(fleet_slo_smoke()))
