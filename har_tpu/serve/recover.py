"""Crash recovery for a journaled FleetServer: load the newest
snapshot, replay the journal suffix, resume serving.

Recovery is deliberately boring: it re-executes the SAME code paths the
live engine ran, with journaling suppressed —

  - ``push`` records feed the exact pre-crash sample rows back through
    the shared ``_WindowAssembler``, so ring buffers, window
    completions, monitor EWMAs and drift verdicts recover
    bit-identically by construction (the PR-2 equivalence argument,
    reused as a durability argument);
  - ``ack`` records consume the completed window they scored and
    re-step the smoother with the recorded probabilities — the event is
    NOT re-emitted (its consumer already saw it: acks are flushed
    before ``poll`` returns), so nothing is ever double-scored or
    double-counted;
  - ``drop`` records re-apply dispatch-time sheds (dispatch failures,
    SLO sheds) that replay could not re-derive; push-time sheds
    (session/global queue bounds) re-derive deterministically from the
    record stream and are therefore not journaled at all;
  - whatever remains un-acked and un-dropped is exactly the pre-crash
    pending queue, re-enqueued in the original global FIFO order and
    scored after restart — with a deterministic model, bit-identically
    to the uninterrupted run.

What recovery canNOT conjure is data that never reached the disk: the
tail of pushes inside the last flush interval.  The transport closes
that gap by re-delivering from ``FleetServer.watermark(sid)`` (lossless
recovery, the chaos harness's default) or declares the gap via
``FleetServer.declare_lost`` — which extends the conservation law to
``enqueued == scored + dropped + pending + lost_in_crash``.
"""

from __future__ import annotations

import dataclasses
import os
import zlib
from collections import deque
from typing import Callable

import numpy as np

from har_tpu.serve.journal import (
    FleetJournal,
    JournalConfig,
    JournalError,
    load_journal,
    monitor_from_state,
)


class RecoveryError(RuntimeError):
    """Journal contents inconsistent with the engine's invariants (an
    ack for a window replay never completed, a record for an unknown
    session) — corruption, not a normal crash signature."""


# Record types whose WRITER has been superseded but whose journals are
# still in the field.  Per-event `ack` records were replaced by the
# group-committed `acks` record (one batched write per retire); the
# `ack` handler below stays forever — old journals never migrate, and a
# mixed log (old `ack` + new `acks`) restores through both handlers in
# record order.  HL003 pins this declaration both ways: a retired type
# must keep its handler, and a type with a live writer must not hide
# here.
RETIRED_RECORD_TYPES = ("ack",)


def _oldest_live(server, sess):
    """The session's oldest live pending index, discarding (and
    releasing the session-list reference of) flagged-dropped heads —
    the SoA pending queue's replay-side head walk (the entries' queue-
    side references stay in the FIFO ring, which skips them at the
    next poll exactly like the live engine)."""
    pq = server._pending
    arena = server._session_arena
    slot = sess.slot
    h = arena.pend_head[slot]
    while h >= 0 and pq.dropped[h]:
        nxt = pq.next_idx[h]
        arena.pend_head[slot] = nxt
        if nxt < 0:
            arena.pend_tail[slot] = -1
        pq.release(h)
        h = nxt
    return int(h) if h >= 0 else None


def _consume_ack(server, sess, ti, ver, shed, probs):
    pq = server._pending
    p = _oldest_live(server, sess)
    if p is None or pq.t_index[p] != ti:
        raise RecoveryError(
            f"ack for session {sess.sid!r} t_index={ti} does not match "
            f"the oldest recovered window "
            f"({None if p is None else int(pq.t_index[p])}) — a window "
            "would be double-scored; refusing to recover from this "
            "journal"
        )
    server._session_pop_head(sess)
    # consumed: hide it from the global FIFO and free its arena slot
    server._release_pending(p)
    sess.n_scored += 1
    server.stats.note_scored(1, ver)
    if shed:
        server.stats.degraded_events += 1
    else:
        # re-step the smoother with the recorded decision inputs: the
        # post-recovery smoothing state equals the pre-crash one, so
        # the NEXT event continues the stream seamlessly
        sess.smoother.step(probs)


def _consume_drop(server, sess, ti, reason):
    pq = server._pending
    h = server._session_arena.pend_head[sess.slot]
    while h >= 0:
        if not pq.dropped[h] and pq.t_index[h] == ti:
            # flagged in place (list position kept for the FIFO
            # unlink), exactly like the live engine's sheds
            server._release_pending(int(h))
            sess.n_dropped += 1
            server.stats.drop(1, reason)
            return
        h = pq.next_idx[h]
    raise RecoveryError(
        f"drop record for session {sess.sid!r} t_index={ti} matches no "
        "recovered window"
    )


def apply_record(server, meta, payload) -> None:
    """Apply ONE journal record to a replaying server — the record
    dispatch shared by crash recovery (``restore_server``'s suffix
    replay) and continuous replication (``har_tpu.serve.replica``'s
    warm standby, which feeds tailed records through this exact body
    as they arrive).  The caller owns the ``server._replaying`` guard;
    this function only interprets records.  Unknown record types are
    skipped: a newer writer's extra records must not brick an older
    reader (harlint HL003 pins the writer↔handler bijection)."""
    channels = server.channels
    t = meta.get("t")
    if t == "push":
        n = int(meta["n"])
        samples = np.frombuffer(payload, np.float32).reshape(
            n, channels
        )
        server.push(meta["sid"], samples)
        # the record's samples are post-guard: re-align the raw
        # transport watermark with the rows the guard rejected
        rejected = int(meta.get("rn", n)) - n
        if rejected:
            server._sessions[meta["sid"]].raw_seen += rejected
            server.stats.rejected_samples += rejected
    elif t == "ack":
        sess = server._sessions.get(meta["sid"])
        if sess is None:
            raise RecoveryError(
                f"ack for unknown session {meta['sid']!r}"
            )
        _consume_ack(
            server, sess, int(meta["ti"]), meta.get("ver", "v0"),
            bool(meta.get("shed")),
            np.frombuffer(payload, np.float64),
        )
    elif t == "acks":
        # group-committed acks (one record per retire): the
        # entries ride in the retire loop's emit order, so
        # replaying them through the same per-event
        # _consume_ack sequence re-steps each smoother
        # bit-identically to a per-record `ack` log.  The
        # per-record handler above stays — old and mixed logs
        # replay without migration.  Each entry's t_index is
        # NOT stored (the push records already determine it:
        # it's the session's oldest live pending); the record
        # carries one crc32 over the expected int64 column
        # ("tic") so a journal that diverged from the engine's
        # ack order still refuses to recover, at 4 bytes per
        # RECORD instead of 8 per entry.
        n = int(meta["n"])
        ver = meta.get("ver", "v0")
        a_shed = bool(meta.get("shed"))
        rows = np.frombuffer(payload, np.float64).reshape(n, -1)
        pq = server._pending
        tis = np.empty(n, np.int64)
        for j, (sid, row) in enumerate(
            zip(meta["sids"], rows)
        ):
            sess = server._sessions.get(sid)
            if sess is None:
                raise RecoveryError(
                    f"ack for unknown session {sid!r}"
                )
            p = _oldest_live(server, sess)
            if p is None:
                raise RecoveryError(
                    f"ack for session {sid!r} but no window "
                    "was recovered pending — a window would "
                    "be double-scored; refusing to recover "
                    "from this journal"
                )
            tis[j] = int(pq.t_index[p])
            _consume_ack(
                server, sess, int(tis[j]), ver, a_shed, row
            )
        crc = zlib.crc32(tis.tobytes()) & 0xFFFFFFFF
        if int(meta.get("tic", crc)) != crc:
            raise RecoveryError(
                "acks record t_index checksum mismatch "
                f"(recorded {meta['tic']}, replayed {crc}) — "
                "the journal's ack order diverged from the "
                "recovered pending queue; refusing to recover"
            )
    elif t == "drop":
        sess = server._sessions.get(meta["sid"])
        if sess is None:
            raise RecoveryError(
                f"drop for unknown session {meta['sid']!r}"
            )
        _consume_drop(
            server, sess, int(meta["ti"]), meta.get("reason", "?")
        )
    elif t == "add":
        server.add_session(
            meta["sid"],
            monitor=monitor_from_state(meta.get("mon")),
        )
    elif t == "remove":
        server.remove_session(meta["sid"])
    elif t == "swap":
        server.model_version = meta["ver"]
        server.stats.model_swaps += 1
        server._device_ms.clear()
    elif t == "resize":
        # elastic capacity resize (FleetServer.resize): the
        # schedule knobs replay exactly; the mesh OBJECT is a
        # runtime resource — recovery shards onto whatever mesh
        # restore_server was given, same stance as the model
        server.config = dataclasses.replace(
            server.config,
            target_batch=int(meta["tb"]),
            pipeline_depth=int(meta["depth"]),
        )
        server.stats.resizes += 1
        if int(meta.get("dir", 0)) > 0:
            server.stats.scale_ups += 1
        elif int(meta.get("dir", 0)) < 0:
            server.stats.scale_downs += 1
    elif t == "disc":
        # graceful disconnect, flush half: re-derive the final
        # partial window from the recovered ring — bit-identical
        # by construction (same _flush_partial, same ring); the
        # following ack then consumes it like any other window
        sess = server._sessions.get(meta["sid"])
        if sess is None:
            raise RecoveryError(
                f"disc record for unknown session {meta['sid']!r}"
            )
        server._flush_partial(sess)
    elif t == "shed":
        on = bool(meta.get("on"))
        if on and not server._smoothing_shed:
            server.stats.smoothing_shed_transitions += 1
        server._smoothing_shed = on
    elif t == "adopt":
        # cluster hand-off, receiving half: rebuild the migrated
        # session from the record's full state payload (ring
        # float32, then the EMA float64 when meta["ema"]) —
        # the same adopt_session path the live migration ran.
        # The stored `handoffs` already counts this adoption;
        # adopt_session re-bumps, so hand it the predecessor's.
        window = server.window
        ring_bytes = window * channels * 4
        ema = None
        if meta.get("ema"):
            ema = np.frombuffer(payload[ring_bytes:], np.float64)
        server.adopt_session(
            {
                "sid": meta["sid"],
                "ring": np.frombuffer(
                    payload[:ring_bytes], np.float32
                ).reshape(window, channels),
                "n_seen": meta["n_seen"],
                "raw_seen": meta["raw_seen"],
                "next_emit": meta["next_emit"],
                "n_enqueued": meta.get("n_enqueued", 0),
                "n_scored": meta.get("n_scored", 0),
                "n_dropped": meta.get("n_dropped", 0),
                "handoffs": int(meta.get("handoffs", 1)) - 1,
                "votes": meta.get("votes") or [],
                "ema": ema,
                "monitor": meta.get("mon"),
            }
        )
    elif t == "handoff":
        # cluster hand-off, source half: the session moved to
        # another worker — evict without dropping (the drain
        # guarantee re-derives: replay reaches this record with
        # the session's queue empty, or the journal is corrupt)
        if meta["sid"] not in server._sessions:
            raise RecoveryError(
                f"handoff record for unknown session "
                f"{meta['sid']!r}"
            )
        server._apply_handoff(meta["sid"])
    elif t == "lost":
        server.declare_lost(meta["sid"], int(meta["pos"]))
    elif t == "adapt":
        server.recovered_adapt_records.append(meta)
    # unknown record types are skipped: a newer writer's extra
    # records must not brick an older reader


def restore_server(
    journal_dir: str,
    model,
    *,
    clock: Callable[[], float] | None = None,
    fault_hook: Callable | None = None,
    journal_config: JournalConfig | None = None,
    reattach: bool = True,
    mesh=None,
    inflight_ship_ok: bool = False,
):
    """Rebuild a FleetServer from its journal directory.

    ``model`` is either a model object (served as-is under the
    recovered version label) or a callable ``version_label -> model``
    (resolved AFTER replay, so a crash mid-swap serves whichever
    version the journal proves durable — typically a loader over the
    adapt ModelRegistry).

    The restored server has ``stats.recoveries`` incremented, the full
    pre-crash pending queue re-enqueued, and (with ``reattach``) a
    fresh journal attached with a recovery-point snapshot — so crashes
    compose: a second kill recovers from the first recovery.

    ``mesh`` — optional device mesh for the recovered server's dispatch
    plane (runtime resource, never journaled: the process that died may
    have run on different hardware than the one recovering).
    ``pipeline_depth`` rides the snapshot's FleetConfig; in-flight
    tickets are NOT part of any snapshot — a ticket in flight at crash
    time was un-acked by construction, so its windows recover as
    pending from the replayed pushes and are simply re-scored.
    """
    from har_tpu.serve.engine import FleetConfig, FleetServer

    state, arrays, records = load_journal(
        journal_dir, inflight_ship_ok=inflight_ship_ok
    )
    geo = state.get("geometry")
    if not geo:
        raise JournalError("snapshot lacks the geometry block")
    cfg_fields = {f.name for f in dataclasses.fields(FleetConfig)}
    config = FleetConfig(
        **{
            k: v
            for k, v in (state.get("config") or {}).items()
            if k in cfg_fields
        }
    )
    server = FleetServer(
        None,  # resolved after replay (mid-swap crashes change it)
        window=geo["window"],
        hop=geo["hop"],
        channels=geo["channels"],
        smoothing=geo["smoothing"],
        ema_alpha=geo["ema_alpha"],
        vote_depth=geo["vote_depth"],
        class_names=geo.get("class_names"),
        config=config,
        fault_hook=fault_hook,
        clock=clock,
        model_version=geo.get("model_version", "v0"),
        mesh=mesh,
    )
    server._replaying = True
    try:
        # ---- snapshot: per-session state -------------------------------
        ladder = state.get("ladder") or {}
        server._smoothing_shed = bool(ladder.get("smoothing_shed", False))
        server._breaches = int(ladder.get("breaches", 0))
        server._ok_streak = int(ladder.get("ok_streak", 0))
        server.stats.load_state(state.get("stats") or {})
        now = server._clock()
        sess_list = state.get("sessions") or []
        for i, s in enumerate(sess_list):
            server.add_session(
                s["sid"], monitor=monitor_from_state(s.get("monitor"))
            )
            sess = server._sessions[s["sid"]]
            asm = sess.asm
            ring = arrays.get(f"ring{i}")
            if ring is not None:
                asm._ring[:] = ring
            asm._n_seen = int(s["n_seen"])
            sess.raw_seen = int(s.get("raw_seen", s["n_seen"]))
            asm._next_emit = int(s["next_emit"])
            sess.n_enqueued = int(s.get("n_enqueued", 0))
            sess.n_scored = int(s.get("n_scored", 0))
            sess.n_dropped = int(s.get("n_dropped", 0))
            # pre-cluster snapshots have no hand-off generation
            sess.handoffs = int(s.get("handoffs", 0))
            ema = arrays.get(f"ema{i}")
            if ema is not None:
                sess.smoother._ema = np.asarray(ema, np.float64)
            votes = s.get("votes") or []
            sess.smoother._votes = deque(
                (int(v) for v in votes), maxlen=geo["vote_depth"]
            )
        # ---- snapshot: the live queue, original FIFO order -------------
        # (re-staged into the arena; pre-arena snapshots carry the same
        # stacked ``pending`` array, so both generations restore here)
        pend_windows = arrays.get("pending")
        for j, (sidx, ti, drift) in enumerate(state.get("pending") or []):
            sess = server._sessions[sess_list[sidx]["sid"]]
            server._restore_pending(
                sess, int(ti),
                np.asarray(pend_windows[j], np.float32), bool(drift), now,
            )
        server.recovered_extra = state.get("extra") or {}
        server.recovered_adapt_records = []

        # ---- replay the journal suffix ---------------------------------
        for meta, payload in records:
            apply_record(server, meta, payload)
    finally:
        server._replaying = False

    server.model = model(server.model_version) if callable(model) else model
    server.stats.recoveries += 1
    server.stats.note_queue_depth(server._n_live)
    if reattach:
        server.attach_journal(
            FleetJournal(journal_dir, journal_config),
            snapshot=True,
            require_fresh=False,  # this IS the resume path
        )
    return server


def recovery_benchmark(
    session_counts,
    n_runs: int = 3,
    *,
    windows_per_session: int = 2,
    seed: int = 13,
    flush_every: int = 64,
) -> list[dict]:
    """THE recovery-time measurement shared by bench.py's
    ``fleet_recovery`` lane and ``scripts/recovery_bench.py`` (the
    committed-artifact path): per session count, drive a journaled
    fleet under live load, kill it (``FleetJournal.kill`` drops the
    un-flushed buffer — the SIGKILL model), and time
    ``FleetServer.restore``; ``contract_ok`` pins the accounting
    invariant across every measured recovery.  One implementation so
    the lane and the artifact cannot silently diverge."""
    import shutil
    import tempfile
    import time

    from har_tpu.serve.engine import FleetConfig, FleetServer
    from har_tpu.serve.journal import FleetJournal, JournalConfig
    from har_tpu.serve.loadgen import (
        AnalyticDemoModel,
        drive_fleet,
        synthetic_sessions,
    )

    model = AnalyticDemoModel()
    rows = []
    for n_sessions in session_counts:
        recordings, _ = synthetic_sessions(
            n_sessions, windows_per_session=windows_per_session, seed=seed
        )
        times, journal_mb, ok = [], 0.0, True
        for _ in range(int(n_runs)):
            root = tempfile.mkdtemp(prefix="har_recovery_bench_")
            try:
                server = FleetServer(
                    model, window=200, hop=200, smoothing="ema",
                    config=FleetConfig(max_sessions=n_sessions),
                    journal=FleetJournal(
                        root,
                        JournalConfig(
                            flush_every=flush_every, snapshot_every=0
                        ),
                    ),
                )
                for i in range(n_sessions):
                    server.add_session(i)
                drive_fleet(server, recordings, seed=seed)
                expected = server.stats.scored
                journal_mb = round(
                    sum(
                        os.path.getsize(os.path.join(dirpath, f))
                        for dirpath, _, files in os.walk(root)
                        for f in files
                    )
                    / 1e6,
                    3,
                )
                server.journal.kill()  # SIGKILL model
                t0 = time.perf_counter()
                restored = FleetServer.restore(root, model)
                times.append((time.perf_counter() - t0) * 1e3)
                acct = restored.stats.accounting()
                ok = ok and (
                    acct["balanced"]
                    and acct["scored"] == expected
                    and acct["pending"] == 0
                    and restored.stats.recoveries == 1
                    and len(restored.sessions) == n_sessions
                )
            finally:
                shutil.rmtree(root, ignore_errors=True)
        rows.append(
            {
                "n_sessions": int(n_sessions),
                "windows": int(n_sessions) * windows_per_session,
                "recovery_ms_median": round(float(np.median(times)), 3),
                "recovery_ms_std": round(float(np.std(times)), 3),
                "recovery_ms_runs": [round(t, 3) for t in times],
                "journal_mb": journal_mb,
                "contract_ok": ok,
            }
        )
    return rows


def recovery_benchmark_summary(
    rows: list[dict], n_runs: int, *, windows_per_session: int = 2
) -> dict:
    """The one summary shape both consumers of ``recovery_benchmark``
    publish (bench.py's ``fleet_recovery`` lane and
    ``scripts/recovery_bench.py``'s committed artifact) — built here so
    the two cannot drift in labeling or summarization."""
    return {
        "model": "analytic_demo",
        "n_runs": int(n_runs),
        "windows_per_session": int(windows_per_session),
        "rows": rows,
        "recovery_ms_median": rows[-1]["recovery_ms_median"],
        "recovery_ms_std": rows[-1]["recovery_ms_std"],
        "contract_ok": all(r["contract_ok"] for r in rows),
    }


def recovery_smoke(
    sessions: int = 16, *, seed: int = 0, kill_points=None
) -> dict:
    """The release gate's crash-recovery check: kill a journaled fleet
    at representative stage boundaries, recover each one, and demand
    the full contract — accounting intact, zero windows lost (the
    harness's transport replays from the watermark), and bit-identical
    acked scores vs an uninterrupted run.  Returns a JSON-ready verdict
    with the ``{kill_points, recovered, windows_lost, recovery_ms}``
    stamp the gate log carries."""
    from har_tpu.serve.chaos import KILL_POINTS, run_kill_point

    points = list(kill_points or KILL_POINTS[:3])
    recovered = 0
    windows_lost = 0
    recovery_ms = []
    failures = []
    for point in points:
        out = run_kill_point(point, sessions=sessions, seed=seed)
        if out["ok"]:
            recovered += 1
        else:
            failures.append({"point": point, "why": out["why"]})
        windows_lost += out["windows_lost"]
        recovery_ms.append(out["recovery_ms"])
    return {
        "ok": recovered == len(points) and windows_lost == 0,
        "kill_points": points,
        "recovered": recovered,
        "windows_lost": windows_lost,
        "recovery_ms": round(float(np.median(recovery_ms)), 3),
        "failures": failures,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(recovery_smoke()))
