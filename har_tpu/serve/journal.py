"""Crash-safe durability for the fleet serving engine: an append-only,
fsync-batched write-ahead journal plus periodic state snapshots.

PR 2/3 made the fleet fault-tolerant *while alive* (retry ladders,
zero-drop swaps, contained registry I/O) — and kept every byte of it in
process memory, so one SIGKILL erased a 1,000-session fleet.  Spark's
core robustness claim is exactly the property that rewrite dropped:
lineage-based recomputation after worker loss.  This module is the
JAX-side equivalent, shaped for a serving loop instead of an RDD DAG:

  - ``FleetJournal`` — an append-only log of fleet MUTATIONS (session
    add/remove, pushed samples, scored-event acks, drops, declared
    losses, swap records, adaptation transitions).  Records are
    buffered in memory and written+fsynced in batches
    (``JournalConfig.flush_every``) plus at every ack boundary — so a
    kill loses AT MOST the un-flushed suffix, never a torn or
    reordered prefix;
  - periodic SNAPSHOTS of full per-session state (ring buffers,
    smoother state, drift-monitor state, queued windows, stats
    counters, adaptation episode state) written atomically
    (tmp + fsync + rename + dir fsync) with the journal rotated to a
    fresh segment — recovery cost is bounded by the snapshot cadence,
    not the fleet's lifetime;
  - recovery (har_tpu.serve.recover) = load newest snapshot + replay
    the journal suffix.  The binary framing is torn-tail-safe: each
    record carries its length and a CRC, so a record half-written at
    the kill instant is detected and discarded instead of corrupting
    the replay.

Durability contract (test-pinned by the kill-point chaos harness,
har_tpu.serve.chaos):

  - an event DELIVERED to the consumer has its ack on disk (poll()
    flushes acks before returning), so recovery never re-emits it —
    zero double-scored, zero double-counted events;
  - a window enqueued but not acked is recovered as pending and scored
    after restart — with a deterministic model, bit-identically to an
    uninterrupted run.  That includes windows riding an in-flight
    dispatch ticket (the pipelined launch/retire split,
    har_tpu.serve.dispatch): acks are written at RETIRE, so a ticket in
    flight at the kill instant is un-acked by construction, and a
    snapshot taken while it flies serializes its windows as ordinary
    pending — pipelining never changes what a crash can lose;
  - windows whose push records never reached disk are re-deliverable
    from the recovered per-session watermark (``FleetServer.
    watermark``); a transport that cannot replay declares them lost
    (``FleetServer.declare_lost``) and the accounting extends to
    ``enqueued == scored + dropped + pending + lost_in_crash``, with
    ``lost_in_crash`` bounded by the flush interval.

Record framing (little-endian):

    u32 meta_len | u32 payload_len | u32 crc32(meta+payload)
    | meta (UTF-8 JSON) | payload (raw bytes, usually float arrays)

Directory layout::

    root/
      wal.<k>.log     journal segments; <k> bumps at every snapshot
      snap.<k>/       snapshot covering everything before wal.<k>.log
        state.json    scalars + per-session metadata + stats + extras
        arrays.npz    ring buffers, pending windows, smoother arrays

Session ids must be JSON-round-trippable (str or int) to be journaled —
a tuple id would come back as a list and break ack matching.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import struct
import zlib
from typing import Callable

import numpy as np

from har_tpu.utils.durable import fsync_dir as _fsync_dir

_HDR = struct.Struct("<III")
_SEG_PREFIX = "wal."
_SEG_SUFFIX = ".log"
_SNAP_PREFIX = "snap."
_STATE = "state.json"
_ARRAYS = "arrays.npz"
# journal-ship receive-side markers (har_tpu.serve.net.ship): a shipped
# copy of a journal directory carries SHIP_LOG (the durable chunk log)
# for its whole life and SHIP_DONE only once every file's whole-file
# digest verified.  load_journal refuses the in-between state — the
# digest-before-replay rule lives HERE, at the replay layer, so no
# caller can restore a torn or bit-rotted ship by accident.
SHIP_LOG = "ship.log"
SHIP_DONE = "ship.done"

# the on-disk format version, stamped into every snapshot: a future
# layout change bumps it and keeps this loader working on old dirs
JOURNAL_FORMAT = 1


class JournalError(RuntimeError):
    """Journal directory unreadable or internally inconsistent."""


@dataclasses.dataclass(frozen=True)
class JournalConfig:
    """Durability/cost knobs for a FleetJournal."""

    # records buffered before an automatic write+fsync; poll() forces a
    # flush at every ack boundary regardless, so this bounds how many
    # PUSH records (the loss window) a kill can erase
    flush_every: int = 64
    # records appended between automatic snapshots (0 = only the
    # attach-time snapshot and explicit snapshot() calls) — bounds
    # recovery replay cost, not durability
    snapshot_every: int = 4096
    # fsync on flush: the durability claim needs it; tests that only
    # exercise replay logic may turn it off for speed
    fsync: bool = True

    def __post_init__(self):
        if self.flush_every < 1:
            raise ValueError("flush_every must be >= 1")
        if self.snapshot_every < 0:
            raise ValueError("snapshot_every must be >= 0")


def encode_record(meta: dict, payload: bytes = b"") -> bytes:
    m = json.dumps(meta, separators=(",", ":")).encode()
    crc = zlib.crc32(m + payload) & 0xFFFFFFFF
    return _HDR.pack(len(m), len(payload), crc) + m + payload


def read_segment(path: str) -> tuple[list[tuple[dict, bytes]], bool]:
    """Decode one segment file; returns (records, torn_tail).  A
    truncated or CRC-failing record ends the read — that is the normal
    signature of a kill mid-write, not an error."""
    records: list[tuple[dict, bytes]] = []
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as exc:
        raise JournalError(f"unreadable journal segment {path}: {exc}")
    pos, n = 0, len(data)
    while pos + _HDR.size <= n:
        meta_len, payload_len, crc = _HDR.unpack_from(data, pos)
        end = pos + _HDR.size + meta_len + payload_len
        if end > n:
            return records, True  # torn tail: record half-written
        body = data[pos + _HDR.size : end]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            return records, True
        try:
            meta = json.loads(body[:meta_len].decode())
        except ValueError:
            return records, True
        records.append((meta, body[meta_len:]))
        pos = end
    return records, pos < n


def read_segment_from(
    path: str, offset: int
) -> tuple[list[tuple[dict, bytes]], int]:
    """Incremental segment read for the replication tail
    (har_tpu.serve.replica): decode every complete record at or after
    ``offset`` and return (records, next_offset) — the byte cursor just
    past the last decodable record, the resume point for the next pass.
    A torn or half-staged tail simply ends the read (the cursor stays
    before it); the next pass re-reads from there once more bytes land.
    Same framing walk as ``read_segment`` — the two cannot disagree on
    what a record is."""
    records: list[tuple[dict, bytes]] = []
    try:
        with open(path, "rb") as f:
            f.seek(int(offset))
            data = f.read()
    except OSError as exc:
        raise JournalError(f"unreadable journal segment {path}: {exc}")
    pos, n = 0, len(data)
    while pos + _HDR.size <= n:
        meta_len, payload_len, crc = _HDR.unpack_from(data, pos)
        end = pos + _HDR.size + meta_len + payload_len
        if end > n:
            break
        body = data[pos + _HDR.size : end]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            break
        try:
            meta = json.loads(body[:meta_len].decode())
        except ValueError:
            break
        records.append((meta, body[meta_len:]))
        pos = end
    return records, int(offset) + pos


class FleetJournal:
    """Append-only fleet mutation log + snapshot writer.

    ``chaos`` is the kill-point hook: the engine (and the adaptation
    controller) call ``journal.chaos_point(name)`` at every stage
    boundary; the chaos harness installs a callable that raises a
    simulated crash at a chosen point, and ``kill()`` then models the
    SIGKILL — the un-flushed buffer is discarded, exactly what the
    kernel would have lost.
    """

    def __init__(self, root: str, config: JournalConfig | None = None):
        self.root = os.path.abspath(os.path.expanduser(root))
        self.config = config or JournalConfig()
        os.makedirs(self.root, exist_ok=True)
        self.chaos: Callable[[str], None] | None = None
        # storage fault hook: called with the operation name ("write" /
        # "fsync" / "snapshot") right before the real syscall; a test
        # hook raises OSError (ENOSPC, EIO) there to model a failing
        # disk.  The ENGINE owns the containment policy (count + warn +
        # keep serving, har_tpu.serve.engine); this layer only makes a
        # failed flush RETRY-SAFE (see flush()).
        self.fault: Callable[[str], None] | None = None
        self._buf: list[bytes] = []
        self._since_snapshot = 0
        self._segment = self._next_segment_index()
        self._fh = open(self._segment_path(self._segment), "ab")
        # retry-safety bookkeeping: the segment offset below which every
        # byte is a COMPLETE written record (bytes past it are the torn
        # tail of an in-flight failed write — the rewind target), and
        # whether written-but-unsynced bytes still need an fsync (a
        # failed fsync must be retried even when the record buffer is
        # empty).  The rewind target must advance on write success, NOT
        # after the fsync: once the buffer is cleared the file is the
        # records' only home, and a later failed-write rewind past them
        # would lose acks a healed journal then claims are durable.
        self._written_off = self._fh.tell()
        self._sync_pending = False
        self._killed = False

    # ----------------------------------------------------- file layout

    def _segment_path(self, k: int) -> str:
        return os.path.join(self.root, f"{_SEG_PREFIX}{k}{_SEG_SUFFIX}")

    def _snap_path(self, k: int) -> str:
        return os.path.join(self.root, f"{_SNAP_PREFIX}{k}")

    def _next_segment_index(self) -> int:
        return max(
            (idx for _, idx in _list_indexed(self.root, _SEG_PREFIX)),
            default=-1,
        ) + 1

    # ------------------------------------------------------- appending

    def chaos_point(self, name: str) -> None:
        if self.chaos is not None:
            self.chaos(name)

    def append(self, meta: dict, payload: bytes = b"") -> None:
        if self._killed:
            return
        self._buf.append(encode_record(meta, payload))
        self._since_snapshot += 1
        if len(self._buf) >= self.config.flush_every:
            self.flush()

    def _fault(self, op: str) -> None:
        if self.fault is not None:
            self.fault(op)

    def flush(self) -> None:
        """Write + fsync the buffered records: everything appended so
        far is durable once this returns.

        RETRY-SAFE under storage faults: a failed WRITE (ENOSPC mid-
        record) truncates the segment back to the last complete-record
        offset before re-raising, so the retry cannot leave a torn
        record in the MIDDLE of the log (the torn-tail framing only
        protects the end — records appended after an interior tear
        would be silently unreachable at replay); a failed FSYNC keeps
        the sync-pending flag set, so the next flush re-fsyncs even
        when no new records arrived.  The rewind target advances with
        the WRITE, not the fsync: records whose write landed but whose
        fsync failed live only in the file (the buffer is cleared), so
        a later failed-write rewind must stop short of them.  The
        caller (the engine's containment path) decides whether a
        failure is fatal."""
        if self._killed:
            return
        if self._buf:
            data = b"".join(self._buf)
            try:
                self._fault("write")
                self._fh.write(data)
                self._fh.flush()
            except OSError:
                # rewind to the complete-record prefix: shrinking needs
                # no disk space, so this succeeds even on a full disk;
                # if the handle itself is broken the torn tail stays —
                # and the framing discards it at replay like any kill
                # tear
                try:
                    self._fh.truncate(self._written_off)
                    self._fh.seek(self._written_off)
                except OSError:
                    pass
                raise
            self._buf.clear()
            self._written_off = self._fh.tell()
            self._sync_pending = True
        if self._sync_pending and self.config.fsync:
            self._fault("fsync")
            os.fsync(self._fh.fileno())
        self._sync_pending = False

    @property
    def pending_records(self) -> int:
        return len(self._buf)

    def has_state(self) -> bool:
        """True when the directory already holds a recoverable journal
        (at least one complete snapshot) — what a fresh attach must
        refuse to silently destroy."""
        return bool(_list_indexed(self.root, _SNAP_PREFIX))

    def snapshot_due(self) -> bool:
        return (
            self.config.snapshot_every > 0
            and self._since_snapshot >= self.config.snapshot_every
        )

    # ------------------------------------------------------- snapshots

    def write_snapshot(self, state: dict, arrays: dict) -> str:
        """Atomically persist a full-state snapshot and rotate to a
        fresh segment.  Crash-ordering: the snapshot only becomes
        visible (rename + dir fsync) after its contents are on disk,
        and old segments are deleted only after that — a kill at ANY
        instant leaves either the old snapshot+segments or the new
        ones, never neither."""
        self.flush()
        self._fault("snapshot")
        nxt = self._segment + 1
        snap = self._snap_path(nxt)
        tmp = snap + ".tmp"
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        state = dict(state)
        state["journal_format"] = JOURNAL_FORMAT
        state["segment"] = nxt
        with open(os.path.join(tmp, _ARRAYS), "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        with open(os.path.join(tmp, _STATE), "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        self.chaos_point("mid_snapshot")
        # failure-ordered rotation: the NEW segment opens BEFORE the
        # snapshot becomes visible, and the old handle closes only
        # after both succeeded — a failing open (full disk) aborts
        # with the old snapshot + old segment + live handle fully
        # intact (the engine's containment can keep appending), and a
        # failing rename leaves only a harmless empty wal.<nxt>.
        # Committing the snapshot BEFORE the segment rotated would be
        # worse than no snapshot: load_journal reads segments >= the
        # snapshot's index, so records still landing in the OLD
        # segment would silently vanish from replay.
        new_fh = open(self._segment_path(nxt), "ab")
        try:
            os.replace(tmp, snap)
            _fsync_dir(self.root)
        except BaseException:
            new_fh.close()
            try:
                os.remove(self._segment_path(nxt))
            except OSError:
                pass
            raise
        self._fh.close()
        self._segment = nxt
        self._fh = new_fh
        self._written_off = self._fh.tell()
        self._sync_pending = False
        self._since_snapshot = 0
        self.prune()
        return snap

    def prune(self) -> None:
        """Delete journal state the newest rotation supersedes: segments
        and snapshots below the current rotation point, and any torn
        ``*.tmp`` snapshot directory a mid-snapshot kill left behind.
        A torn tmp is invisible to recovery by construction
        (``_list_indexed`` skips ``.tmp`` names, so ``load_journal``
        never reads it) but each one holds a full state copy — a fleet
        that crashes inside snapshots for a week must not fill the disk
        with them.  Pinned in tests/test_recovery.py."""
        for path, idx in _list_indexed(self.root, _SEG_PREFIX):
            if idx < self._segment:
                try:
                    os.remove(path)
                except OSError:
                    pass
        for path, idx in _list_indexed(self.root, _SNAP_PREFIX):
            if idx < self._segment:
                shutil.rmtree(path, ignore_errors=True)
        for name in sorted(os.listdir(self.root)):
            if not name.endswith(".tmp"):
                continue
            path = os.path.join(self.root, name)
            # never the in-progress tmp: prune runs only from
            # write_snapshot AFTER its rename, or between snapshots
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                try:
                    os.remove(path)
                except OSError:
                    pass

    # ------------------------------------------------------ lifecycle

    def kill(self) -> None:
        """Simulate SIGKILL: drop the un-flushed buffer and abandon the
        file handle.  What is on disk afterwards is exactly what a real
        kill would have left (the chaos harness's crash model)."""
        self._killed = True
        self._buf.clear()
        try:
            self._fh.close()
        except OSError:
            pass

    def close(self) -> None:
        self.flush()
        self._killed = True
        try:
            self._fh.close()
        except OSError:
            pass


def _list_indexed(root: str, prefix: str) -> list[tuple[str, int]]:
    out = []
    for name in os.listdir(root):
        if not name.startswith(prefix) or name.endswith(".tmp"):
            continue
        stem = name[len(prefix):]
        if stem.endswith(_SEG_SUFFIX):
            stem = stem[: -len(_SEG_SUFFIX)]
        try:
            out.append((os.path.join(root, name), int(stem)))
        except ValueError:
            continue
    return sorted(out, key=lambda t: t[1])


def monitor_state(monitor) -> dict | None:
    """None-tolerant wrapper over ``DriftMonitor.state()`` — the
    serialization itself lives on the monitor class, next to the fields
    it depends on."""
    return None if monitor is None else monitor.state()


def monitor_from_state(state: dict | None):
    """None-tolerant wrapper over ``DriftMonitor.from_state``."""
    if state is None:
        return None
    from har_tpu.monitoring import DriftMonitor

    return DriftMonitor.from_state(state)


def load_journal(
    root: str, *, inflight_ship_ok: bool = False
) -> tuple[dict, dict, list[tuple[dict, bytes]]]:
    """Read a journal directory back: (snapshot_state, snapshot_arrays,
    suffix_records).  The newest COMPLETE snapshot wins (a mid-snapshot
    kill leaves a ``.tmp`` dir, ignored by construction); the suffix is
    every decodable record in segments at or after the snapshot's
    rotation point, torn tails discarded.

    ``inflight_ship_ok`` lifts the partially-shipped-copy refusal for
    the WARM REPLICA only (har_tpu.serve.replica): a standby's tail
    destination carries ``ship.log`` without ``ship.done`` for its
    whole tailing life by design, and its reads are advisory — every
    FAILOVER restore still runs with the guard on, after
    ``finalize_tail`` verified whole-file digests and landed the done
    marker.  Never set this on a recovery path."""
    root = os.path.abspath(os.path.expanduser(root))
    if not os.path.isdir(root):
        raise JournalError(f"no journal directory at {root}")
    if (
        not inflight_ship_ok
        and os.path.exists(os.path.join(root, SHIP_LOG))
        and not os.path.exists(os.path.join(root, SHIP_DONE))
    ):
        raise JournalError(
            f"journal directory {root} is a partially shipped copy "
            "(ship.log without ship.done): the whole-file digests were "
            "never verified — resume the ship "
            "(har_tpu.serve.net.ship.fetch_journal); a torn or "
            "bit-rotted ship is refused, never replayed"
        )
    snaps = _list_indexed(root, _SNAP_PREFIX)
    state: dict = {}
    arrays: dict = {}
    base = 0
    for path, idx in reversed(snaps):
        try:
            with open(os.path.join(path, _STATE)) as f:
                state = json.load(f)
            with np.load(os.path.join(path, _ARRAYS)) as z:
                arrays = {k: z[k] for k in z.files}
        except (OSError, ValueError) as exc:
            raise JournalError(f"unreadable snapshot {path}: {exc}")
        base = idx
        break
    if not state:
        raise JournalError(
            f"no snapshot in {root} — a journaled fleet always writes "
            "one at attach time; is this a journal directory?"
        )
    records: list[tuple[dict, bytes]] = []
    for path, idx in _list_indexed(root, _SEG_PREFIX):
        if idx < base:
            continue
        recs, _torn = read_segment(path)
        records.extend(recs)
    return state, arrays, records
