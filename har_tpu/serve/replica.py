"""Warm standby replicas: the tailed journal replayed continuously
through the one recovery code path.

``har_tpu.serve.net.tail`` keeps a byte-faithful, durably-resumable
copy of each live worker's journal on the standby's disk; this module
keeps that copy WARM — a live in-memory ``FleetServer`` rebuilt from
the tailed snapshot and advanced record-by-record through
``har_tpu.serve.recover.apply_record`` as the suffix lands.  The
replica is a streaming validator and a lag gauge, not a second serving
plane: it never attaches a journal, never retires a window to a
client, and failover still restores through the unchanged
``FleetServer.restore`` path — what the standby changes is that the
bytes that path reads are already local and already verified, so the
failover transfer is ~0 and ``ship_ms`` leaves the failover path.

The pieces:

  ``WarmReplica``   one source's replica: rebuilds from the tailed
        snapshot whenever the manifest base rotates (the re-manifest
        boundary), otherwise advances incrementally from per-segment
        byte cursors via ``read_segment_from`` — the same CRC framing
        decides record completeness on the tail as on the worker's own
        disk, so a half-landed chunk can never half-apply;

  ``StandbyAgent``  the per-host loop: one ``cycle()`` tails every
        followed source (``tail_once``), advances every replica, and
        publishes per-source ``replication_lag_records`` /
        ``replication_lag_bytes`` gauges on its ``FleetStats``
        (ephemeral — lag is recomputed by the next cycle, never
        snapshot state).  An unreachable source parks and retries next
        cycle; it never fails the loop;

  ``StandbyHost``   the ``har serve-agent --follow`` wrapper: a plain
        ship agent over the standby's staged root (so a downstream can
        ship FROM the standby) interleaved with standby cycles, plus a
        ``standby_status`` RPC exposing the replication section.

A torn-tail note that makes the incremental replay safe: the replica
reads ``.part`` bytes past the durable ship-log offset.  Those bytes
are real source-journal bytes (append-only source, idempotent-by-offset
pull) — a crash-and-resume re-pulls byte-identical content — and the
record CRC framing stops at any half-landed record, so early applies
are applies of records the source durably holds.
"""

from __future__ import annotations

import os
import time
from typing import Callable

from har_tpu.serve.journal import (
    SHIP_LOG,
    JournalError,
    read_segment_from,
)
from har_tpu.serve.net.ship import (
    DEFAULT_CHUNK_BYTES,
    ShipError,
    ShipUnavailable,
    replay_ship_log,
)
from har_tpu.serve.net.tail import (
    LocalShipSource,
    _segment_index,
    finalize_tail,
    manifest_base,
    tail_once,
)
from har_tpu.serve.recover import apply_record, restore_server
from har_tpu.serve.stats import FleetStats

__all__ = [
    "WarmReplica",
    "StandbyAgent",
    "StandbyHost",
    "LocalShipSource",
]


class WarmReplica:
    """One tailed journal directory kept live in memory.  ``advance()``
    is idempotent and cheap when nothing landed; a manifest-base
    rotation (the source snapshotted) triggers a full rebuild from the
    new snapshot — O(state), paid once per ``snapshot_every`` — and
    everything else is an incremental ``apply_record`` walk from
    per-segment byte cursors."""

    def __init__(self, dest: str, loader, *, clock=None):
        self.dest = dest
        self._loader = loader
        self._clock = clock
        self.server = None
        self.base = -1
        self.applied_records = 0
        self.rebuilds = 0
        self.lag_records = 0
        self._cursors: dict[str, int] = {}
        self._model_version = None

    # ------------------------------------------------------- internals

    def _segment_path(self, rel: str) -> str | None:
        """A tailed segment lives as a verified final or a growing
        ``.part`` — same bytes either way, the cursor carries over."""
        final = os.path.join(self.dest, rel)
        if os.path.exists(final):
            return final
        if os.path.exists(final + ".part"):
            return final + ".part"
        return None

    def _rebuild(self, base: int, names) -> None:
        """Re-found the replica on the newest tailed snapshot.  The
        restore replays every VERIFIED final segment (``load_journal``
        never sees a ``.part`` — the suffix ``.log.part`` fails its
        index parse), so cursors start at file-size for finals and at
        zero for the active tail."""
        server = restore_server(
            self.dest,
            self._loader,
            clock=self._clock,
            reattach=False,
            inflight_ship_ok=True,
        )
        self.server = server
        self.base = base
        self.rebuilds += 1
        self._model_version = server.model_version
        self._cursors = {}
        for rel in names:
            if _segment_index(rel) is None:
                continue
            final = os.path.join(self.dest, rel)
            self._cursors[rel] = (
                os.path.getsize(final) if os.path.exists(final) else 0
            )

    # ------------------------------------------------------------- api

    def advance(self) -> dict:
        """Fold everything newly staged into the live replica.
        Returns ``{ready, applied, lag_records, base, rebuilds}``;
        ``ready`` is False until the tail has landed a complete
        verified snapshot (a replica cannot be founded on bytes that
        have not passed their digest)."""
        out = {"ready": False, "applied": 0, "lag_records": 0,
               "base": self.base, "rebuilds": self.rebuilds}
        prog = replay_ship_log(self.dest)
        if prog.manifest is None:
            return out
        names = [e["f"] for e in prog.manifest]
        base = manifest_base(names)
        if self.server is None or base != self.base:
            try:
                self._rebuild(base, names)
            except JournalError:
                # the new snapshot has not fully landed yet: stay on
                # the old founding (or none) and catch up next cycle
                return out
            out["rebuilds"] = self.rebuilds
            out["base"] = self.base
        applied = 0
        segments = sorted(
            (rel for rel in names if _segment_index(rel) is not None),
            key=_segment_index,
        )
        server = self.server
        server._replaying = True
        try:
            for rel in segments:
                path = self._segment_path(rel)
                if path is None:
                    continue
                records, cursor = read_segment_from(
                    path, self._cursors.get(rel, 0)
                )
                for meta, payload in records:
                    apply_record(server, meta, payload)
                self._cursors[rel] = cursor
                applied += len(records)
        finally:
            server._replaying = False
        if applied and server.model_version != self._model_version:
            # a swap record crossed the tail: re-resolve the model the
            # same way restore_server does after its replay
            if callable(self._loader):
                server.model = self._loader(server.model_version)
            self._model_version = server.model_version
        self.applied_records += applied
        self.lag_records = applied
        out.update(ready=True, applied=applied, lag_records=applied,
                   base=self.base)
        return out


class StandbyAgent:
    """Tail-follow a set of live workers into ``<root>/<wid>`` staging
    directories and keep a warm replica of each.  One ``cycle()`` is
    one pass over every source; the controller drives it from its poll
    loop (in-process) or ``StandbyHost`` drives it on a cadence
    (``har serve-agent --follow``)."""

    def __init__(
        self,
        root: str,
        sources: dict,
        *,
        loader=None,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
        chaos: Callable[[str], None] | None = None,
        clock=None,
        stats: FleetStats | None = None,
    ):
        self.root = os.path.abspath(os.path.expanduser(root))
        self.sources = dict(sources)
        self.stats = stats if stats is not None else FleetStats()
        self.replicas: dict[str, WarmReplica] = {}
        self.parked: dict[str, str] = {}
        self.cycles = 0
        self._loader = loader
        self._chunk_bytes = int(chunk_bytes)
        self._chaos = chaos
        self._clock = clock

    def dest(self, wid) -> str:
        return os.path.join(self.root, str(wid))

    def holds(self, wid) -> bool:
        """True when a tail for ``wid`` has durable progress — the
        signal controller placement uses to prefer this standby's
        bytes over a cold ship."""
        return str(wid) in {str(k) for k in self.sources} and (
            os.path.exists(os.path.join(self.dest(wid), SHIP_LOG))
        )

    def cycle(self) -> dict:
        """One tail + advance pass over every followed source.
        Publishes the per-source lag gauges; an unreachable or
        not-yet-snapshotted source parks (recorded in ``parked``) and
        is retried next cycle."""
        self.cycles += 1
        out = {"sources": {}, "lag_records": 0, "lag_bytes": 0}
        for wid, client in self.sources.items():
            dest = self.dest(wid)
            try:
                tailed = tail_once(
                    client, str(wid), dest,
                    chunk_bytes=self._chunk_bytes,
                    chaos=self._chaos, stats=self.stats,
                )
            except (ShipUnavailable, ShipError) as exc:
                self.parked[str(wid)] = str(exc)
                continue
            self.parked.pop(str(wid), None)
            replica = self.replicas.get(str(wid))
            if replica is None:
                replica = WarmReplica(
                    dest, self._loader, clock=self._clock
                )
                self.replicas[str(wid)] = replica
            adv = replica.advance()
            lag_bytes = max(
                0, tailed["manifest_bytes"] - tailed["staged_bytes"]
            )
            self.stats.replication_lag_records[str(wid)] = adv[
                "lag_records"
            ]
            self.stats.replication_lag_bytes[str(wid)] = lag_bytes
            out["sources"][str(wid)] = {
                "tail": tailed, "replica": adv, "lag_bytes": lag_bytes,
            }
            out["lag_records"] += adv["lag_records"]
            out["lag_bytes"] += lag_bytes
        return out

    def finalize(self, wid) -> dict:
        """Failover completion for one (now dead) source: pull the
        missing suffix — zero bytes when the tail was caught up —
        verify every whole-file digest, land ``ship_done``.  Returns
        the transfer accounting; ``out["bytes"]`` IS the
        failover-path transfer."""
        client = self.sources[wid if wid in self.sources else str(wid)]
        return finalize_tail(
            client, str(wid), self.dest(wid),
            chunk_bytes=self._chunk_bytes, chaos=self._chaos,
            stats=self.stats,
        )

    def status(self) -> dict:
        """The standby's observable state; the ``replication`` section
        is the satellite contract the status RPC exposes."""
        replication = {}
        for wid in self.sources:
            wid = str(wid)
            replica = self.replicas.get(wid)
            replication[wid] = {
                "lag_records": self.stats.replication_lag_records.get(
                    wid, 0
                ),
                "lag_bytes": self.stats.replication_lag_bytes.get(
                    wid, 0
                ),
                "base": replica.base if replica else -1,
                "applied_records": (
                    replica.applied_records if replica else 0
                ),
                "rebuilds": replica.rebuilds if replica else 0,
                "ready": bool(replica and replica.server is not None),
                "parked": self.parked.get(wid),
            }
        return {
            "root": self.root,
            "cycles": self.cycles,
            "sources": sorted(str(w) for w in self.sources),
            "replication": replication,
        }

    def close(self) -> None:
        for client in self.sources.values():
            close = getattr(client, "close", None)
            if close is not None:
                close()


class StandbyHost:
    """The ``har serve-agent --follow`` process body: a plain ship
    agent over the standby's staged root (the tailed copies are
    themselves shippable — a failover can pull FROM the standby over
    the same protocol) interleaved with standby cycles on a cadence,
    plus a ``standby_status`` RPC returning ``StandbyAgent.status()``.
    Follow mode is NOT engine-free: warming a replica replays records
    through the fleet engine, so this import lives behind the
    ``--follow`` flag in the agent CLI."""

    def __init__(
        self,
        root: str,
        follows: dict,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        cycle_s: float = 0.5,
        loader=None,
        chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    ):
        from har_tpu.serve.net.ship import ShipAgent, ShipClient

        self.agent = ShipAgent(root, host=host, port=port)
        sources = {
            wid: ShipClient(h, p) for wid, (h, p) in follows.items()
        }
        self.standby = StandbyAgent(
            root, sources, loader=loader, chunk_bytes=chunk_bytes
        )
        self.cycle_s = float(cycle_s)
        handlers = self.agent.rpc.handlers  # registered pre-serve

        def standby_status(meta, payload):
            return self.standby.status(), b""

        handlers["standby_status"] = standby_status

    def serve_forever(self, *, max_idle_s: float = 0.0) -> int:
        """RPC steps interleaved with standby cycles.  A cycling
        standby is ACTIVE — idle-orphan reaping only counts RPC
        silence, mirroring the plain agent."""
        agent = self.agent
        next_cycle = 0.0
        try:
            while not agent._shutdown:
                agent.rpc.step(min(0.05, self.cycle_s))
                now = time.monotonic()
                if now >= next_cycle:
                    self.standby.cycle()
                    next_cycle = now + self.cycle_s
                if (
                    max_idle_s
                    and now - agent.rpc.last_activity > max_idle_s
                ):
                    return 2
            return 0
        finally:
            self.close()

    def close(self) -> None:
        self.standby.close()
        self.agent.close()
