"""Load-adaptive capacity control: a hysteresis/cooldown policy loop
that resizes the fleet online from the signals FleetStats already
exports.

The engine's capacity knobs — ``target_batch``, ``pipeline_depth``, the
dispatch mesh, the cluster's worker count — were all frozen at startup
until PR 9.  This controller closes the loop the ROADMAP's "production
traffic realism" item names: it reads the queue backlog, the dispatch
fill fraction, the dispatch p99 and the shed-rate delta from
``FleetStats``, applies HYSTERESIS (consecutive-evidence streaks, so
one bursty poll never thrashes the mesh) and a COOLDOWN (a resize is a
recompile ladder and a re-shard — they must amortize), and walks a
fixed capacity ladder:

    scale UP    target_batch ×2 ... max → pipeline_depth +1 ... max →
                next mesh rung (``mesh_ladder`` × ``mesh_for``) →
                [cluster] add_worker(rebalance=True)
    scale DOWN  the exact reverse

Every engine-level action lands through ``FleetServer.resize`` — the
dispatch-boundary, zero-drop, journaled resize path, so autoscaling
inherits the whole durability story (a ``mid_resize`` crash recovers
and the controller re-issues).  Cluster-level actions reuse PR 7's
drain → hand-off machinery verbatim: the controller drains the cluster
(the drained events are returned to the driver — never swallowed),
then ``add_worker(rebalance=True)`` / ``retire_worker``.

The controller never blocks the hot path: ``step()`` is host-side
arithmetic over counters, called from the serving loop's poll hook
(``drive_trace(on_round=controller.on_round)`` or ``drive_fleet
(on_poll=...)``), and the one thing it does per decision is stage a
resize the next dispatch boundary applies.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Thresholds, hysteresis and bounds for a CapacityController.

    Signals (read per step):
      - queue backlog (``stats.queue_depth``, the live gauge —
        ``drive_trace`` fires its on_round hook BEFORE the poll for
        exactly this reason: the poll would drain the backlog the
        controller needs to see): backlog >= ``queue_high`` ×
        target_batch is scale-UP evidence;
      - dispatch fill (``stats.utilization``): fill <= ``util_low``
        with a small backlog is scale-DOWN evidence — as is a fully
        IDLE step (nothing scored since the last one: the fill gauge
        only updates when a batch launches, so a load collapse would
        otherwise freeze it at the last batch's fill and pin capacity
        at the ceiling);
      - dispatch p99 (``stats.dispatch.percentile(99)``) above
        ``p99_high_ms`` is scale-UP evidence;
      - shed delta (``stats.dropped_total`` increased since the last
        step) is scale-UP evidence — the ladder is already paying.

    ``up_after`` / ``down_after`` consecutive evidence steps are needed
    before acting (down is deliberately slower — capacity should be
    shed reluctantly), and ``cooldown_s`` must pass between actions.
    """

    min_target_batch: int = 16
    max_target_batch: int = 256
    min_depth: int = 1
    # the dispatch plane's ticket ring runs depth >= 3 (PR 10): a
    # third in-flight ticket keeps the device busy across a slow host
    # round, so the default ladder now walks one rung past classic
    # double-buffering before it reaches for the mesh
    max_depth: int = 3
    mesh_ladder: tuple = (1,)
    queue_high: float = 1.5
    util_low: float = 0.5
    p99_high_ms: float = float("inf")
    up_after: int = 2
    down_after: int = 4
    cooldown_s: float = 0.5
    # cluster axis (0 = worker scaling off)
    sessions_per_worker_high: int = 0
    sessions_per_worker_low: int = 0
    min_workers: int = 1
    max_workers: int = 8

    def __post_init__(self):
        if self.min_target_batch < 1 or (
            self.max_target_batch < self.min_target_batch
        ):
            raise ValueError("target_batch bounds invalid")
        if self.min_depth < 1 or self.max_depth < self.min_depth:
            raise ValueError("depth bounds invalid")
        if not self.mesh_ladder or list(self.mesh_ladder) != sorted(
            set(int(d) for d in self.mesh_ladder)
        ):
            raise ValueError(
                "mesh_ladder must be ascending unique device counts"
            )
        if self.up_after < 1 or self.down_after < 1:
            raise ValueError("hysteresis streaks must be >= 1")


class CapacityController:
    """The policy loop.  ``server`` mode resizes one FleetServer's
    ``target_batch`` / ``pipeline_depth`` / mesh; give it a ``cluster``
    instead (a FleetCluster) and it scales the worker count, reading
    the same signals aggregated across workers.

    ``mesh_for(devices) -> mesh | None`` builds the mesh for a ladder
    rung (``None`` for rung 1 — back to single-device); required only
    when ``mesh_ladder`` goes past one device.  ``clock`` is the
    injected seconds source the cooldown reads (FakeClock in tests).
    """

    def __init__(
        self,
        server=None,
        *,
        cluster=None,
        config: AutoscaleConfig | None = None,
        mesh_for: Callable | None = None,
        clock: Callable[[], float] | None = None,
    ):
        if (server is None) == (cluster is None):
            raise ValueError(
                "pass exactly one of server= (engine scaling) or "
                "cluster= (worker scaling)"
            )
        self.server = server
        self.cluster = cluster
        self.config = config or AutoscaleConfig()
        self._mesh_for = mesh_for
        self._clock = clock or time.monotonic
        if max(self.config.mesh_ladder) > 1 and mesh_for is None:
            raise ValueError(
                "mesh_ladder goes past one device; pass mesh_for="
            )
        self._mesh_rung = 0  # index into mesh_ladder
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_t: float | None = None
        # delta watermarks start at the server's CURRENT totals: a
        # controller attached to a recovered or long-running fleet must
        # not read its whole drop history as one fresh shed burst
        self._last_dropped = (
            0 if server is None else server.stats.dropped_total
        )
        self._last_scored = 0 if server is None else server.stats.scored
        self.actions: list[dict] = []
        self.worker_adds = 0
        self.worker_retires = 0
        # events produced by the controller's own cluster drains — the
        # driver folds these into the run's event stream (on_round
        # returns them), so a pre-retire drain never swallows events
        self._drained_events: list = []

    # ------------------------------------------------------- plumbing

    def on_round(self, target, round_index) -> list:
        """The ``drive_trace(on_round=...)`` adapter: one policy step,
        returning any events the step's own drains produced."""
        self.step()
        return self.take_events()

    def take_events(self) -> list:
        out = self._drained_events
        self._drained_events = []
        return out

    def status(self) -> dict:
        return {
            "mode": "cluster" if self.cluster is not None else "engine",
            "actions": len(self.actions),
            "worker_adds": self.worker_adds,
            "worker_retires": self.worker_retires,
            "last_action": self.actions[-1] if self.actions else None,
        }

    # -------------------------------------------------------- signals

    def _signals(self) -> dict:
        if self.cluster is not None:
            servers = [w.server for w in self.cluster._workers.values()]
            n_sessions = sum(len(s.sessions) for s in servers)
            return {
                "workers": len(servers),
                "sessions": n_sessions,
                "per_worker": n_sessions / max(1, len(servers)),
            }
        stats = self.server.stats
        p99 = stats.dispatch.percentile(99)
        dropped = stats.dropped_total
        shed_delta = dropped - self._last_dropped
        self._last_dropped = dropped
        scored_delta = stats.scored - self._last_scored
        self._last_scored = stats.scored
        return {
            "queue_depth": stats.queue_depth,
            "utilization": stats.utilization,
            # nothing scored since the last step: the engine sat fully
            # idle — the utilization gauge is STALE then (it only
            # updates when a batch launches, so a load collapse leaves
            # it frozen at the last batch's fill), and idleness itself
            # is the strongest under-utilization evidence there is
            "idle": scored_delta == 0,
            "p99_ms": p99,
            "shed_delta": shed_delta,
        }

    # -------------------------------------------------------- the loop

    def step(self, now: float | None = None) -> dict | None:
        """One policy step: gather evidence, advance the hysteresis
        streaks, act when a streak crosses its threshold and the
        cooldown has passed.  Returns the action dict, or None."""
        cfg = self.config
        now = self._clock() if now is None else now
        sig = self._signals()
        if self.cluster is not None:
            up = bool(
                cfg.sessions_per_worker_high
                and sig["per_worker"] >= cfg.sessions_per_worker_high
                and sig["workers"] < cfg.max_workers
            )
            down = bool(
                not up
                and cfg.sessions_per_worker_low
                and sig["per_worker"] <= cfg.sessions_per_worker_low
                and sig["workers"] > cfg.min_workers
            )
        else:
            scfg = self.server.config
            up = bool(
                sig["queue_depth"] >= cfg.queue_high * scfg.target_batch
                or (
                    sig["p99_ms"] is not None
                    and sig["p99_ms"] > cfg.p99_high_ms
                )
                or sig["shed_delta"] > 0
            )
            down = bool(
                not up
                and (sig["utilization"] <= cfg.util_low or sig["idle"])
                and sig["queue_depth"] < scfg.target_batch
            )
        self._up_streak = self._up_streak + 1 if up else 0
        self._down_streak = self._down_streak + 1 if down else 0
        if (
            self._last_action_t is not None
            and now - self._last_action_t < cfg.cooldown_s
        ):
            return None
        action = None
        if self._up_streak >= cfg.up_after:
            action = self._scale(+1)
        elif self._down_streak >= cfg.down_after:
            action = self._scale(-1)
        if action is not None:
            action["signals"] = sig
            self._up_streak = 0
            self._down_streak = 0
            self._last_action_t = now
            self.actions.append(action)
        return action

    def _scale(self, direction: int) -> dict | None:
        if self.cluster is not None:
            return self._scale_cluster(direction)
        return self._scale_engine(direction)

    def _scale_engine(self, direction: int) -> dict | None:
        """Walk the capacity ladder one rung: target_batch first (the
        cheap knob — same scorer, one more compiled shape at most),
        then pipeline depth, then the mesh.  Scale-down walks the
        exact reverse, so the configuration retraces its own path."""
        cfg = self.config
        scfg = self.server.config
        if direction > 0:
            if scfg.target_batch < cfg.max_target_batch:
                tb = min(scfg.target_batch * 2, cfg.max_target_batch)
                self.server.resize(target_batch=tb)
                return {"action": "up", "knob": "target_batch", "to": tb}
            if scfg.pipeline_depth < cfg.max_depth:
                depth = scfg.pipeline_depth + 1
                self.server.resize(pipeline_depth=depth)
                return {
                    "action": "up", "knob": "pipeline_depth", "to": depth
                }
            if self._mesh_rung < len(cfg.mesh_ladder) - 1:
                self._mesh_rung += 1
                devices = int(cfg.mesh_ladder[self._mesh_rung])
                self.server.resize(
                    mesh=(
                        None if devices <= 1 else self._mesh_for(devices)
                    )
                )
                return {"action": "up", "knob": "mesh", "to": devices}
            return None  # at the ceiling
        if self._mesh_rung > 0:
            self._mesh_rung -= 1
            devices = int(cfg.mesh_ladder[self._mesh_rung])
            self.server.resize(
                mesh=(None if devices <= 1 else self._mesh_for(devices))
            )
            return {"action": "down", "knob": "mesh", "to": devices}
        if self.server.config.pipeline_depth > cfg.min_depth:
            depth = self.server.config.pipeline_depth - 1
            self.server.resize(pipeline_depth=depth)
            return {
                "action": "down", "knob": "pipeline_depth", "to": depth
            }
        if self.server.config.target_batch > cfg.min_target_batch:
            tb = max(
                self.server.config.target_batch // 2,
                cfg.min_target_batch,
            )
            self.server.resize(target_batch=tb)
            return {"action": "down", "knob": "target_batch", "to": tb}
        return None  # at the floor

    def _scale_cluster(self, direction: int) -> dict | None:
        """Worker-count rung: drain first (PR 7's hand-off machinery
        refuses live windows BY DESIGN — draining here also means no
        acked-but-undelivered event can sit in controller memory across
        the mid_handoff crash window), keep the drained events for the
        driver, then add or retire."""
        cluster = self.cluster
        if direction > 0:
            self._drained_events.extend(cluster.flush())
            wid = cluster.add_worker(rebalance=True)
            self.worker_adds += 1
            return {"action": "up", "knob": "workers", "added": wid}
        # retire the least-loaded worker: its sessions move anyway, so
        # move the fewest
        loads = [
            (len(w.server.sessions), wid)
            for wid, w in cluster._workers.items()
        ]
        loads.sort()
        victim = loads[0][1]
        self._drained_events.extend(cluster.flush())
        moved = cluster.retire_worker(victim)
        self.worker_retires += 1
        return {
            "action": "down",
            "knob": "workers",
            "retired": victim,
            "moved": moved,
        }
