"""Elastic traffic generation: diurnal / bursty / storm arrival
processes driving session churn through the fleet engine.

The PR-2 load generator (``har_tpu.serve.loadgen.drive_fleet``) holds N
sessions flat from the first round to the last — the steady state a
24/7 monitoring service never actually sees.  Real cohorts connect in
the morning, disconnect overnight, burst on alarms, and stall behind
slow uplinks.  This module models that load as a REPLAYABLE ARTIFACT:

  ``TraceSpec``     — the seed+params record.  Everything about a trace
                      (arrival shape, swing, storms, slow-client and
                      rate-mix draws) is a pure function of the spec, so
                      ``TrafficTrace.from_spec(trace.spec())`` rebuilds
                      the exact same schedule on any host — export a
                      trace from an incident, replay it in a test.

  ``TrafficTrace``  — the materialized schedule: per delivery round, the
                      sessions that connect and the sessions that
                      disconnect, plus each session's delivery rate.
                      Session churn uses the engine's GRACEFUL
                      disconnect (``FleetServer.disconnect_sessions``,
                      one batch per round): the assembler's partial
                      window flushes and the pending queue settles
                      before the eviction — churn never silently drops
                      accepted data.

  ``drive_trace``   — the driver: delivers hop-sized chunks per active
                      session per round (scaled by its rate), applies
                      slow-client stalls (chunks held for a few rounds,
                      then delivered as one catch-up burst — exactly the
                      delivery shape ``DeliveryFaults.delay_prob``
                      models, but seeded per session from the trace),
                      polls the engine, and advances the injected clock.
                      Works against a ``FleetServer`` or a
                      ``FleetCluster`` (both speak add / disconnect /
                      push / poll / flush).

Determinism stance (HL004-clean by construction): every draw comes from
``np.random.default_rng`` seeded off the spec, the driver reads only
the injected clock (``FakeClock`` in tests; a real monotonic clock in
the bench lane, where latency must be wall time), and no set is ever
iterated.  The schedule itself never depends on the clock at all —
round indices are the only time base, which is what makes a trace
replayable across hosts of different speeds.
"""

from __future__ import annotations

import dataclasses
import math
import time

import numpy as np

from har_tpu.data.raw_windows import synthetic_raw_stream


@dataclasses.dataclass(frozen=True)
class TraceSpec:
    """Seed + parameters of one traffic trace — the replayable record.

    kind:
        ``diurnal``  — sinusoidal active-session target: trough at round
                       0 (overnight), peak mid-period, back to trough.
        ``bursty``   — the diurnal base plus seeded Poisson-modulated
                       connect bursts (alarm fan-ins).
        ``storm``    — the diurnal base with the ``storms`` steps
                       applied (mass overnight-cohort disconnects).
        (``storms`` apply to every kind; ``storm`` just names a trace
        whose headline event they are.)
    peak_sessions / swing:
        peak concurrent sessions, and the peak/trough ratio — a
        ``swing`` of 10 means the trough holds peak/10 sessions.
    rounds / period:
        delivery rounds to run, and rounds per diurnal cycle.
    storms:
        ``((round, fraction), ...)`` — at each round, that fraction of
        the currently active cohort disconnects AT ONCE, oldest
        sessions first (the morning cohort leaves in the evening).
    burst_prob / burst_size:
        bursty kind: per-round probability of a connect burst, and its
        Poisson mean size.
    slow_prob / slow_rounds:
        per-(session, round) probability a delivery stalls, and for how
        many rounds the stalled chunks are held before arriving as one
        catch-up burst.
    rate_mix:
        cycled per-session delivery-rate multipliers: a session with
        rate r delivers ``r * hop`` samples per round (mixed cohorts —
        20/40 Hz sensors through the same assembler).
    """

    kind: str = "diurnal"
    peak_sessions: int = 64
    swing: float = 10.0
    rounds: int = 120
    period: int = 120
    storms: tuple = ()
    burst_prob: float = 0.0
    burst_size: int = 8
    slow_prob: float = 0.0
    slow_rounds: int = 3
    rate_mix: tuple = (1,)
    seed: int = 0

    def __post_init__(self):
        if self.kind not in ("diurnal", "bursty", "storm"):
            raise ValueError(f"unknown trace kind {self.kind!r}")
        if self.peak_sessions < 1 or self.rounds < 1 or self.period < 2:
            raise ValueError(
                "peak_sessions/rounds must be >= 1, period >= 2"
            )
        if self.swing < 1.0:
            raise ValueError("swing is peak/trough and must be >= 1")
        if not self.rate_mix or any(r < 1 for r in self.rate_mix):
            raise ValueError("rate_mix entries must be >= 1")


class TrafficTrace:
    """A materialized churn schedule: ``schedule[r]`` holds the session
    ids that connect and disconnect at round r, and ``rate_of[sid]``
    each session's delivery-rate multiplier.  Pure function of the
    spec; ``spec()``/``from_spec`` are the export/replay pair."""

    def __init__(self, spec: TraceSpec):
        self._spec = spec
        rng = np.random.default_rng((spec.seed, 0x7AF1C))
        trough = max(1, int(round(spec.peak_sessions / spec.swing)))
        storms = {int(r): float(f) for r, f in spec.storms}
        schedule: list[dict] = []
        active: list[int] = []  # connect order — oldest first
        self.rate_of: dict[int, int] = {}
        next_sid = 0
        peak_active = 0
        trough_active = None
        storm_disconnects = 0
        for r in range(spec.rounds):
            # diurnal target: trough at r=0, peak at r=period/2
            phase = 2.0 * math.pi * (r % spec.period) / spec.period
            target = trough + (spec.peak_sessions - trough) * 0.5 * (
                1.0 - math.cos(phase)
            )
            target = int(round(target))
            if spec.kind == "bursty" and spec.burst_prob:
                if rng.random() < spec.burst_prob:
                    target += int(rng.poisson(spec.burst_size))
            connects: list[int] = []
            disconnects: list[int] = []
            storm = storms.get(r)
            if storm is not None:
                n_out = int(len(active) * storm)
                disconnects.extend(active[:n_out])  # oldest cohort
                active = active[n_out:]
                storm_disconnects += n_out
            while len(active) < target:
                sid = next_sid
                next_sid += 1
                self.rate_of[sid] = int(
                    spec.rate_mix[sid % len(spec.rate_mix)]
                )
                active.append(sid)
                connects.append(sid)
            while len(active) > target:
                disconnects.append(active.pop(0))  # oldest first
            schedule.append(
                {"connect": connects, "disconnect": disconnects}
            )
            peak_active = max(peak_active, len(active))
            trough_active = (
                len(active)
                if trough_active is None
                else min(trough_active, len(active))
            )
        self.schedule = schedule
        self.total_sessions = next_sid
        self.peak_active = peak_active
        self.trough_active = trough_active or 0
        self.storm_disconnects = storm_disconnects

    def spec(self) -> dict:
        """The replayable export: JSON-ready seed+params."""
        d = dataclasses.asdict(self._spec)
        d["storms"] = [list(s) for s in d["storms"]]
        d["rate_mix"] = list(d["rate_mix"])
        return d

    @classmethod
    def from_spec(cls, spec) -> "TrafficTrace":
        """Replay: rebuild the identical schedule from an exported
        spec (a TraceSpec or its ``spec()`` dict)."""
        if isinstance(spec, TraceSpec):
            return cls(spec)
        spec = dict(spec)
        spec["storms"] = tuple(tuple(s) for s in spec.get("storms") or ())
        spec["rate_mix"] = tuple(spec.get("rate_mix") or (1,))
        return cls(TraceSpec(**spec))


@dataclasses.dataclass(frozen=True)
class TraceReport:
    """What the traffic drive actually did."""

    rounds: int
    connects: int
    disconnects: int
    storm_disconnects: int
    peak_active: int
    trough_active: int
    slow_stalls: int
    samples_delivered: int
    windows_enqueued: int
    duration_s: float


class _SessionFeed:
    """Per-session sample source + slow-client hold buffer.  Samples
    come from one shared seeded synthetic pool, each session reading a
    distinct stride-offset slice with wraparound — thousands of
    connects never re-generate data."""

    __slots__ = ("offset", "cursor", "rate", "held", "stall_left")

    def __init__(self, offset: int, rate: int):
        self.offset = offset
        self.cursor = 0
        self.rate = rate
        self.held: list[np.ndarray] = []
        self.stall_left = 0


def _pool(spec: TraceSpec, window: int) -> np.ndarray:
    """The shared sample pool every session slices (wraparound)."""
    stream = synthetic_raw_stream(
        n_windows=max(64, 2 * spec.peak_sessions),
        seed=spec.seed,
        window=window,
    )
    return stream.windows.reshape(-1, stream.windows.shape[-1])


def drive_trace(
    target,
    trace: TrafficTrace,
    *,
    clock=None,
    round_dt: float = 0.01,
    monitor_for=None,
    on_round=None,
    events: list | None = None,
) -> tuple[list, TraceReport]:
    """Run one traffic trace against a FleetServer or FleetCluster.

    Per round: apply the schedule's connects, deliver ``rate × hop``
    samples for every active session (slow clients hold theirs and
    catch up in one burst), poll, apply the graceful disconnects as
    ONE batch (``disconnect_sessions``: the leavers' partial windows
    flush and settle through a single forced poll — after the regular
    poll, so the settle's forced drain can never break the round's
    batch coalescing), then advance the injected clock by ``round_dt``
    (``clock`` defaults to real time: no advance, wall latency — the
    bench lane's mode; pass the server's FakeClock for deterministic
    tests).

    ``on_round(target, round_index)`` fires after each round's
    deliveries, BEFORE the poll and the disconnect settle — the
    capacity controller's hook (``CapacityController.on_round``): it
    reads the true backlog there (a disconnect settle running first
    would drain the very signal it scales on), and a resize it stages
    applies to this very poll's dispatches.  Any event list it returns
    (a controller drain before a worker add/retire) is folded into the
    returned events.

    Returns ``(events, TraceReport)``.  Sessions still connected when
    the trace ends stay connected (the fleet keeps serving); their
    queued windows are drained by the final flush.
    """
    spec = trace._spec
    hop = int(target.hop)
    pool = _pool(spec, hop)
    n_pool = len(pool)
    rng = np.random.default_rng((spec.seed, 0xD21F))
    feeds: dict[int, _SessionFeed] = {}
    order: list[int] = []  # active sids, connect order
    events = [] if events is None else events
    connects = disconnects = slow_stalls = 0
    delivered = enqueued = 0
    t0 = time.perf_counter()
    for r, step in enumerate(trace.schedule):
        for sid in step["connect"]:
            target.add_session(
                sid,
                monitor=(
                    monitor_for(sid) if monitor_for is not None else None
                ),
            )
            # stride-offset into the shared pool: sessions see distinct
            # (wrapped) slices without per-connect generation
            feeds[sid] = _SessionFeed(
                offset=(sid * 131 * hop) % n_pool, rate=trace.rate_of[sid]
            )
            order.append(sid)
            connects += 1
        for sid in order:
            feed = feeds[sid]
            n = feed.rate * hop
            start = (feed.offset + feed.cursor) % n_pool
            chunk = pool[start : start + n]
            if len(chunk) < n:  # wraparound
                chunk = np.concatenate([chunk, pool[: n - len(chunk)]])
            feed.cursor += n
            if feed.stall_left > 0:
                feed.stall_left -= 1
                feed.held.append(chunk)
                continue
            if spec.slow_prob and rng.random() < spec.slow_prob:
                # slow client: this and the next slow_rounds-1 chunks
                # are held, then delivered as ONE catch-up burst — a
                # stalled uplink flushing its buffer
                feed.stall_left = max(0, spec.slow_rounds - 1)
                feed.held.append(chunk)
                slow_stalls += 1
                continue
            if feed.held:
                chunk = np.concatenate([*feed.held, chunk])
                feed.held = []
            enqueued += target.push(sid, chunk)
            delivered += len(chunk)
        for sid in step["disconnect"]:
            feed = feeds.pop(sid)
            if feed.held:
                # the uplink flushes on hangup: held chunks arrive
                # before the goodbye, never silently vanish
                payload = np.concatenate(feed.held)
                enqueued += target.push(sid, payload)
                delivered += len(payload)
            order.remove(sid)
            disconnects += 1
        if on_round is not None:
            # fired after the round's deliveries but BEFORE the poll
            # and the disconnect settle: a capacity controller reads
            # the true backlog (either would drain it) and its staged
            # resize applies to this very poll's dispatches.  Any
            # events the hook returns (a controller's pre-retire
            # cluster drain) fold in here.
            extra = on_round(target, r)
            if extra:
                events.extend(extra)
        events.extend(target.poll())
        if step["disconnect"]:
            # the goodbyes land AFTER the regular poll, as one batch:
            # the leavers' grid windows scored with normal coalescing
            # above, so the settle's forced poll drains only their
            # flushed partials — one drain per round, not per session
            events.extend(target.disconnect_sessions(step["disconnect"]))
        if clock is not None and hasattr(clock, "advance"):
            clock.advance(round_dt)
    events.extend(target.flush())
    report = TraceReport(
        rounds=len(trace.schedule),
        connects=connects,
        disconnects=disconnects,
        storm_disconnects=trace.storm_disconnects,
        peak_active=trace.peak_active,
        trough_active=trace.trough_active,
        slow_stalls=slow_stalls,
        samples_delivered=delivered,
        windows_enqueued=enqueued,
        duration_s=round(time.perf_counter() - t0, 4),
    )
    return events, report
