"""Elastic traffic engine: replayable churn loadgen + load-adaptive
capacity control (docs/elasticity.md).

Public surface:
  TraceSpec / TrafficTrace / TraceReport / drive_trace
      — seeded diurnal/bursty/storm arrival processes driving session
        connect/disconnect churn, overnight-cohort storms, slow-client
        stalls and mixed per-session rates through the fleet engine; a
        trace is a replayable artifact (export/replay by seed+params).
  AutoscaleConfig / CapacityController
      — the hysteresis/cooldown policy loop that resizes target_batch /
        pipeline_depth / the dispatch mesh online (FleetServer.resize,
        zero-drop at a dispatch boundary) and drives the cluster's
        add_worker / retire_worker from load.
  elastic_smoke — the release gate's elastic-traffic check.
"""

from har_tpu.serve.traffic.autoscale import (
    AutoscaleConfig,
    CapacityController,
)
from har_tpu.serve.traffic.generate import (
    TraceReport,
    TraceSpec,
    TrafficTrace,
    drive_trace,
)
from har_tpu.serve.traffic.smoke import (
    DECLARED_SHEDS,
    elastic_smoke,
    undeclared_drops,
)

__all__ = [
    "AutoscaleConfig",
    "CapacityController",
    "DECLARED_SHEDS",
    "TraceReport",
    "TraceSpec",
    "TrafficTrace",
    "drive_trace",
    "elastic_smoke",
    "undeclared_drops",
]
