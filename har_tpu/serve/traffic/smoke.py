"""The release gate's elastic-traffic check.

``elastic_smoke()`` runs the whole elastic story once, small, in two
phases, and returns the ``{swing, resizes, p99_ms, shed_rate,
windows_lost}`` verdict the gate log stamps:

  phase 1 (engine)   a seeded 10× diurnal swing with a mid-run
                     overnight-cohort disconnect storm, slow clients
                     and mixed per-session rates, served by the jitted
                     demo model while a CapacityController walks the
                     target_batch → pipeline_depth → mesh ladder up the
                     swing and back down — at least one online resize
                     must land (a MESH re-shard when >1 device is
                     visible; the gate forces the 8-device dry-run
                     mesh), with the conservation law balanced in every
                     per-round snapshot and zero windows dropped
                     outside the SLO ladder's declared shed reasons;

  phase 2 (cluster)  the same churn against a 2-worker FleetCluster
                     while the controller scales the worker count: one
                     ``add_worker(rebalance=True)`` at the peak and one
                     drained ``retire_worker`` at the trough, global
                     conservation balanced in every per-round snapshot.

Everything is seeded and round-indexed (the trace is a replayable
artifact); the clock only feeds latency histograms.
"""

from __future__ import annotations

import shutil
import tempfile

import numpy as np

# shed reasons the SLO ladder / bounded queues DECLARE: a drop under
# one of these is the engine degrading as designed.  Anything else
# (dispatch_failed, session_removed) is a lost window the elastic run
# must not produce.
DECLARED_SHEDS = ("slo_shed", "backpressure", "session_queue")


def undeclared_drops(stats_snapshot: dict) -> int:
    by_reason = stats_snapshot["dropped_by_reason"]
    return sum(
        n for reason, n in by_reason.items()
        if reason not in DECLARED_SHEDS
    )


def elastic_smoke(seed: int = 0) -> dict:
    import jax

    from har_tpu.parallel.mesh import create_mesh
    from har_tpu.serve.engine import FleetConfig, FleetServer
    from har_tpu.serve.loadgen import AnalyticDemoModel, JitDemoModel
    from har_tpu.serve.traffic.autoscale import (
        AutoscaleConfig,
        CapacityController,
    )
    from har_tpu.serve.traffic.generate import (
        TraceSpec,
        TrafficTrace,
        drive_trace,
    )

    # ---- phase 1: engine ladder over a 10x diurnal swing -----------------
    n_dev = min(2, len(jax.devices()))
    spec = TraceSpec(
        kind="storm",
        peak_sessions=32,
        swing=10.0,
        rounds=48,
        period=48,
        storms=((30, 0.5),),
        slow_prob=0.05,
        slow_rounds=2,
        rate_mix=(1, 1, 2),
        seed=seed,
    )
    trace = TrafficTrace(spec)
    server = FleetServer(
        JitDemoModel(tunnel_rtt_ms=1.0),
        window=200,
        hop=200,
        smoothing="ema",
        config=FleetConfig(
            max_sessions=4096, target_batch=8, max_delay_ms=5.0
        ),
    )
    controller = CapacityController(
        server,
        config=AutoscaleConfig(
            min_target_batch=8,
            max_target_batch=32,
            max_depth=2,
            mesh_ladder=tuple(sorted({1, n_dev})),
            queue_high=1.0,
            util_low=0.4,
            up_after=1,
            down_after=2,
            cooldown_s=0.0,
        ),
        mesh_for=lambda d: create_mesh(
            dp=d, tp=1, devices=jax.devices()[:d]
        ),
    )
    balance = {"ok": True}
    devices_seen = {"max": 1}

    def on_round(target, r):
        out = controller.on_round(target, r)
        snap = target.stats.accounting()
        balance["ok"] = balance["ok"] and snap["balanced"]
        scorer = target._scorer
        if scorer is not None:
            devices_seen["max"] = max(devices_seen["max"], scorer.devices)
        return out

    events, report = drive_trace(server, trace, on_round=on_round)
    snap = server.stats_snapshot()
    acct = snap["accounting"]
    lost_engine = undeclared_drops(snap)
    shed_rate = (
        round(acct["dropped"] / acct["enqueued"], 4)
        if acct["enqueued"]
        else 0.0
    )
    mesh_ok = devices_seen["max"] > 1 or n_dev == 1

    # ---- phase 2: cluster worker scaling over churn ----------------------
    from har_tpu.serve.cluster.controller import FleetCluster
    from har_tpu.serve.faults import FakeClock

    root = tempfile.mkdtemp(prefix="har_elastic_smoke_")
    try:
        clock = FakeClock()
        cluster = FleetCluster(
            AnalyticDemoModel(),
            root,
            workers=2,
            window=200,
            hop=200,
            smoothing="ema",
            fleet_config=FleetConfig(max_sessions=4096, target_batch=16),
            clock=clock,
        )
        cspec = TraceSpec(
            kind="diurnal",
            peak_sessions=24,
            swing=6.0,
            rounds=36,
            period=36,
            seed=seed + 1,
        )
        ccontroller = CapacityController(
            cluster=cluster,
            config=AutoscaleConfig(
                sessions_per_worker_high=9,
                sessions_per_worker_low=2,
                min_workers=2,
                max_workers=3,
                up_after=1,
                down_after=2,
                cooldown_s=0.0,
            ),
            clock=clock,
        )
        cbalance = {"ok": True}

        def c_on_round(target, r):
            out = ccontroller.on_round(target, r)
            acct = target.accounting()
            cbalance["ok"] = cbalance["ok"] and acct["balanced"]
            return out

        c_events, c_report = drive_trace(
            cluster, TrafficTrace(cspec), clock=clock, on_round=c_on_round
        )
        c_acct = cluster.accounting()
        lost_cluster = sum(
            undeclared_drops(w.server.stats.snapshot())
            for w in cluster._workers.values()
        )
        c_stats = cluster.cluster_stats()
        cluster.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    windows_lost = lost_engine + lost_cluster
    p99 = snap["stages"]["event_ms"].get("p99_ms")
    ok = bool(
        server.stats.resizes >= 2
        and server.stats.scale_ups >= 1
        # the advertised contract is up the swing AND back down — a
        # dead scale-down path (capacity stuck at the ceiling after
        # the trough returns) must go red here
        and server.stats.scale_downs >= 1
        and mesh_ok
        and report.storm_disconnects > 0
        and balance["ok"]
        and acct["balanced"]
        and acct["pending"] == 0
        and ccontroller.worker_adds >= 1
        and ccontroller.worker_retires >= 1
        and cbalance["ok"]
        and c_acct["balanced"]
        and c_acct["pending"] == 0
        and windows_lost == 0
    )
    return {
        "ok": ok,
        "swing": round(
            report.peak_active / max(report.trough_active, 1), 1
        ),
        "resizes": server.stats.resizes,
        "scale_ups": server.stats.scale_ups,
        "scale_downs": server.stats.scale_downs,
        "mesh_devices": devices_seen["max"],
        "p99_ms": p99,
        "shed_rate": shed_rate,
        "windows_lost": windows_lost,
        "storm_disconnects": report.storm_disconnects,
        "connects": report.connects,
        "disconnects": report.disconnects,
        "events": len(events),
        "worker_adds": ccontroller.worker_adds,
        "worker_retires": ccontroller.worker_retires,
        "workers": c_stats["workers"],
        "cluster_migrated": c_stats["migrated_sessions"],
        "balanced_every_round": balance["ok"] and cbalance["ok"],
    }


class _DispatchCost:
    """Deterministic dispatch-cost model on the injected clock: every
    dispatch attempt charges a fixed launch/RTT cost plus a per-window
    compute cost (``base_ms + per_window_ms × k``), advancing the
    FakeClock instead of sleeping.  This is the capacity tradeoff the
    bench lane measures, made reproducible: small batches pay the
    fixed cost many times over at peak load, large batches pay the
    coalescing wait at trough load — and windows/s stays a wall-clock
    measurement, untouched by the fake latency."""

    def __init__(self, clock, base_ms: float, per_window_ms: float):
        self.clock = clock
        self.base_ms = float(base_ms)
        self.per_window_ms = float(per_window_ms)
        self.dispatches = 0

    def __call__(self, windows) -> None:
        self.dispatches += 1
        self.clock.advance(
            (self.base_ms + self.per_window_ms * len(windows)) / 1e3
        )


def elastic_traffic_benchmark(
    n_runs: int = 3, smoke: bool = False, seed: int = 0
) -> dict:
    """The ``elastic_traffic`` bench lane's measurement: the same
    seeded 10× diurnal swing (storm + slow clients + mixed rates)
    served three ways — a static floor configuration, a static ceiling
    configuration, and the autoscaled run — under a deterministic
    dispatch-cost model on the FakeClock (event p99 and shed rate are
    exactly reproducible; windows/s is wall time).

    The lane's claim: the autoscaled run beats the BEST static
    configuration on p99 or shed rate at equal windows/s across the
    swing (``beats_static``), because no single static batch size wins
    both ends — the floor pays the per-dispatch launch cost dozens of
    times over at peak, the ceiling pays the coalescing deadline at
    every sub-peak round."""
    import time

    from har_tpu.serve.engine import FleetConfig, FleetServer
    from har_tpu.serve.faults import FakeClock
    from har_tpu.serve.loadgen import AnalyticDemoModel
    from har_tpu.serve.traffic.autoscale import (
        AutoscaleConfig,
        CapacityController,
    )
    from har_tpu.serve.traffic.generate import (
        TraceSpec,
        TrafficTrace,
        drive_trace,
    )

    spec = TraceSpec(
        kind="storm",
        peak_sessions=48 if smoke else 192,
        swing=10.0,
        rounds=24 if smoke else 48,
        period=24 if smoke else 48,
        storms=((16 if smoke else 32, 0.5),),
        slow_prob=0.05,
        slow_rounds=2,
        rate_mix=(1, 1, 2),
        seed=seed,
    )
    trace = TrafficTrace(spec)
    floor_tb, ceil_tb = 16, 256
    # per-dispatch launch/RTT charge (a conservative third of the
    # documented ~30 ms remote-tunnel RTT) + per-window compute charge
    base_ms, per_window_ms = 10.0, 0.1
    configs = {
        "static_floor": {"target_batch": floor_tb, "autoscale": False},
        "static_ceiling": {"target_batch": ceil_tb, "autoscale": False},
        "autoscaled": {"target_batch": floor_tb, "autoscale": True},
    }

    def one_run(cfg):
        clock = FakeClock()
        cost = _DispatchCost(clock, base_ms, per_window_ms)
        server = FleetServer(
            AnalyticDemoModel(),
            window=200,
            hop=200,
            smoothing="ema",
            config=FleetConfig(
                max_sessions=4096,
                target_batch=cfg["target_batch"],
                max_delay_ms=50.0,
            ),
            fault_hook=cost,
            clock=clock,
        )
        controller = None
        if cfg["autoscale"]:
            controller = CapacityController(
                server,
                config=AutoscaleConfig(
                    min_target_batch=floor_tb,
                    # the operator-sized ceiling: the largest batch
                    # whose one-dispatch cost still clears the SLO —
                    # the ladder's job is to find the best rung UNDER
                    # it, not to chase the backlog into a batch size
                    # that trades stacking for coalescing waits
                    max_target_batch=128,
                    max_depth=1,
                    queue_high=1.0,
                    util_low=0.5,
                    up_after=2,
                    down_after=4,
                    cooldown_s=0.0,
                ),
                clock=clock,
            )
        t0 = time.perf_counter()
        _events, _report = drive_trace(
            server,
            trace,
            clock=clock,
            round_dt=0.05,  # one 20 Hz hop of wall time per round
            on_round=(
                controller.on_round if controller is not None else None
            ),
        )
        duration = time.perf_counter() - t0
        snap = server.stats_snapshot()
        acct = snap["accounting"]
        return {
            "windows_per_sec": (
                acct["scored"] / duration if duration else 0.0
            ),
            "p99_ms": snap["stages"]["event_ms"].get("p99_ms") or 0.0,
            "shed_rate": (
                acct["dropped"] / acct["enqueued"]
                if acct["enqueued"]
                else 0.0
            ),
            "resizes": snap["resizes"],
            "contract_ok": bool(
                acct["balanced"]
                and acct["pending"] == 0
                and undeclared_drops(snap) == 0
            ),
        }

    rows = {}
    for name, cfg in configs.items():
        runs = [one_run(cfg) for _ in range(n_runs)]
        rows[name] = {
            "target_batch": cfg["target_batch"],
            "autoscale": cfg["autoscale"],
            "n_runs": n_runs,
            "windows_per_sec_median": round(
                float(np.median([r["windows_per_sec"] for r in runs])), 1
            ),
            "windows_per_sec_std": round(
                float(np.std([r["windows_per_sec"] for r in runs])), 1
            ),
            # fake-clock latencies: identical across runs by seeding
            "p99_ms_median": round(
                float(np.median([r["p99_ms"] for r in runs])), 3
            ),
            "shed_rate_median": round(
                float(np.median([r["shed_rate"] for r in runs])), 4
            ),
            "resizes": runs[-1]["resizes"],
            "contract_ok": all(r["contract_ok"] for r in runs),
        }
    auto = rows["autoscaled"]
    statics = [rows["static_floor"], rows["static_ceiling"]]
    best_static_p99 = min(r["p99_ms_median"] for r in statics)
    best_static_shed = min(r["shed_rate_median"] for r in statics)
    best_static_wps = max(r["windows_per_sec_median"] for r in statics)
    # "at equal windows/s": every configuration scores the same offered
    # load, so throughput parity is a wall-clock measurement with noise
    # — the autoscaled median must stay within this declared tolerance
    # of the best static's, and the measured ratio is stamped so the
    # tolerance is never hidden in the verdict.  Smoke-scale runs last
    # ~100 ms wall; their parity draw is pure noise (measured swinging
    # 0.73–0.95 on identical inputs), so smoke mode stamps the ratio
    # but excludes it from the verdict — the p99/shed comparison stays
    # exactly reproducible (fake clock) at any scale
    parity_floor = 0.9
    parity_checked = not smoke
    wps_parity = round(
        auto["windows_per_sec_median"] / best_static_wps, 3
    ) if best_static_wps else 0.0
    return {
        "trace": trace.spec(),
        "swing": round(
            trace.peak_active / max(trace.trough_active, 1), 1
        ),
        "dispatch_cost_model": {
            "base_ms": base_ms, "per_window_ms": per_window_ms,
        },
        "configs": rows,
        "best_static_p99_ms": best_static_p99,
        "best_static_shed_rate": best_static_shed,
        "windows_per_sec_parity": wps_parity,
        "parity_floor": parity_floor,
        "parity_checked": parity_checked,
        "beats_static": bool(
            (
                auto["p99_ms_median"] < best_static_p99
                or auto["shed_rate_median"] < best_static_shed
            )
            and (wps_parity >= parity_floor or not parity_checked)
        ),
        "contract_ok": all(r["contract_ok"] for r in rows.values()),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(elastic_smoke()))
