"""Weight-only int8 post-training quantization for the neural families.

The reference has no deployment pipeline at all (models die with the
Spark driver, `Main/main.py:115-130`); har_tpu adds checkpoints, a
serving path and StableHLO export — this module adds the size/bandwidth
lever on top: every ``kernel`` weight is stored int8 with a per-output-
channel float scale (symmetric, 4x smaller), and the forward pass
dequantizes on the fly.

TPU rationale (weight-ONLY, not activation quant):
  - The HAR models are small and latency/bandwidth-bound at serving
    batch sizes; what int8 buys is 4x smaller weight STORAGE (the
    checkpoint-free exported artifact ships int8 weights; the live
    jitted path constant-folds the dequant back to f32 at trace time)
    — not MXU int8 throughput, which would need activation quant and
    per-batch calibration for accuracy risk with no measurable win at
    these shapes.
  - Dequantization is ``int8 -> f32 * scale`` fused by XLA into the
    consuming matmul/conv (one elementwise op in VMEM); compute stays
    bf16/f32 on the MXU, so accuracy loss is bounded by weight rounding
    alone (per-channel scales keep that ~1e-2 relative).
  - Composes with ``har_tpu.export``: a quantized model's weights ship
    int8 in the artifact (as weight inputs + npz — see export_parts for
    why not constants), shrinking the artifact ~1.7x end-to-end (the
    StableHLO bytecode already stores f32 constants compactly; the raw
    weight bytes themselves shrink the full 4x).

``quantize_model(model)`` → ``QuantizedModel`` implementing the
ClassifierModel protocol (transform → Predictions), so it drops into
evaluation, serving, and export unchanged.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def _leaf_name(path) -> str:
    last = path[-1]
    return str(getattr(last, "key", getattr(last, "name", last)))


@dataclasses.dataclass(frozen=True)
class _Stored:
    """One parameter leaf: int8+scale when quantized, raw otherwise."""

    kind: str  # "q8" | "f"
    value: np.ndarray  # int8 weights or the original array
    scale: np.ndarray | None  # per-output-channel f32 (q8 only)


@dataclasses.dataclass
class QuantizedModel:
    """A neural model with int8 kernels, ClassifierModel-compatible."""

    module: object
    treedef: object
    stored: list[_Stored]
    scaler: object | None
    num_classes: int

    def __post_init__(self):
        self._jit_predict = None

    def dequantized_params(self):
        """The parameter pytree with kernels reconstructed as f32."""
        import jax
        import jax.numpy as jnp

        leaves = []
        for s in self.stored:
            if s.kind == "q8":
                # NOTE: on concrete closed-over arrays these ops run
                # EAGERLY even under a jit trace, so the live-serving
                # program embeds the folded f32 weights — accuracy and
                # storage-on-disk are the live wins, not device memory.
                # The export path keeps weights int8 end-to-end by
                # making them program INPUTS instead (export_parts).
                leaves.append(
                    jnp.asarray(s.value).astype(jnp.float32)
                    * jnp.asarray(s.scale)
                )
            else:
                leaves.append(jnp.asarray(s.value))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def predict_fn(self):
        """x -> (logits, probs), scaler folded in — the transform core.

        Uses export.make_predict_core so the contract is shared with
        every exported artifact.  See dequantized_params: in this LIVE
        path the dequant folds to f32 constants at trace time; int8
        persists end-to-end only through export_parts' weight-input
        form.
        """
        from har_tpu.export import make_predict_core

        core = make_predict_core(self.module, self.scaler)
        return lambda x: core(self.dequantized_params(), x)

    def export_parts(self):
        """(predict(weights, x), weights) for har_tpu.export.

        Inside a jit trace, ops on closed-over CONCRETE arrays run
        eagerly — a baked-in int8 constant would be dequantized at trace
        time and re-embedded as f32, un-shrinking the artifact.  So the
        exported program takes the weight leaves as INPUTS (the convert
        is then a traced op on an int8 operand) and export_model stores
        them alongside as an int8 npz.
        """
        import jax
        import jax.numpy as jnp

        from har_tpu.export import make_predict_core

        core = make_predict_core(self.module, self.scaler)
        stored = self.stored
        treedef = self.treedef

        def predict(weight_leaves, x):
            leaves = []
            for s, w in zip(stored, weight_leaves):
                if s.kind == "q8":
                    leaves.append(
                        w.astype(jnp.float32) * jnp.asarray(s.scale)
                    )
                else:
                    leaves.append(w)
            return core(
                jax.tree_util.tree_unflatten(treedef, leaves), x
            )

        return predict, [s.value for s in self.stored]

    def transform(self, data):
        import jax

        from har_tpu.models.base import Predictions

        if self._jit_predict is None:
            self._jit_predict = jax.jit(self.predict_fn())
        x = data.features if hasattr(data, "features") else data
        logits, probs = self._jit_predict(np.asarray(x, np.float32))
        return Predictions.from_raw(logits, probs)

    def size_report(self) -> dict:
        """Weight-storage accounting: int8+scales vs the f32 original."""
        q_bytes = f_bytes = 0
        n_q = 0
        for s in self.stored:
            orig = s.value.size * 4  # all trained params are f32
            f_bytes += orig
            if s.kind == "q8":
                n_q += 1
                q_bytes += s.value.size + s.scale.size * 4
            else:
                q_bytes += orig
        return {
            "quantized_kernels": n_q,
            "float_bytes": f_bytes,
            "quantized_bytes": q_bytes,
            "ratio": round(q_bytes / f_bytes, 4) if f_bytes else None,
        }


def _q8(w: np.ndarray) -> _Stored:
    """Symmetric per-output-channel int8 storage of one >=2-dim weight
    (last axis = output features in flax's Dense/Conv layout) — THE
    quantization arithmetic shared by the export path
    (``quantize_model``) and the serving tier (``quantize_serving``),
    so the two cannot round differently."""
    scale = np.abs(w).max(axis=tuple(range(w.ndim - 1))) / 127.0
    scale = np.where(scale > 0, scale, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return _Stored("q8", q, scale)


def quantize_model(model) -> QuantizedModel:
    """Weight-only int8 quantization of a fitted neural model.

    ``model`` is a ``NeuralClassifierModel`` (scaler carried over) or a
    bare ``NeuralModel``.  Every ``kernel`` leaf with >=2 dims is stored
    int8 with a symmetric per-output-channel scale (last axis = output
    features in flax's Dense/Conv layout); biases and norm parameters
    stay f32 — they are a rounding-sensitive sliver of the bytes.
    """
    import jax

    inner = getattr(model, "inner", model)
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(
        inner.params
    )
    stored: list[_Stored] = []
    for path, leaf in leaves_with_path:
        w = np.asarray(leaf)
        if _leaf_name(path) == "kernel" and w.ndim >= 2:
            stored.append(_q8(w))
        else:
            stored.append(_Stored("f", w, None))
    return QuantizedModel(
        module=inner.module,
        treedef=treedef,
        stored=stored,
        scaler=getattr(model, "scaler", None),
        num_classes=int(model.num_classes),
    )


class _Int8Inner:
    """The ``(_predict, params)`` pair the dispatch plane serves —
    weight leaves device-resident in their STORED dtype (int8 kernels +
    f32 rest, the same weight-input form ``export_parts`` ships), with
    the dequant a traced op inside the jitted logits program.  XLA
    fuses the ``int8 → f32 × scale`` convert into the consuming matmul;
    the weights never exist as f32 in device memory at rest."""

    supports_fused = True  # plain jit chain: the fused program traces it

    def __init__(self, base_predict, treedef, stored):
        import jax
        import jax.numpy as jnp

        scales = [
            None if s.kind != "q8" else jnp.asarray(s.scale)
            for s in stored
        ]

        def logits(leaves, x):
            rebuilt = [
                w.astype(jnp.float32) * sc if sc is not None else w
                for w, sc in zip(leaves, scales)
            ]
            return base_predict(
                jax.tree_util.tree_unflatten(treedef, rebuilt), x
            )

        # device-resident once at build: every dispatch reuses the int8
        # buffers instead of re-uploading the weight set per call
        self.params = [jax.device_put(s.value) for s in stored]
        self._predict = jax.jit(logits)


@dataclasses.dataclass
class Int8ServingModel:
    """The int8 SERVING tier: a DeviceScorer-compatible wrapper
    (``scaler`` + ``inner`` exposing ``_predict``/``params``) whose
    weights live int8 on device, built by ``quantize_serving``.

    Drops into ``serve.dispatch.make_scorer(model, tier="int8")`` — and
    therefore into pipelining, mesh sharding, the fused hot loop and
    the adaptation engine's shadow/swap machinery — exactly like a f32
    model: ``_split_predict`` unwraps ``scaler``/``inner`` the same way
    it unwraps ``NeuralClassifierModel``.  ``transform`` is the
    synchronous reference path (ShadowEvaluator scores candidates
    through it), same op order as the async launch+fetch chain.
    """

    inner: _Int8Inner
    scaler: object | None
    num_classes: int
    stored: list
    tunnel_rtt_ms: float = 0.0
    int8_weights: bool = True

    def transform(self, x):
        import jax

        from har_tpu.models.base import Predictions

        x = np.asarray(x, np.float32)
        if self.scaler is not None:
            x = self.scaler.transform(x)
        # softmax on the DEVICE logits before fetching: one transfer
        # each way (ShadowEvaluator scores every mirrored batch through
        # here during int8 promotion — a host round trip of the logits
        # just to re-upload them for softmax would be pure waste)
        dev_logits = self.inner._predict(
            self.inner.params, jax.device_put(x)
        )
        probs = np.asarray(jax.nn.softmax(dev_logits, axis=-1))
        return Predictions.from_raw(np.asarray(dev_logits), probs)

    def size_report(self) -> dict:
        """Same accounting as QuantizedModel.size_report."""
        q_bytes = f_bytes = 0
        n_q = 0
        for s in self.stored:
            orig = s.value.size * 4
            f_bytes += orig
            if s.kind == "q8":
                n_q += 1
                q_bytes += s.value.size + s.scale.size * 4
            else:
                q_bytes += orig
        return {
            "quantized_kernels": n_q,
            "float_bytes": f_bytes,
            "quantized_bytes": q_bytes,
            "ratio": round(q_bytes / f_bytes, 4) if f_bytes else None,
        }


def quantize_serving(model) -> Int8ServingModel:
    """Weight-only int8 quantization of any DEVICE-servable model — the
    serving-tier entry point behind ``make_scorer(..., tier="int8")``
    and ``AdaptationEngine.propose_int8``.

    Unlike ``quantize_model`` (which rebuilds a flax ``module.apply``
    chain and therefore covers the NeuralModel families only), this
    wraps whatever jitted ``(_predict, params)`` pair the dispatch
    plane would serve — a trained checkpoint, the jitted demo MLP, any
    scorer-compatible model — and quantizes every >=2-dim float leaf
    (kernels; biases/norms stay f32) with the shared ``_q8``
    arithmetic.  Raises ValueError for host-only models: there is no
    device program to quantize.
    """
    import jax

    from har_tpu.serve.dispatch import _split_predict

    pre, inner = _split_predict(model)
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(
        inner.params
    )
    stored: list[_Stored] = []
    for _path, leaf in leaves_with_path:
        w = np.asarray(leaf)
        if w.ndim >= 2 and np.issubdtype(w.dtype, np.floating):
            stored.append(_q8(w))
        else:
            stored.append(_Stored("f", w, None))
    if not any(s.kind == "q8" for s in stored):
        # nothing quantizable: an exported StableHLO artifact (weights
        # baked into the program, or already int8) or a kernel-less
        # model — refuse loudly instead of minting a no-op "int8" tier
        # (and instead of re-jitting an exported call, which is not
        # re-traceable under a surrounding jit)
        raise ValueError(
            "nothing to quantize: the model exposes no >=2-dim float "
            f"weight leaves ({type(model).__name__}) — quantize before "
            "export (har export --quantize int8), or serve the f32 tier"
        )
    num_classes = getattr(model, "num_classes", None)
    if num_classes is None:
        # fall back to the logits width of the last QUANTIZED kernel
        # (the output head) — the last tree leaf of any kind could be
        # a trailing bias/norm leaf with a hidden width
        num_classes = int(
            next(
                s for s in reversed(stored) if s.kind == "q8"
            ).value.shape[-1]
        )
    return Int8ServingModel(
        inner=_Int8Inner(inner._predict, treedef, stored),
        scaler=pre,
        num_classes=int(num_classes),
        stored=stored,
        tunnel_rtt_ms=float(getattr(model, "tunnel_rtt_ms", 0.0) or 0.0),
    )
