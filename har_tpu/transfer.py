"""Transfer learning: fine-tune a saved checkpoint on new data.

The reference trains from scratch every run and persists nothing
(`Main/main.py:115-130`; SURVEY §5.4) — but the paper's deployment
story (continuous monitoring of a specific wearer) is exactly the
setting where a model pretrained on the cohort should be ADAPTED to the
individual: a few minutes of the wearer's labeled windows, not a
retrain.  ``fine_tune`` is that path:

  - warm-starts the trainer from the checkpoint's parameters (the
    fresh-init tree is kept as a structural template, so an
    architecture mismatch fails loudly);
  - reuses the checkpoint's OWN scaler — refitting statistics on a
    small adaptation set would shift the input distribution under the
    pretrained features;
  - optionally freezes parameter subtrees (``freeze=("ConvBlock_0",)``)
    via an ``optax.masked`` wrapper around the standard optimizer, so
    a small adaptation set tunes the head without washing out the
    pretrained feature extractor.

Everything else (scanned whole-run program, schedule, SPMD mesh) is the
ordinary ``train.Trainer`` — fine-tuning is a starting point and a
gradient mask, not a second training stack.
"""

from __future__ import annotations

import numpy as np


def freeze_mask(params, freeze: tuple[str, ...]):
    """Per-leaf trainability pytree: False under any top-level module
    named in ``freeze``, True elsewhere."""
    import jax

    unknown = set(freeze) - set(params.keys())
    if unknown:
        raise ValueError(
            f"freeze names {sorted(unknown)} not in params "
            f"(top-level modules: {sorted(params.keys())})"
        )
    return {
        k: jax.tree.map(lambda _: k not in freeze, sub)
        for k, sub in params.items()
    }


def fine_tune(
    checkpoint_path: str,
    data,
    config=None,
    *,
    mesh=None,
    freeze: tuple[str, ...] = (),
    model=None,
):
    """Fine-tuned ``NeuralClassifierModel`` from a saved checkpoint.

    ``data`` is a FeatureSet (or anything with ``features``/``label``)
    of NEW examples in the checkpoint's input space; ``config`` is a
    TrainerConfig for the adaptation run (short schedules and lower
    learning rates are the norm — default: 20 epochs at lr/10).
    """
    import jax
    import optax

    from har_tpu.checkpoint import load_model
    from har_tpu.models.neural_classifier import NeuralClassifierModel
    from har_tpu.train.trainer import (
        Trainer,
        TrainerConfig,
        make_optimizer,
    )

    if model is None:  # caller may pass the already-restored model
        model = load_model(checkpoint_path)
    if config is None:
        config = TrainerConfig(epochs=20, learning_rate=3e-4)

    x = np.asarray(
        data.features if hasattr(data, "features") else data[0], np.float32
    )
    y = np.asarray(
        data.label if hasattr(data, "label") else data[1], np.int32
    )
    if len(y) and (y.max() >= model.num_classes or y.min() < 0):
        # fail loudly: under jit the one-hot gather would silently CLAMP
        # out-of-range labels onto the last class and train toward it
        raise ValueError(
            f"adaptation labels span [{y.min()}, {y.max()}] but the "
            f"checkpoint has {model.num_classes} classes"
        )
    if model.scaler is not None:
        # the checkpoint's own statistics — never refit on the small
        # adaptation set
        x = model.scaler.transform(x)

    optimizer_factory = None
    if freeze:
        mask = freeze_mask(model.inner.params, tuple(freeze))

        def optimizer_factory(cfg, total_steps):
            # frozen leaves must receive EXACTLY zero updates: masking
            # the whole optimizer (not just the grads) keeps adamw's
            # decoupled weight decay and Adam moments off them too
            return optax.chain(
                optax.masked(make_optimizer(cfg, total_steps), mask),
                optax.masked(
                    optax.set_to_zero(),
                    jax.tree.map(lambda t: not t, mask),
                ),
            )

        # stable checkpoint-fingerprint identity: runs with different
        # freeze sets must not resume each other's snapshots
        optimizer_factory.fingerprint_tag = f"freeze:{sorted(freeze)}"

    trained = Trainer(
        model.inner.module,
        config,
        mesh=mesh,
        optimizer_factory=optimizer_factory,
    ).fit(
        x, y,
        num_classes=model.num_classes,
        init_params=model.inner.params,
    )
    return NeuralClassifierModel(
        inner=trained,
        scaler=model.scaler,
        num_classes=model.num_classes,
    )
