"""StringIndexer: frequency-descending vocabulary → integer index.

Matches MLlib semantics used by the reference (Main/main.py:52-61): labels
ordered by descending frequency — for WISDM ACTIVITY the mapping is
Walking=0, Jogging=1, Upstairs=2, Downstairs=3, Sitting=4, Standing=5
(reference result.txt class counts).

Equal-count ties: MLlib keeps whatever order ``countByValue().toSeq``
yields — the scala immutable.HashMap trie iteration order.
``tie_break="spark_hash"`` reproduces it bit-for-bit (so one-hot indices
match the reference's feature vectors); ``"lexicographic"`` is the
readable default for standalone use.
"""

from __future__ import annotations

import numpy as np

from har_tpu.features.pipeline import ColumnSpace, FrameLike, as_columns


class StringIndexer:
    def __init__(
        self,
        input_col: str,
        output_col: str,
        handle_invalid: str = "error",  # error | keep (extra bucket)
        tie_break: str = "lexicographic",  # lexicographic | spark_hash
    ):
        self.input_col = input_col
        self.output_col = output_col
        if handle_invalid not in ("error", "keep"):
            raise ValueError(f"handle_invalid={handle_invalid!r}")
        if tie_break not in ("lexicographic", "spark_hash"):
            raise ValueError(f"tie_break={tie_break!r}")
        self.handle_invalid = handle_invalid
        self.tie_break = tie_break

    def fit(self, frame: FrameLike) -> "StringIndexerModel":
        col = as_columns(frame)[self.input_col]
        if self.tie_break == "spark_hash":
            from har_tpu.data.spark_split import mllib_vocab

            ranks = mllib_vocab([str(v) for v in col])
            vocab = tuple(
                v for v, _ in sorted(ranks.items(), key=lambda kv: kv[1])
            )
        else:
            values, counts = np.unique(col.astype(str), return_counts=True)
            order = np.lexsort((values, -counts))  # freq desc, then lex
            vocab = tuple(str(values[i]) for i in order)
        return StringIndexerModel(
            self.input_col, self.output_col, vocab, self.handle_invalid
        )


class StringIndexerModel:
    def __init__(
        self,
        input_col: str,
        output_col: str,
        vocab: tuple[str, ...],
        handle_invalid: str = "error",
    ):
        self.input_col = input_col
        self.output_col = output_col
        self.vocab = vocab
        self.handle_invalid = handle_invalid
        self._index = {v: i for i, v in enumerate(vocab)}

    @property
    def cardinality(self) -> int:
        return len(self.vocab)

    def transform(self, frame: FrameLike) -> ColumnSpace:
        columns = as_columns(frame)
        col = columns[self.input_col].astype(str)
        unseen_bucket = len(self.vocab)
        idx = np.fromiter(
            (self._index.get(v, unseen_bucket) for v in col),
            dtype=np.int32,
            count=len(col),
        )
        if self.handle_invalid == "error" and np.any(idx == unseen_bucket):
            bad = sorted(set(col[idx == unseen_bucket]))[:5]
            raise ValueError(
                f"unseen labels in column {self.input_col!r}: {bad}"
            )
        columns[self.output_col] = idx
        return columns
