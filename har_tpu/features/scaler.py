"""Feature standardization (the analogue of MLlib's StandardScaler).

MLlib's LogisticRegression standardizes internally (mirrored inside
har_tpu.models.logistic_regression); neural models need it explicitly —
the 43 WISDM features span ~0.1 histogram fractions to hundreds-of-ms
peak gaps, and an unscaled MLP barely trains.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class StandardScaler:
    """fit → (mean, std); transform → (x - mean) / std, zero-variance
    columns pass through centered."""

    with_mean: bool = True
    with_std: bool = True

    def fit(self, x: np.ndarray) -> "FittedScaler":
        x = np.asarray(x, np.float32)
        mean = x.mean(axis=0) if self.with_mean else np.zeros(x.shape[1], np.float32)
        if self.with_std:
            std = x.std(axis=0, ddof=1)
            std = np.where(std > 0, std, 1.0).astype(np.float32)
        else:
            std = np.ones(x.shape[1], np.float32)
        return FittedScaler(mean=mean.astype(np.float32), std=std)


@dataclasses.dataclass(frozen=True)
class FittedScaler:
    mean: np.ndarray
    std: np.ndarray

    def transform(self, x: np.ndarray) -> np.ndarray:
        return ((np.asarray(x, np.float32) - self.mean) / self.std).astype(
            np.float32
        )
