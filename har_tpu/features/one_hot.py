"""OneHotEncoder with MLlib's dropLast semantics.

The reference's OneHotEncoderEstimator (Main/main.py:52-58) defaults to
``dropLast=true``: a column of cardinality k becomes a (k-1)-dim vector and
the last vocabulary index encodes as all-zeros.  That is what yields
934+1401+755 = 3,090 one-hot dims for the PEAK columns (SURVEY §2 F).

The encoder itself is a pure transformer parameterized by the input
cardinality; ``fit`` just reads the max index, like MLlib's estimator.
"""

from __future__ import annotations

import numpy as np

from har_tpu.features.pipeline import ColumnSpace, FrameLike, as_columns


def one_hot_matrix(
    indices: np.ndarray, cardinality: int, drop_last: bool = True
) -> np.ndarray:
    width = cardinality - 1 if drop_last else cardinality
    out = np.zeros((len(indices), width), dtype=np.float32)
    valid = indices < width
    out[np.nonzero(valid)[0], indices[valid]] = 1.0
    return out


class OneHotEncoder:
    def __init__(self, input_col: str, output_col: str, drop_last: bool = True):
        self.input_col = input_col
        self.output_col = output_col
        self.drop_last = drop_last

    def fit(self, frame: FrameLike) -> "OneHotEncoderModel":
        idx = as_columns(frame)[self.input_col]
        cardinality = int(idx.max()) + 1 if len(idx) else 0
        return OneHotEncoderModel(
            self.input_col, self.output_col, cardinality, self.drop_last
        )


class OneHotEncoderModel:
    def __init__(
        self,
        input_col: str,
        output_col: str,
        cardinality: int,
        drop_last: bool = True,
    ):
        self.input_col = input_col
        self.output_col = output_col
        self.cardinality = cardinality
        self.drop_last = drop_last

    @property
    def width(self) -> int:
        return self.cardinality - 1 if self.drop_last else self.cardinality

    def transform(self, frame: FrameLike) -> ColumnSpace:
        columns = as_columns(frame)
        idx = columns[self.input_col]
        columns[self.output_col] = one_hot_matrix(
            idx, self.cardinality, self.drop_last
        )
        return columns
