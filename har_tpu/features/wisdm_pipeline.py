"""The WISDM feature pipeline, assembled like the reference's.

Reference Main/main.py:51-73: for each PEAK column a StringIndexer +
OneHotEncoder, a label StringIndexer for ACTIVITY, then a VectorAssembler
over the three one-hot vectors plus the 10 numeric columns.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from har_tpu.data.table import Table
from har_tpu.features.assembler import VectorAssembler
from har_tpu.features.one_hot import OneHotEncoder
from har_tpu.features.pipeline import ColumnSpace, Pipeline, PipelineModel
from har_tpu.features.string_indexer import StringIndexer
from har_tpu.data.wisdm import (
    LABEL_COLUMN,
    WISDM_CATEGORICAL_COLUMNS,
    WISDM_NUMERIC_COLUMNS,
)


@dataclasses.dataclass(frozen=True)
class FeatureSet:
    """Device-ready arrays produced by the pipeline."""

    features: np.ndarray  # (n, d) float32
    label: np.ndarray  # (n,) int32
    uid: np.ndarray | None = None
    # label id -> display name, from the SAME indexer fit that produced
    # `label` (so reports can never mislabel classes); None when the
    # source has no name vocabulary
    class_names: tuple[str, ...] | None = None
    # original-table row indices this set was carved from (set by the
    # split paths, in sampled-stream order) — lets the report render the
    # reference's train/test show(5) tables; None once re-indexed
    rows: np.ndarray | None = None
    # float64 sparse design for this split (models.mllib_exact.ExactDesign),
    # attached by the spark-exact split path; the bit-exact MLlib replay
    # estimators train from it (float32 device features drop the low
    # bits MLlib's L-BFGS trajectory depends on).  Dropped by take().
    exact: object | None = None

    def __len__(self) -> int:
        return len(self.features)

    @property
    def num_features(self) -> int:
        return self.features.shape[1]

    def take(self, indices: np.ndarray) -> "FeatureSet":
        return FeatureSet(
            features=self.features[indices],
            label=self.label[indices],
            uid=None if self.uid is None else self.uid[indices],
            class_names=self.class_names,
        )

    def split(self, fractions, seed: int) -> list["FeatureSet"]:
        from har_tpu.data.split import split_indices

        return [
            dataclasses.replace(self.take(idx), rows=idx)
            for idx in split_indices(len(self), fractions, seed)
        ]

    def train_test(
        self, train_fraction: float, seed: int
    ) -> tuple["FeatureSet", "FeatureSet"]:
        """Bernoulli train/test split.  Tabular-WISDM paths must go
        through runner.derive_split instead (which routes to the
        spark-exact replay per DataConfig.split_method and falls back
        here) — every evaluation path sharing one derivation is what
        keeps scoring on the same held-out rows."""
        train, test = self.split(
            [train_fraction, 1.0 - train_fraction], seed=seed
        )
        return train, test


def build_wisdm_pipeline(
    categorical: tuple[str, ...] = WISDM_CATEGORICAL_COLUMNS,
    numeric: tuple[str, ...] = WISDM_NUMERIC_COLUMNS,
    label: str = LABEL_COLUMN,
) -> Pipeline:
    stages: list = []
    assembled: list[str] = []
    for col in categorical:
        # spark_hash tie-break: equal-count vocabulary entries keep
        # MLlib's order, so one-hot indices equal the reference's
        # feature vectors bit-for-bit (result.txt:110-137)
        stages.append(
            StringIndexer(
                col, f"{col}_index",
                handle_invalid="keep", tie_break="spark_hash",
            )
        )
        stages.append(OneHotEncoder(f"{col}_index", f"{col}_vec"))
        assembled.append(f"{col}_vec")
    stages.append(StringIndexer(label, "label"))
    stages.append(VectorAssembler(assembled + list(numeric), "features"))
    return Pipeline(stages)


def make_feature_set(
    columns: ColumnSpace, class_names: tuple[str, ...] | None = None
) -> FeatureSet:
    return FeatureSet(
        features=np.ascontiguousarray(columns["features"], dtype=np.float32),
        label=columns["label"].astype(np.int32),
        uid=columns.get("UID"),
        class_names=class_names,
    )


def fit_transform(
    pipeline: Pipeline, train: Table, *others: Table
) -> tuple[PipelineModel, list[FeatureSet]]:
    """Fit on `train`, transform train + others (reference fits the pipeline
    on the full df before splitting — Main/main.py:68-80; callers choose)."""
    model = pipeline.fit(train)
    sets = [make_feature_set(model.transform(t)) for t in (train, *others)]
    return model, sets
