from har_tpu.features.pipeline import Pipeline, PipelineModel, Estimator, Transformer
from har_tpu.features.string_indexer import StringIndexer, StringIndexerModel
from har_tpu.features.one_hot import OneHotEncoder, OneHotEncoderModel
from har_tpu.features.assembler import VectorAssembler
from har_tpu.features.wisdm_pipeline import build_wisdm_pipeline, FeatureSet, make_feature_set

__all__ = [
    "Pipeline",
    "PipelineModel",
    "Estimator",
    "Transformer",
    "StringIndexer",
    "StringIndexerModel",
    "OneHotEncoder",
    "OneHotEncoderModel",
    "VectorAssembler",
    "build_wisdm_pipeline",
    "FeatureSet",
    "make_feature_set",
]
