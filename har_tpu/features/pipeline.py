"""Composable feature pipeline.

The MLlib Pipeline the reference builds (reference Main/main.py:68-73) is a
list of estimators/transformers fitted in order, each adding columns to a
DataFrame.  Here the "frame" is a plain ``dict[str, np.ndarray]`` column
space (2-D arrays represent vector columns); fitting is host-side vocabulary
building, and transformation is vectorized numpy feeding device arrays.
All per-row work that MLlib runs on JVM executors becomes array ops.
"""

from __future__ import annotations

from typing import Mapping, Protocol, Union

import numpy as np

from har_tpu.data.table import Table

ColumnSpace = dict[str, np.ndarray]
FrameLike = Union[Table, Mapping[str, np.ndarray]]


def as_columns(frame: FrameLike) -> ColumnSpace:
    if isinstance(frame, Table):
        return {n: frame.column(n) for n in frame.column_names}
    return dict(frame)


class Transformer(Protocol):
    def transform(self, columns: FrameLike) -> ColumnSpace: ...


class Estimator(Protocol):
    def fit(self, columns: FrameLike) -> Transformer: ...


class Pipeline:
    """Ordered stages; estimators are fitted on the running column space."""

    def __init__(self, stages: list):
        self.stages = list(stages)

    def fit(self, frame: FrameLike) -> "PipelineModel":
        columns = as_columns(frame)
        fitted = []
        for stage in self.stages:
            if hasattr(stage, "fit"):
                model = stage.fit(columns)
            else:
                model = stage
            fitted.append(model)
            columns = model.transform(columns)
        return PipelineModel(fitted)


class PipelineModel:
    def __init__(self, stages: list):
        self.stages = list(stages)

    def transform(self, frame: FrameLike) -> ColumnSpace:
        columns = as_columns(frame)
        for stage in self.stages:
            columns = stage.transform(columns)
        return columns
