"""VectorAssembler: concatenate vector/numeric columns into one matrix.

Replaces reference Main/main.py:63-66.  Column order is preserved, so for
WISDM the layout is [XPEAK one-hot | YPEAK one-hot | ZPEAK one-hot | 10
numeric] = 3,100 dims, matching the reference's sparse vectors.  Output is a
dense float32 matrix: at this scale a dense design matrix is both smaller
than Spark's JVM sparse rows and the MXU-friendly layout for the models.
"""

from __future__ import annotations

import numpy as np

from har_tpu.features.pipeline import ColumnSpace, FrameLike, as_columns


class VectorAssembler:
    def __init__(self, input_cols: list[str], output_col: str = "features"):
        self.input_cols = list(input_cols)
        self.output_col = output_col

    def transform(self, frame: FrameLike) -> ColumnSpace:
        columns = as_columns(frame)
        parts = []
        for name in self.input_cols:
            col = np.asarray(columns[name])
            if col.ndim == 1:
                col = col.astype(np.float32)[:, None]
            parts.append(col.astype(np.float32))
        columns[self.output_col] = np.concatenate(parts, axis=1)
        return columns
