"""Jitted raw-window → WISDM-transformed feature extraction.

The WISDM "transformed" dataset the reference trains on (SURVEY §2 S) is
the output of a 43-feature reduction of each 10 s window: per-axis means,
absolute/standard deviations, 10-bin value histograms, average
time-between-peaks, and the mean resultant magnitude.  The reference
receives this as a CSV (the transform itself lives outside its repo); here
the transform is a `jax.vmap`'d on-device kernel (BASELINE.json north star:
"the DataFrame sliding-window feature extractor becomes a jax.vmap over raw
(x,y,z) accelerometer segments"), so raw streams can feed either the
classical pipeline (via these features) or the neural models (directly).

Feature layout matches the CSV column order (har_tpu.data.wisdm):
  X0..X9, Y0..Y9, Z0..Z9   per-axis 10-bin histogram fractions
  XAVG, YAVG, ZAVG         per-axis means
  XPEAK, YPEAK, ZPEAK      avg time between detected peaks, milliseconds
  XABSDEV...               mean |x - mean|
  XSTDDEV...               population standard deviation
  RESULTANT                mean ℓ2 magnitude of (x,y,z)

Peak detection is a strict local-maximum test with a mean+0.1·std height
threshold — the published WISDM description ("time between sensor peaks")
leaves the detector unspecified, so exact numeric parity with the shipped
CSV is not expected (nor checkable: the raw stream isn't in the repo).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from har_tpu.data.raw_windows import SAMPLE_HZ

NUM_BINS = 10


def _axis_histogram(x: jax.Array) -> jax.Array:
    """Fraction of samples in 10 equal-width bins over [min, max]."""
    lo, hi = x.min(), x.max()
    width = jnp.maximum(hi - lo, 1e-12)
    bins = jnp.clip(
        ((x - lo) / width * NUM_BINS).astype(jnp.int32), 0, NUM_BINS - 1
    )
    counts = jax.ops.segment_sum(
        jnp.ones_like(x), bins, num_segments=NUM_BINS
    )
    return counts / x.shape[0]


def _avg_peak_gap_ms(x: jax.Array) -> jax.Array:
    """Average distance between strict local maxima above a height
    threshold, in milliseconds; 0 when fewer than 2 peaks."""
    mid = x[1:-1]
    is_peak = (mid > x[:-2]) & (mid > x[2:]) & (
        mid > x.mean() + 0.1 * x.std()
    )
    n_peaks = is_peak.sum()
    pos = jnp.arange(1, x.shape[0] - 1, dtype=jnp.float32)
    first = jnp.min(jnp.where(is_peak, pos, jnp.inf))
    last = jnp.max(jnp.where(is_peak, pos, -jnp.inf))
    span_ms = (last - first) * (1000.0 / SAMPLE_HZ)
    return jnp.where(n_peaks > 1, span_ms / jnp.maximum(n_peaks - 1, 1), 0.0)


def _window_features(window: jax.Array) -> jax.Array:
    """(T, 3) → (43,) feature vector in CSV column order."""
    x, y, z = window[:, 0], window[:, 1], window[:, 2]
    hists = [_axis_histogram(a) for a in (x, y, z)]
    avgs = jnp.stack([a.mean() for a in (x, y, z)])
    peaks = jnp.stack([_avg_peak_gap_ms(a) for a in (x, y, z)])
    absdev = jnp.stack([jnp.abs(a - a.mean()).mean() for a in (x, y, z)])
    stddev = jnp.stack([a.std() for a in (x, y, z)])
    resultant = jnp.sqrt(x**2 + y**2 + z**2).mean()
    return jnp.concatenate(
        [*hists, avgs, peaks, absdev, stddev, resultant[None]]
    )


@functools.partial(jax.jit)
def extract_features(windows: jax.Array) -> jax.Array:
    """(n, T, 3) raw windows → (n, 43) transformed features, on device."""
    return jax.vmap(_window_features)(windows)


FEATURE_NAMES = (
    tuple(f"{axis}{i}" for axis in ("X", "Y", "Z") for i in range(NUM_BINS))
    + ("XAVG", "YAVG", "ZAVG")
    + ("XPEAK", "YPEAK", "ZPEAK")
    + ("XABSDEV", "YABSDEV", "ZABSDEV")
    + ("XSTDDEV", "YSTDDEV", "ZSTDDEV")
    + ("RESULTANT",)
)
