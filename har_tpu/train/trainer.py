"""SPMD neural-network trainer.

The TPU-native counterpart of an MLlib estimator `.fit` (reference
Main/main.py:117): instead of a driver broadcasting coefficients to JVM
executors each iteration (SURVEY §3.3), the whole optimization step —
forward, backward, cross-shard `psum` gradient reduction, optimizer
update — is one compiled XLA program executed SPMD over the `dp` mesh
axis.  The host loop only feeds pre-sharded device batches.

Dropout keys are derived per-step from a root key and decorrelated across
shards with `axis_index('dp')`, so data parallelism changes no semantics
except the usual reduction order.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Iterator

import chex
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from har_tpu.parallel.mesh import DP_AXIS, TP_AXIS, single_device_mesh
from har_tpu.parallel.mesh import (
    data_axes,
    data_shard_count,
    linear_data_shard_index,
)
from har_tpu.parallel.sharding import batch_sharding, pad_to_multiple


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    batch_size: int = 512
    epochs: int = 60
    learning_rate: float = 3e-3
    weight_decay: float = 1e-4
    warmup_fraction: float = 0.1
    seed: int = 0
    log_every: int = 0  # 0 → silent
    # fault tolerance: checkpoint (params, opt_state) every N epochs
    # under checkpoint_dir and auto-resume from the latest snapshot.
    # Snapshots live in a subdirectory keyed by a fingerprint of the
    # model configuration + training data + schedule config, so a resume
    # only ever matches the identical run (CV folds, refits, changed
    # architectures, or changed seeds/batch sizes each get their own
    # slot instead of silently adopting another run's params).  The
    # batch schedule is derived deterministically from `seed`, so an
    # interrupted-and-resumed run executes the same step sequence as an
    # uninterrupted one (tested equal).
    # save_every_epochs=0 with a checkpoint_dir means every epoch.
    checkpoint_dir: str | None = None
    save_every_epochs: int = 0
    # early stopping (both paths): carve validation_fraction of the rows
    # out of training, evaluate after every epoch, stop after
    # early_stop_patience epochs without a val-accuracy improvement, and
    # return the best epoch's parameters.  0 → off.
    early_stop_patience: int = 0
    validation_fraction: float = 0.1
    # None → every row weighs 1; "balanced" reweighs the loss by
    # n / (num_classes * count(class)) so minority classes pull equally
    class_weight: str | None = None
    # record the compiled training program's XLA flop count in
    # history["program_flops"] (simple scan path only) — the bench derives
    # achieved FLOP/s and MFU from it.  Off by default: the explicit
    # lower/compile adds a retrace to every fit
    compute_flops: bool = False


def _run_fingerprint(
    cfg: TrainerConfig, x: np.ndarray, y: np.ndarray, module, augment=None,
    params=None, warm_start_digest=None, optimizer_tag=None,
) -> str:
    """Stable id for (model, data, schedule): the checkpoint-slot key.

    Hashes the module's configuration (Flax modules repr their dataclass
    fields), the parameter tree's structure/shapes (a module whose repr
    is unchanged but whose param layout changed — e.g. an internal layer
    rewrite — must NOT resume old snapshots), data shapes + a sample,
    and every config field that shapes the step sequence or optimizer
    schedule — two fits resume each other's snapshots only when they
    would execute the identical run.
    """
    import hashlib

    h = hashlib.sha1()
    h.update(repr(module).encode())
    if params is not None:
        leaves = jax.tree_util.tree_flatten_with_path(params)[0]
        h.update(
            repr(
                [
                    (jax.tree_util.keystr(p), tuple(l.shape), str(l.dtype))
                    for p, l in leaves
                ]
            ).encode()
        )
    h.update(repr((x.shape, y.shape, str(x.dtype))).encode())
    h.update(np.ascontiguousarray(x[:64]).tobytes())
    h.update(np.ascontiguousarray(y[:64]).tobytes())
    h.update(
        repr(
            (
                cfg.batch_size, cfg.epochs, cfg.learning_rate,
                cfg.weight_decay, cfg.warmup_fraction, cfg.seed,
            )
        ).encode()
    )
    if augment is not None:
        # augmentation changes the run; None is not hashed so slots from
        # before augmentation existed keep resuming
        h.update(repr(augment).encode())
    if cfg.class_weight is not None:
        h.update(repr(cfg.class_weight).encode())
    if cfg.early_stop_patience:
        # the early-stop loop snapshots different state (best-iterate
        # carry) and a different schedule than the plain chunked run
        h.update(
            repr(
                ("early_stop", cfg.early_stop_patience,
                 cfg.validation_fraction)
            ).encode()
        )
    if warm_start_digest is not None:
        # warm starts (transfer.fine_tune) share shapes with from-scratch
        # runs; the VALUE digest keeps fine-tunes of different checkpoints
        # (and from-scratch runs) from resuming each other's snapshots
        h.update(b"warm_start")
        h.update(warm_start_digest.encode())
    if optimizer_tag is not None:
        # a custom optimizer (e.g. a freeze mask) executes a different
        # run even with identical config/data
        h.update(b"optimizer")
        h.update(optimizer_tag.encode())
    return h.hexdigest()[:16]


def _early_stop_template(host_params, host_opt_state) -> dict:
    """Restore template for early-stop snapshots — ONE schema for both
    trainer paths (they share fingerprinted checkpoint slots, so drift
    here would corrupt cross-path resumes)."""
    return {
        "params": host_params,
        "opt_state": host_opt_state,
        "extra": {
            "best_params": host_params,
            "best_acc": 0.0,
            "best_epoch": 0,
            "bad": 0,
        },
    }


def _early_stop_extra(best_params, params, best_acc, best_epoch, bad) -> dict:
    """The extra payload early-stop snapshots carry (same schema note)."""
    return {
        "best_params": (
            best_params if best_params is not None else jax.device_get(params)
        ),
        "best_acc": best_acc,
        "best_epoch": best_epoch,
        "bad": bad,
    }


def _should_snapshot(cfg: TrainerConfig, stopped: bool, epoch: int) -> bool:
    """Snapshot at chunk boundaries AND on stop/final-epoch exit (a
    completed run that isn't snapshotted would retrain its tail on the
    next invocation)."""
    return (
        stopped
        or epoch == cfg.epochs
        or epoch % (cfg.save_every_epochs or 1) == 0
    )


def make_optimizer(cfg: TrainerConfig, total_steps: int):
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=max(1, int(cfg.warmup_fraction * total_steps)),
        decay_steps=max(2, total_steps),
    )
    return optax.adamw(schedule, weight_decay=cfg.weight_decay)


def _is_single_device(mesh: Mesh) -> bool:
    return int(np.prod(list(mesh.shape.values()))) == 1


def make_train_step(
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    augment: Callable | None = None,
) -> Callable:
    """step(params, opt_state, rng, x, y, mask) -> (params, opt_state, loss).

    On a 1-device mesh the body compiles under plain ``jit``: the psum and
    axis_index are identities there, so the shard_map manual-sharding
    partitioner adds nothing but compile-time work.
    """
    single = _is_single_device(mesh)
    dp_axes = data_axes(mesh)

    def local_step(params, opt_state, rng, x, y, mask):
        shard = 0 if single else linear_data_shard_index(mesh)
        shard_rng = jax.random.fold_in(rng, shard)
        if augment is not None:
            # same decorrelation convention as the scan path: the
            # augmentation key is one fold past the dropout key
            x = augment(jax.random.fold_in(shard_rng, 1), x)

        def local_sum(p):
            logits = apply_fn(
                {"params": p}, x, train=True, rngs={"dropout": shard_rng}
            )
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            return jnp.sum(ce * mask), jnp.sum(mask)

        (loss_sum, count), grads = jax.value_and_grad(
            local_sum, has_aux=True
        )(params)
        if not single:
            loss_sum, count, grads = jax.lax.psum(
                (loss_sum, count, grads), dp_axes
            )
        count = jnp.maximum(count, 1.0)
        grads = jax.tree.map(lambda g: g / count, grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss_sum / count

    if single:
        return jax.jit(local_step, donate_argnums=(0, 1))
    rep, bat = P(), P(dp_axes)
    step = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(rep, rep, rep, bat, bat, bat),
        out_specs=(rep, rep, rep),
        check_vma=False,
    )
    return jax.jit(step, donate_argnums=(0, 1))


def make_scan_fit(
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    augment: Callable | None = None,
    class_weights: jax.Array | None = None,  # (C,) per-class loss weights
) -> Callable:
    """fit(params, opt_state, rng, x, y, batch_idx, step0) -> (params, opt_state, losses).

    ``step0`` is the global index of the first step (nonzero when a
    checkpointed run executes in chunks — keeps per-step rng folds on
    the uninterrupted schedule).  The whole training run as ONE
    compiled program: `lax.scan` over
    precomputed shuffled batch indices, gathering each batch from the
    device-resident dataset.  This amortizes host→device dispatch latency
    (the per-step python loop costs ~0.5 s/step through a remote-chip
    tunnel; scanned, the same run is one dispatch).

    x/y are replicated (the classical datasets are small); each shard
    gathers its slice of every batch — batch_idx has shape
    (total_steps, batch_size) and is sharded on its second axis.

    On a 1-device mesh the whole run compiles under plain ``jit`` (the
    psum/axis_index are identities there — see make_train_step).

    Hybrid multi-slice meshes (create_multihost_mesh: dp_dcn outermost,
    dp inner) work transparently: the batch shards over BOTH data axes
    and the gradient reduction psums over the (dp_dcn, dp) tuple — XLA
    reduces over ICI within each slice, then once over DCN.
    """
    single = _is_single_device(mesh)
    dp_axes = data_axes(mesh)

    def local_fit(params, opt_state, rng, x, y, batch_idx, step0):
        # linear shard id across every data axis, so per-shard rng
        # folds stay unique on hybrid meshes
        shard = 0 if single else linear_data_shard_index(mesh)

        def step(carry, step_and_idx):
            params, opt_state = carry
            step_i, idx = step_and_idx
            xb, yb = x[idx], y[idx]
            step_rng = jax.random.fold_in(
                jax.random.fold_in(rng, step_i), shard
            )
            if augment is not None:
                # augmentation runs inside the compiled step (fused by
                # XLA); its randomness is decorrelated from dropout's
                xb = augment(jax.random.fold_in(step_rng, 1), xb)

            if class_weights is not None:
                wb = class_weights[yb]
            else:
                wb = jnp.ones((yb.shape[0],), jnp.float32)

            def local_sum(p):
                logits = apply_fn(
                    {"params": p}, xb, train=True,
                    rngs={"dropout": step_rng},
                )
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    logits, yb
                )
                return jnp.sum(ce * wb), jnp.sum(wb)

            (loss_sum, count), grads = jax.value_and_grad(
                local_sum, has_aux=True
            )(params)
            if not single:
                loss_sum, count, grads = jax.lax.psum(
                    (loss_sum, count, grads), dp_axes
                )
            grads = jax.tree.map(lambda g: g / count, grads)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss_sum / count

        # step0 keeps global step numbering when the run is executed in
        # checkpointed chunks (per-step rng folds stay aligned with the
        # uninterrupted schedule)
        steps = step0 + jnp.arange(batch_idx.shape[0])
        (params, opt_state), losses = jax.lax.scan(
            step, (params, opt_state), (steps, batch_idx)
        )
        return params, opt_state, losses

    if single:
        return jax.jit(local_fit, donate_argnums=(0, 1))
    rep = P()
    fit = jax.shard_map(
        local_fit,
        mesh=mesh,
        in_specs=(rep, rep, rep, rep, rep, P(None, dp_axes), rep),
        out_specs=(rep, rep, rep),
        check_vma=False,
    )
    return jax.jit(fit, donate_argnums=(0, 1))


def batch_iterator(
    n: int, batch_size: int, rng: np.random.Generator
) -> Iterator[np.ndarray]:
    """Shuffled fixed-size batch indices; the last partial batch is padded
    by wrapping (shapes must be static under jit)."""
    perm = rng.permutation(n)
    n_batches = max(1, -(-n // batch_size))
    padded = np.resize(perm, n_batches * batch_size)
    for i in range(n_batches):
        yield padded[i * batch_size : (i + 1) * batch_size]


@dataclasses.dataclass
class NeuralModel:
    """Trained model implementing the ClassifierModel protocol."""

    module: nn.Module
    params: Any
    num_classes: int
    history: dict | None = None

    def __post_init__(self):
        self._predict = jax.jit(
            lambda p, x: self.module.apply({"params": p}, x)
        )

    def predict_logits(self, x: np.ndarray, batch_size: int = 8192) -> np.ndarray:
        outs = []
        for start in range(0, len(x), batch_size):
            chunk = x[start : start + batch_size]
            pad = 0
            if len(chunk) < batch_size and start > 0:
                chunk, pad = pad_to_multiple(chunk, batch_size)
            logits = np.asarray(self._predict(self.params, jnp.asarray(chunk)))
            outs.append(logits[: len(logits) - pad if pad else None])
        return np.concatenate(outs, axis=0)

    def transform(self, data) -> "Predictions":
        # imported here, not at module top: models/__init__ pulls in
        # neural_classifier which imports this module — a top-level
        # import of har_tpu.models.base would make "import trainer
        # first" a circular-import error
        from har_tpu.models.base import Predictions

        x = data.features if hasattr(data, "features") else data
        logits = self.predict_logits(np.asarray(x, np.float32))
        probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        return Predictions.from_raw(logits, probs)


def _replace_on_mesh(params, opt_state, mesh, specs):
    """Re-place restored host arrays for a tp>1 run: params in the tp
    layout, optimizer state replicated mesh-wide (GSPMD reshards mu/nu on
    first use, and the first donated output re-adopts the computed
    sharded layout for the rest of the run)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from har_tpu.parallel.tensor_parallel import shard_params

    params = shard_params(params, mesh, specs)
    rep = NamedSharding(mesh, PartitionSpec())
    opt_state = jax.tree.map(
        lambda leaf: jax.device_put(leaf, rep), opt_state
    )
    return params, opt_state


class Trainer:
    """Fits a Flax module on (x, y) arrays, data-parallel over a mesh."""

    def __init__(
        self,
        module: nn.Module,
        config: TrainerConfig | None = None,
        mesh: Mesh | None = None,
        scan: bool = True,
        augment: Callable | None = None,
        optimizer_factory: Callable | None = None,
        zero1: bool = False,
    ):
        self.module = module
        self.config = config or TrainerConfig()
        self.mesh = mesh or single_device_mesh()
        # scan=True compiles the whole run into one program (fast, data
        # must fit on device); scan=False streams batches from host.
        self.scan = scan
        # augment(key, xb) -> xb, applied inside the compiled train step
        # (scan path); see har_tpu.data.augment
        self.augment = augment
        # optimizer_factory(cfg, total_steps) -> GradientTransformation;
        # defaults to make_optimizer.  Lets callers wrap the optimizer
        # (e.g. transfer.fine_tune masks frozen subtrees) while keeping
        # the schedule derived from the actual step count.
        self.optimizer_factory = optimizer_factory
        # zero1=True shards the optimizer state 1/N over the data axes
        # (parallel.zero1) while keeping every other feature —
        # augmentation, class weights, early stopping, checkpoint/resume
        # — on the same code path; the fitted params equal the
        # replicated run's to float tolerance (test-pinned)
        self.zero1 = zero1
        if zero1 and not scan:
            raise ValueError(
                "zero1=True requires scan=True: the sharded-optimizer "
                "fit is a scanned program (the streaming path's "
                "per-step host dispatch would dwarf the memory saving)"
            )
        # Warm-refit cache for the plain scanned path: a bench lane
        # times several fits of the SAME (module, config, data) — each
        # used to re-trace the whole scanned program, re-upload the
        # dataset through the (possibly degraded) device tunnel, and
        # re-stage the batch schedule, all inside the timed region.
        # Keyed by data identity (the source ndarrays are held strongly,
        # so an id can never be recycled while cached) + the shapes the
        # compiled program depends on; any miss falls through to the
        # normal path.  tp / zero1 / checkpointed / early-stop runs
        # bypass it (they re-place or slice their inputs).
        self._scan_cache: dict | None = None

    def _open_checkpointer(self, cfg, x, y, params):
        """One slot-derivation for every checkpointing path (chunked and
        early-stop), so the two can never drift onto different slots."""
        import os

        from har_tpu.checkpoint import TrainCheckpointer

        slot = os.path.join(
            cfg.checkpoint_dir,
            _run_fingerprint(
                cfg, x, y, self.module, augment=self.augment, params=params,
                warm_start_digest=getattr(self, "_warm_start_digest", None),
                optimizer_tag=getattr(self, "_optimizer_tag", None),
            ),
        )
        return TrainCheckpointer(slot)

    def fit(
        self,
        x: np.ndarray,
        y: np.ndarray,
        num_classes: int | None = None,
        init_params=None,
    ) -> NeuralModel:
        cfg = self.config
        mesh = self.mesh
        n = len(x)
        num_classes = num_classes or int(y.max()) + 1
        x = np.asarray(x, np.float32)
        y = np.asarray(y, np.int32)

        x_val = y_val = None
        if cfg.early_stop_patience < 0:
            raise ValueError(
                f"early_stop_patience must be >= 0 "
                f"(got {cfg.early_stop_patience})"
            )
        if cfg.early_stop_patience:
            if not 0.0 < cfg.validation_fraction < 1.0:
                raise ValueError(
                    "early stopping needs 0 < validation_fraction < 1 "
                    f"(got {cfg.validation_fraction})"
                )
            val_n = max(1, int(round(n * cfg.validation_fraction)))
            if val_n >= n:
                raise ValueError(
                    f"validation_fraction={cfg.validation_fraction} leaves "
                    f"no training rows (n={n})"
                )
            perm = np.random.default_rng(cfg.seed).permutation(n)
            val_rows, train_rows = perm[:val_n], perm[val_n:]
            x_val, y_val = x[val_rows], y[val_rows]
            x, y = x[train_rows], y[train_rows]
            n = len(x)

        dp = data_shard_count(mesh)
        if cfg.batch_size % dp:
            raise ValueError(
                f"batch_size {cfg.batch_size} must be divisible by the "
                f"data-parallel shard count ({dp})"
            )
        steps_per_epoch = max(1, -(-n // cfg.batch_size))
        total_steps = steps_per_epoch * cfg.epochs
        optimizer = (self.optimizer_factory or make_optimizer)(
            cfg, total_steps
        )

        root = jax.random.PRNGKey(cfg.seed)
        init_rng, step_root = jax.random.split(root)
        params = self.module.init(
            init_rng, jnp.asarray(x[: min(2, n)]), train=False
        )["params"]
        if init_params is not None:
            # warm start (transfer.fine_tune): the fresh init above is
            # the structural template the restored tree must match, so a
            # checkpoint from a different architecture fails loudly here
            chex.assert_trees_all_equal_shapes(params, init_params)
            params = jax.tree.map(jnp.asarray, init_params)
        # checkpoint-slot fingerprint context: warm starts share shapes
        # with from-scratch runs and a custom optimizer (freeze mask)
        # changes the run — both must key the slot (_open_checkpointer)
        self._warm_start_digest = None
        if init_params is not None:
            import hashlib

            hh = hashlib.sha1()
            for leaf in jax.tree.leaves(init_params):
                hh.update(np.ascontiguousarray(leaf).tobytes())
            self._warm_start_digest = hh.hexdigest()
        self._optimizer_tag = None
        if self.optimizer_factory is not None:
            self._optimizer_tag = getattr(
                self.optimizer_factory,
                "fingerprint_tag",
                getattr(
                    self.optimizer_factory, "__qualname__", "custom"
                ),
            )
        if self.zero1:
            # zero1 snapshots carry a flattened sharded optimizer state —
            # a different schema than the replicated tree, so the run
            # must key its own checkpoint slot (set before
            # _open_checkpointer derives the fingerprint)
            self._optimizer_tag = f"zero1:{self._optimizer_tag or ''}"
        # zero1's optimizer state is created by its fit factory (padded
        # flattened vector, sharded over the data axes) in the scan
        # branch below, not here
        opt_state = None if self.zero1 else optimizer.init(params)

        host_rng = np.random.default_rng(cfg.seed)
        history: dict[str, Any] = {"loss": []}
        t0 = time.perf_counter()
        tp = mesh.shape.get(TP_AXIS, 1)
        if cfg.class_weight not in (None, "balanced"):
            raise ValueError(
                f"class_weight={cfg.class_weight!r}; use None or "
                "'balanced'"
            )
        class_weights = None
        if cfg.class_weight == "balanced":
            counts = np.bincount(y, minlength=num_classes).astype(
                np.float32
            )
            class_weights = jnp.asarray(
                n / (num_classes * np.maximum(counts, 1.0))
            )
        if cfg.save_every_epochs < 0:
            raise ValueError("save_every_epochs must be >= 0")
        if cfg.save_every_epochs and not cfg.checkpoint_dir:
            raise ValueError(
                "save_every_epochs is set but checkpoint_dir is not — "
                "snapshots have nowhere to go"
            )
        if self.scan:
            # warm-refit cache (see __init__): identical (data, schedule)
            # re-fits reuse the traced program, the device-resident
            # dataset, and the staged batch schedule — repeat bench fits
            # pay only init + one dispatch instead of re-trace +
            # re-upload through the tunnel
            use_cache = (
                tp == 1
                and not self.zero1
                and not cfg.checkpoint_dir
                and not cfg.early_stop_patience
            )
            cached = self._scan_cache if use_cache else None
            hit = (
                cached is not None
                and cached["x"] is x
                and cached["y"] is y
                and cached["total_steps"] == total_steps
                and cached["num_classes"] == num_classes
            )
            history["warm_refit"] = bool(hit)
            batch_idx_dev = None
            if hit:
                # opt_state was freshly init'd above; params are a fresh
                # init (or caller-provided) — only the traced program and
                # the immutable device inputs are reused
                fit = cached["fit"]
                x_dev, y_dev = cached["x_dev"], cached["y_dev"]
                batch_idx_dev = cached["batch_idx_dev"]
            else:
                batch_idx = np.stack(
                    [
                        idx
                        for _ in range(cfg.epochs)
                        for idx in batch_iterator(
                            n, cfg.batch_size, host_rng
                        )
                    ]
                ).astype(np.int32)
                if tp > 1:
                    if self.zero1:
                        raise ValueError(
                            "zero1=True composes with data parallelism "
                            "only — a tp>1 mesh already shards params "
                            "(and GSPMD places the optimizer state with "
                            "them)"
                        )
                    # tensor parallelism: params sharded over tp, XLA
                    # inserts the collectives (GSPMD) — see
                    # har_tpu.parallel.tensor_parallel
                    from har_tpu.parallel.tensor_parallel import (
                        dense_alternating_specs,
                        make_gspmd_scan_fit,
                        shard_params,
                        tp_dim_check,
                    )

                    specs = dense_alternating_specs(params)
                    tp_dim_check(params, specs, tp)
                    params = shard_params(params, mesh, specs)
                    opt_state = optimizer.init(params)
                    fit = make_gspmd_scan_fit(
                        self.module.apply, optimizer, mesh,
                        augment=self.augment,
                        class_weights=class_weights,
                    )
                elif self.zero1:
                    # same scanned contract, optimizer state sharded 1/N
                    # over the data axes; the step mirrors make_scan_fit's
                    # rng/augment/weighting exactly, so everything
                    # downstream (chunked checkpointing, early stop,
                    # flops) is unchanged
                    from har_tpu.parallel.zero1 import make_zero1_fit

                    fit, init_opt_state = make_zero1_fit(
                        self.module.apply, optimizer, mesh, params,
                        augment=self.augment,
                        class_weights=class_weights,
                    )
                    opt_state = init_opt_state()
                    history["zero1_shards"] = dp
                else:
                    fit = make_scan_fit(
                        self.module.apply, optimizer, mesh,
                        augment=self.augment,
                        class_weights=class_weights,
                    )
                x_dev, y_dev = jnp.asarray(x), jnp.asarray(y)
                if use_cache:
                    batch_idx_dev = jnp.asarray(batch_idx)
                    self._scan_cache = {
                        "x": x, "y": y,
                        "total_steps": total_steps,
                        "num_classes": num_classes,
                        "fit": fit,
                        "x_dev": x_dev, "y_dev": y_dev,
                        "batch_idx_dev": batch_idx_dev,
                    }
            start_epoch = 0
            epochs_run = cfg.epochs  # branches override when they differ
            if cfg.checkpoint_dir and not cfg.early_stop_patience:
                # fault tolerance: run in save_every_epochs chunks — one
                # dispatch each — snapshotting (params, opt_state) after
                # every chunk and resuming from the newest snapshot.  The
                # batch schedule and per-step rng are derived from global
                # step numbers, so resumed runs retrace the uninterrupted
                # step sequence exactly.  Snapshots live under a
                # fingerprint of (model, data, schedule config): only the
                # identical run resumes them.
                import os

                ckpt_every = cfg.save_every_epochs or 1
                ckptr = self._open_checkpointer(cfg, x, y, params)
                try:
                    restored = ckptr.restore(
                        template={
                            "params": jax.device_get(params),
                            "opt_state": jax.device_get(opt_state),
                        }
                    )
                    if restored is not None:
                        start_epoch, params, opt_state = restored
                        start_epoch = min(start_epoch, cfg.epochs)
                        if tp > 1:
                            params, opt_state = _replace_on_mesh(
                                params, opt_state, mesh, specs
                            )
                    chunks_losses = []
                    epoch = start_epoch
                    while epoch < cfg.epochs:
                        chunk = min(ckpt_every, cfg.epochs - epoch)
                        lo = epoch * steps_per_epoch
                        hi = (epoch + chunk) * steps_per_epoch
                        params, opt_state, losses = fit(
                            params, opt_state, step_root, x_dev, y_dev,
                            jnp.asarray(batch_idx[lo:hi]),
                            jnp.asarray(lo, jnp.int32),
                        )
                        chunks_losses.append(np.asarray(losses))
                        epoch += chunk
                        ckptr.save(epoch, params, opt_state)
                finally:
                    ckptr.close()
                losses = (
                    np.concatenate(chunks_losses)
                    if chunks_losses
                    else np.zeros((0,), np.float32)
                )
                history["resumed_from_epoch"] = start_epoch
                epochs_run = cfg.epochs - start_epoch
                history["loss"] = (
                    list(
                        losses.reshape(-1, steps_per_epoch)[:, -1]
                    )
                    if len(losses)
                    else []
                )
            elif cfg.early_stop_patience:
                # per-epoch dispatches: train one epoch's scan, score the
                # held-out rows, keep the best epoch's parameters, stop
                # after `patience` epochs without improvement.  With a
                # checkpoint_dir, (params, opt_state) AND the best-
                # iterate carry snapshot every save_every_epochs epochs
                # and the run resumes mid-search after an interruption.
                x_val_dev, y_val_np = jnp.asarray(x_val), np.asarray(y_val)
                predict = jax.jit(
                    lambda p, xv: jnp.argmax(
                        self.module.apply({"params": p}, xv), -1
                    )
                )
                best_params, best_acc, best_epoch = None, -1.0, 0
                val_accs: list[float] = []
                chunk_losses = []
                bad = 0
                epoch = 0
                stopped = False
                ckptr = None
                if cfg.checkpoint_dir:
                    ckptr = self._open_checkpointer(cfg, x, y, params)
                    restored = ckptr.restore(
                        template=_early_stop_template(
                            jax.device_get(params),
                            jax.device_get(opt_state),
                        ),
                        with_extra=True,
                    )
                    if restored is not None:
                        epoch, params, opt_state, extra = restored
                        epoch = min(epoch, cfg.epochs)
                        best_params = extra["best_params"]
                        best_acc = float(extra["best_acc"])
                        best_epoch = int(extra["best_epoch"])
                        bad = int(extra["bad"])
                        history["resumed_from_epoch"] = epoch
                        if tp > 1:
                            params, opt_state = _replace_on_mesh(
                                params, opt_state, mesh, specs
                            )
                        # a run that already exhausted its patience is
                        # COMPLETE: re-invoking it must serve the stored
                        # best iterate, not train extra epochs
                        stopped = bad >= cfg.early_stop_patience
                try:
                    while not stopped and epoch < cfg.epochs:
                        lo = epoch * steps_per_epoch
                        hi = lo + steps_per_epoch
                        params, opt_state, losses = fit(
                            params, opt_state, step_root, x_dev, y_dev,
                            jnp.asarray(batch_idx[lo:hi]),
                            jnp.asarray(lo, jnp.int32),
                        )
                        chunk_losses.append(np.asarray(losses))
                        acc = float(
                            (np.asarray(predict(params, x_val_dev))
                             == y_val_np).mean()
                        )
                        val_accs.append(acc)
                        epoch += 1
                        if acc > best_acc:
                            best_acc, best_epoch = acc, epoch
                            best_params = jax.device_get(params)
                            bad = 0
                        else:
                            bad += 1
                            if bad >= cfg.early_stop_patience:
                                stopped = True
                        if ckptr is not None and _should_snapshot(
                            cfg, stopped, epoch
                        ):
                            ckptr.save(
                                epoch, params, opt_state,
                                extra=_early_stop_extra(
                                    best_params, params, best_acc,
                                    best_epoch, bad,
                                ),
                            )
                        if stopped:
                            break
                finally:
                    if ckptr is not None:
                        ckptr.close()
                params = best_params if best_params is not None else params
                losses = (
                    np.concatenate(chunk_losses)
                    if chunk_losses
                    else np.zeros((0, 1), np.float32)
                )
                history["loss"] = list(
                    losses.reshape(-1, steps_per_epoch)[:, -1]
                )
                history["val_accuracy"] = val_accs
                history["best_epoch"] = best_epoch
                history["stopped_epoch"] = epoch
                epochs_run = epoch
            else:
                if batch_idx_dev is None:
                    batch_idx_dev = jnp.asarray(batch_idx)
                args = (
                    params,
                    opt_state,
                    step_root,
                    x_dev,
                    y_dev,
                    batch_idx_dev,
                    jnp.asarray(0, jnp.int32),
                )
                if cfg.compute_flops:
                    compiled = fit.lower(*args).compile()
                    try:
                        ca = compiled.cost_analysis()
                    except Exception:  # some PJRT plugins: UNIMPLEMENTED
                        ca = None
                    if isinstance(ca, (list, tuple)):  # older jax returns
                        ca = ca[0] if ca else None  # one dict per device
                    # XLA's cost analysis counts a while-loop (scan)
                    # body ONCE regardless of trip count (measured so on
                    # this backend for length 1/10/100 scans), so scale
                    # by the step count.  That behavior is backend/
                    # version-dependent (ADVICE r2), so the RAW count is
                    # recorded alongside and program_flops is an
                    # estimate: if a future cost model folds the trip
                    # count in, raw == scaled/steps stops holding and
                    # MFU consumers can detect it.
                    # mfu_fields treats 0.0 as "unavailable".
                    raw_flops = float((ca or {}).get("flops", 0.0))
                    n_steps = int(args[5].shape[0])
                    history["program_flops_raw"] = raw_flops
                    history["program_flops_steps"] = n_steps
                    history["program_flops"] = raw_flops * n_steps
                    params, opt_state, losses = compiled(*args)
                else:
                    params, opt_state, losses = fit(*args)
                losses = np.asarray(losses)  # blocks until the run ends
                history["loss"] = list(
                    losses.reshape(cfg.epochs, steps_per_epoch)[:, -1]
                )
            step_idx = epochs_run * steps_per_epoch
        else:
            # STREAMING path: batches fed from host, one dispatch per
            # step.  Feature parity with the scanned path (VERDICT r2
            # item 7): tp>1 (GSPMD step over tp-sharded params),
            # augmentation (inside the compiled step), early stopping
            # and mid-training checkpointing all work here too — the
            # only remaining difference is the dispatch granularity.
            from har_tpu.data.prefetch import prefetch_to_device

            if tp > 1:
                from har_tpu.parallel.tensor_parallel import (
                    dense_alternating_specs,
                    make_gspmd_train_step,
                    shard_params,
                    tp_dim_check,
                )

                specs = dense_alternating_specs(params)
                tp_dim_check(params, specs, tp)
                params = shard_params(params, mesh, specs)
                opt_state = optimizer.init(params)
                step = make_gspmd_train_step(
                    self.module.apply, optimizer, mesh,
                    augment=self.augment,
                )
            else:
                step = make_train_step(
                    self.module.apply, optimizer, mesh,
                    augment=self.augment,
                )
            x_shard = batch_sharding(mesh, x.ndim)
            y_shard = batch_sharding(mesh, 1)
            cw_np = (
                np.asarray(class_weights) if class_weights is not None
                else None
            )

            predict = None
            if cfg.early_stop_patience:
                x_val_dev, y_val_np = jnp.asarray(x_val), np.asarray(y_val)
                predict = jax.jit(
                    lambda p, xv: jnp.argmax(
                        self.module.apply({"params": p}, xv), -1
                    )
                )
            best_params, best_acc, best_epoch = None, -1.0, 0
            val_accs: list[float] = []
            bad = 0
            stopped = False

            start_epoch = 0
            ckptr = None
            if cfg.checkpoint_dir:
                ckptr = self._open_checkpointer(cfg, x, y, params)
                template = _early_stop_template(
                    jax.device_get(params), jax.device_get(opt_state)
                )
                if not cfg.early_stop_patience:
                    del template["extra"]
                restored = ckptr.restore(
                    template=template,
                    with_extra=bool(cfg.early_stop_patience),
                )
                if restored is not None:
                    if cfg.early_stop_patience:
                        start_epoch, params, opt_state, extra = restored
                        best_params = extra["best_params"]
                        best_acc = float(extra["best_acc"])
                        best_epoch = int(extra["best_epoch"])
                        bad = int(extra["bad"])
                        stopped = bad >= cfg.early_stop_patience
                    else:
                        start_epoch, params, opt_state = restored
                    start_epoch = min(start_epoch, cfg.epochs)
                    history["resumed_from_epoch"] = start_epoch
                    if tp > 1:
                        params, opt_state = _replace_on_mesh(
                            params, opt_state, mesh, specs
                        )
                # replay the batch-schedule rng to the resume point so
                # the resumed run consumes the same epoch permutations
                # an uninterrupted run would
                for _ in range(start_epoch):
                    for _idx in batch_iterator(n, cfg.batch_size, host_rng):
                        pass

            start_steps = start_epoch * steps_per_epoch
            step_idx = start_steps
            epoch = start_epoch
            try:
                while not stopped and epoch < cfg.epochs:
                    # double-buffered host→device feed: the next batch's
                    # transfer overlaps the current step's compute; class
                    # weights ride the existing per-row mask
                    batches = prefetch_to_device(
                        batch_iterator(n, cfg.batch_size, host_rng),
                        size=2,
                        transfer=lambda idx: (
                            jax.device_put(x[idx], x_shard),
                            jax.device_put(y[idx], y_shard),
                            jax.device_put(
                                np.ones(len(idx), np.float32)
                                if cw_np is None
                                else cw_np[y[idx]],
                                y_shard,
                            ),
                        ),
                    )
                    for xb, yb, mb in batches:
                        rng = jax.random.fold_in(step_root, step_idx)
                        params, opt_state, loss = step(
                            params, opt_state, rng, xb, yb, mb
                        )
                        step_idx += 1
                    history["loss"].append(float(loss))
                    epoch += 1
                    if cfg.log_every and epoch % cfg.log_every == 0:
                        print(
                            f"epoch {epoch}/{cfg.epochs} "
                            f"loss {float(loss):.4f}"
                        )
                    if predict is not None:
                        acc = float(
                            (np.asarray(predict(params, x_val_dev))
                             == y_val_np).mean()
                        )
                        val_accs.append(acc)
                        if acc > best_acc:
                            best_acc, best_epoch = acc, epoch
                            best_params = jax.device_get(params)
                            bad = 0
                        else:
                            bad += 1
                            if bad >= cfg.early_stop_patience:
                                stopped = True
                    if ckptr is not None and _should_snapshot(
                        cfg, stopped, epoch
                    ):
                        extra = (
                            _early_stop_extra(
                                best_params, params, best_acc,
                                best_epoch, bad,
                            )
                            if cfg.early_stop_patience
                            else None
                        )
                        ckptr.save(
                            epoch, params, opt_state, extra=extra
                        )
            finally:
                if ckptr is not None:
                    ckptr.close()
            if cfg.early_stop_patience:
                if best_params is not None:
                    params = best_params
                history["val_accuracy"] = val_accs
                history["best_epoch"] = best_epoch
                history["stopped_epoch"] = epoch
            # the throughput rate must count only the steps THIS process
            # executed — a resumed run's pre-resume steps ran on another
            # process's clock (the scan path handles this via epochs_run)
            step_idx = step_idx - start_steps
        history["train_time_s"] = time.perf_counter() - t0
        history["windows_per_sec"] = (
            step_idx * cfg.batch_size / history["train_time_s"]
        )
        return NeuralModel(
            module=self.module,
            params=jax.device_get(params),
            num_classes=num_classes,
            history=history,
        )
