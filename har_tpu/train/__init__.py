"""Training loops for the neural model family."""

from har_tpu.train.trainer import (
    NeuralModel,
    Trainer,
    TrainerConfig,
    make_optimizer,
    make_train_step,
)

__all__ = [
    "NeuralModel",
    "Trainer",
    "TrainerConfig",
    "make_optimizer",
    "make_train_step",
]
