"""Fused flash-attention forward as a Pallas TPU kernel.

The Transformer1D encoder (BASELINE.json's raw-window configs) spends its
attention FLOPs in `full_attention` (har_tpu/parallel/ring_attention.py),
which materializes the (B, H, T, T) score tensor in HBM.  This kernel is
the fused alternative: per (batch×head, q-block) grid step it streams K/V
blocks through VMEM with the running-max/numerator/denominator softmax, so
scores never leave on-chip memory and the matmuls land on the MXU.

Scope: bidirectional (no causal mask — sensor windows are encoders, not
decoders), f32 accumulators regardless of input dtype, forward-only kernel
with a `jax.custom_vjp`.  The backward is the fused XLA recompute for
short T and a chunked flash-style backward (`lax.scan` over key blocks,
online-logsumexp renormalization, O(T·block) memory) past `_BWD_FULL_T`,
so neither direction materializes the (B, H, T, T) score tensor at the
lengths where it would dominate HBM.

Falls back to interpret mode off-TPU (the CPU test mesh), and callers
should fall back to `full_attention` when T has no usable block divisor
(see `pick_block`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def pick_block(t: int, max_block: int = 256) -> int:
    """Largest divisor of ``t`` that is ≤ max_block (kernel needs uniform
    blocks; returns 0 when only degenerate divisors exist)."""
    best = 0
    for b in range(1, min(t, max_block) + 1):
        if t % b == 0:
            best = b
    return best if best >= 8 or best == t else 0


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, scale: float):
    q = q_ref[0].astype(jnp.float32) * scale  # (TQ, D)
    t = k_ref.shape[1]
    n_kb = t // block_k
    tq, d = q.shape

    # all softmax state is kept 2-D (TQ, 1): 1-D vectors map poorly onto
    # the (sublane, lane) layout and miscompile reductions on some Mosaic
    # versions — 2-D keepdims reductions are the supported path
    def body(j, carry):
        m, num, den = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # (TQ, TK)
        blk_max = s.max(axis=-1, keepdims=True)  # (TQ, 1)
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m)
        num = num * corr + jax.lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        den = den * corr + p.sum(axis=-1, keepdims=True)
        return new_m, num, den

    m0 = jnp.full((tq, 1), -jnp.inf, jnp.float32)
    num0 = jnp.zeros((tq, d), jnp.float32)
    den0 = jnp.zeros((tq, 1), jnp.float32)
    m, num, den = jax.lax.fori_loop(0, n_kb, body, (m0, num0, den0))
    o_ref[0] = (num / den).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_q", "block_k"))
def _flash_bht(q, k, v, block_q: int, block_k: int):
    """(BH, T, D) fused attention."""
    bh, t, d = q.shape
    scale = d**-0.5
    kernel = functools.partial(_flash_kernel, block_k=block_k, scale=scale)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        grid=(bh, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        # every grid step owns a disjoint output block → both dims are
        # free for Mosaic to parallelize
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel")
        ),
        interpret=jax.default_backend() != "tpu",
    )(q, k, v)


def _attention_reference(q, k, v):
    """XLA attention on (B, T, H, D), f32 internally — the vjp recompute."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)


# below this T the full-recompute backward (one fused XLA attention vjp) is
# fastest and its (B,H,T,T) scores are small; above it the chunked backward
# keeps memory at O(T·block) so training stays feasible at the lengths the
# forward kernel exists for
_BWD_FULL_T = 1024


def _chunked_attention_bwd(q, k, v, out, g, block_k: int):
    """Flash-style backward: O(T·block) memory, never materializes scores.

    Standard decomposition (dV = Pᵀ dO; dS = P ∘ (dP − D) with
    D = rowsum(dO ∘ O); dQ/dK from dS) evaluated per key block under
    `lax.scan`, with the softmax normalizer recomputed by an online
    logsumexp pass — the same recurrence the forward kernel runs.
    All inputs (B, T, H, D); f32 internally; returns grads in input dtype.
    """
    in_dtype = q.dtype
    bhtd = lambda x: x.transpose(0, 2, 1, 3).astype(jnp.float32)
    qh, kh, vh, oh, gh = map(bhtd, (q, k, v, out, g))
    b, h, t, d = qh.shape
    scale = d**-0.5
    n_blocks = t // block_k
    blocked = lambda x: x.reshape(b, h, n_blocks, block_k, d).transpose(
        2, 0, 1, 3, 4
    )
    kb, vb = blocked(kh), blocked(vh)  # (n, B, H, bk, D)

    def lse_step(carry, kblk):
        m, l = carry
        s = jnp.einsum(
            "bhtd,bhkd->bhtk", qh, kblk,
            preferred_element_type=jnp.float32,
        ) * scale
        blk_max = s.max(-1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        l = l * jnp.exp(m - new_m) + jnp.exp(s - new_m).sum(
            -1, keepdims=True
        )
        return (new_m, l), None

    m0 = jnp.full((b, h, t, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t, 1), jnp.float32)
    (m, l), _ = jax.lax.scan(lse_step, (m0, l0), kb)
    lse = m + jnp.log(l)  # (B, H, T, 1)
    d_vec = (gh * oh).sum(-1, keepdims=True)  # rowsum(dO ∘ O)

    def bwd_step(dq, blk):
        kblk, vblk = blk
        s = jnp.einsum(
            "bhtd,bhkd->bhtk", qh, kblk,
            preferred_element_type=jnp.float32,
        ) * scale
        p = jnp.exp(s - lse)  # (B, H, T, bk)
        dv = jnp.einsum(
            "bhtk,bhtd->bhkd", p, gh, preferred_element_type=jnp.float32
        )
        dp = jnp.einsum(
            "bhtd,bhkd->bhtk", gh, vblk,
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - d_vec)
        dq = dq + scale * jnp.einsum(
            "bhtk,bhkd->bhtd", ds, kblk,
            preferred_element_type=jnp.float32,
        )
        dk = scale * jnp.einsum(
            "bhtk,bhtd->bhkd", ds, qh, preferred_element_type=jnp.float32
        )
        return dq, (dk, dv)

    dq, (dks, dvs) = jax.lax.scan(
        bwd_step, jnp.zeros_like(qh), (kb, vb)
    )
    unblock = lambda x: x.transpose(1, 2, 0, 3, 4).reshape(b, h, t, d)
    to_bthd = lambda x: x.transpose(0, 2, 1, 3).astype(in_dtype)
    return to_bthd(dq), to_bthd(unblock(dks)), to_bthd(unblock(dvs))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, block_q: int = 128, block_k: int = 128):
    """Fused attention, (B, T, H, D) layout, bidirectional.

    ``block_q``/``block_k`` must divide T (use `pick_block`); gradients
    flow via an XLA-recompute backward, so this drop-in replaces
    `full_attention` under `jax.grad`.
    """
    b, t, h, d = q.shape
    if t % block_q or t % block_k:
        # a non-dividing block would silently attend over only
        # (t // block) * block positions — refuse loudly instead
        raise ValueError(
            f"block_q={block_q}/block_k={block_k} must divide T={t} "
            "(use pick_block)"
        )
    to_bht = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    out = _flash_bht(to_bht(q), to_bht(k), to_bht(v), block_q, block_k)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, block_q, block_k):
    out = flash_attention(q, k, v, block_q, block_k)
    return out, (q, k, v, out)


def _flash_bwd(block_q, block_k, residuals, g):
    q, k, v, out = residuals
    if q.shape[1] <= _BWD_FULL_T:
        _, vjp = jax.vjp(_attention_reference, q, k, v)
        return vjp(g)
    return _chunked_attention_bwd(q, k, v, out, g, block_k)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
