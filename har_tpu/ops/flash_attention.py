"""Fused flash-attention forward as a Pallas TPU kernel.

The Transformer1D encoder (BASELINE.json's raw-window configs) spends its
attention FLOPs in `full_attention` (har_tpu/parallel/ring_attention.py),
which materializes the (B, H, T, T) score tensor in HBM.  This kernel is
the fused alternative: per (batch×head, q-block) grid step it streams K/V
blocks through VMEM with the running-max/numerator/denominator softmax, so
scores never leave on-chip memory and the matmuls land on the MXU.

Scope: bidirectional (no causal mask — sensor windows are encoders, not
decoders), f32 accumulators regardless of input dtype, forward-only kernel
with a `jax.custom_vjp`.  The backward is the fused XLA recompute for
short T and a chunked flash-style backward (`lax.scan` over key blocks,
online-logsumexp renormalization, O(T·block) memory) past `_BWD_FULL_T`,
so neither direction materializes the (B, H, T, T) score tensor at the
lengths where it would dominate HBM.

Falls back to interpret mode off-TPU (the CPU test mesh), and callers
should fall back to `full_attention` when T has no usable block divisor
(see `pick_block`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


# Minimum head dim the Mosaic-compiled kernel supports: sub-lane head
# dims (observed at d=16) deterministically fault the TPU worker on
# v5e.  flash_attention refuses smaller; Transformer1D's auto mode
# imports this so the gate and the guard cannot drift apart.
MIN_HEAD_DIM = 32

# jax renamed pltpu.TPUCompilerParams → CompilerParams; support both so
# the kernel runs on either side of the rename
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)


def pick_block(t: int, max_block: int = 512) -> int:
    """Largest divisor of ``t`` that is ≤ max_block (kernel needs uniform
    blocks; returns 0 when only degenerate divisors exist)."""
    best = 0
    for b in range(1, min(t, max_block) + 1):
        if t % b == 0:
            best = b
    return best if best >= 8 or best == t else 0


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, *refs,
    n_kb: int, scale: float,
):
    """One (bh, q-block, k-block) grid step.

    K/V stream on the LAST grid dimension — each step sees only one
    (block_k, D) slice in VMEM, so VMEM stays O(block) at any T (the
    earlier whole-K/V-block layout hit the 16M scoped-VMEM ceiling by
    T=32768), and Mosaic pipelines the next K/V fetch behind this step's
    matmuls.  Softmax state (running max / denominator / f32 numerator)
    lives in scratch across those steps.

    ``refs`` is (m, den, acc) scratch, optionally preceded by an lse
    output ref (with_lse in _flash_bht): the per-query log-sum-exp is
    what lets partial attention results over disjoint key sets combine
    exactly — ring attention runs this kernel per hop and merges with a
    logaddexp reweighting (ring_flash_attention).

    Matmul inputs stay in the model dtype (bf16) with f32 MXU
    accumulation — the same numerics family as XLA's fused attention.
    (The kernel originally upcast to f32 with Precision.HIGHEST, which
    lowers to multi-pass MXU matmuls: measured 0.66x XLA at T=16384;
    bf16 single-pass is what makes the kernel competitive.)

    All softmax state is kept 2-D (TQ, 1): 1-D vectors map poorly onto
    the (sublane, lane) layout and miscompile reductions on some Mosaic
    versions — 2-D keepdims reductions are the supported path.
    """
    lse_ref = refs[0] if len(refs) == 4 else None
    m_ref, den_ref, acc_ref = refs[-3:]
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        den_ref[...] = jnp.zeros_like(den_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    _online_softmax_step(
        q_ref, k_ref, v_ref, m_ref, den_ref, acc_ref, scale
    )

    @pl.when(j == n_kb - 1)
    def _finish():
        o_ref[0] = (acc_ref[...] / den_ref[...]).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0] = m_ref[...] + jnp.log(den_ref[...])


def _online_softmax_step(
    q_ref, k_ref, v_ref, m_ref, den_ref, acc_ref, scale: float
):
    """Fold one K/V block into the running-softmax scratch state."""
    q = q_ref[0]  # (TQ, D)
    s = jax.lax.dot_general(
        q, k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # (TQ, TK) f32
    m_prev = m_ref[...]
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    m_ref[...] = m_new
    den_ref[...] = den_ref[...] * corr + p.sum(axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
        p.astype(q.dtype), v_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "with_lse")
)
def _flash_bht(q, k, v, block_q: int, block_k: int, with_lse: bool = False):
    """(BH, T, D) fused attention; with_lse adds a (BH, T, 1) f32 output.

    One pallas_call plumbing for both kernels — grid, BlockSpecs and
    scratch are identical; only the out list and the finish differ.
    """
    bh, t, d = q.shape
    scale = d**-0.5
    n_kb = t // block_k
    kernel = functools.partial(_flash_kernel, n_kb=n_kb, scale=scale)
    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    out_shape = [jax.ShapeDtypeStruct((bh, t, d), q.dtype)]
    out_specs = [q_spec]
    if with_lse:
        out_shape.append(jax.ShapeDtypeStruct((bh, t, 1), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
        )
    return pl.pallas_call(
        kernel,
        out_shape=out_shape if with_lse else out_shape[0],
        grid=(bh, t // block_q, n_kb),
        in_specs=[
            q_spec,
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=out_specs if with_lse else out_specs[0],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # denominator
            pltpu.VMEM((block_q, d), jnp.float32),   # f32 numerator
        ],
        # (bh, q-block) steps own disjoint outputs; the k dimension
        # carries the softmax state through scratch, so it is sequential
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=jax.default_backend() != "tpu",
    )(q, k, v)


def _attention_with_lse_ref(q, k, v):
    """(out, lse) via plain XLA — the differentiable recompute twin of
    the lse kernel (f32 scores; materializes (B,H,T,Tk) in the backward
    only, which at ring-hop block sizes is the per-hop score tile).
    `_attention_reference` is this function's out half — one body."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    lse = jax.scipy.special.logsumexp(s, axis=-1)  # (B,H,Tq)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(q.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_with_lse(
    q, k, v, block_q: int = 128, block_k: int = 128
):
    """Fused attention returning (out (B,T,H,D), lse (B,H,T) float32).

    ``lse[b,h,t] = log Σ_k exp(q·k/√d)`` — the per-query normalizer that
    makes partial results over disjoint key sets exactly mergeable
    (ring_flash_attention).  Gradients flow through an XLA recompute of
    both outputs (lse included: the ring merge differentiates through
    its softmax weights).
    """
    b, t, h, d = q.shape
    if d < MIN_HEAD_DIM:
        raise ValueError(
            f"flash_attention requires head_dim >= {MIN_HEAD_DIM}, "
            f"got {d}"
        )
    if t % block_q or t % block_k:
        raise ValueError(
            f"block_q={block_q}/block_k={block_k} must divide T={t} "
            "(use pick_block)"
        )
    to_bht = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    out, lse = _flash_bht(
        to_bht(q), to_bht(k), to_bht(v), block_q, block_k, with_lse=True
    )
    return (
        out.reshape(b, h, t, d).transpose(0, 2, 1, 3),
        lse.reshape(b, h, t),
    )


def _flash_lse_fwd(q, k, v, block_q, block_k):
    out, lse = flash_attention_with_lse(q, k, v, block_q, block_k)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(block_q, block_k, residuals, g):
    q, k, v, out, lse = residuals
    g_out, g_lse = g
    if q.shape[1] <= _BWD_FULL_T:
        _, vjp = jax.vjp(_attention_with_lse_ref, q, k, v)
        return vjp((g_out, g_lse))
    # past the full-recompute threshold the score tile must never be
    # materialized — exactly the regime ring_flash_attention auto-selects.
    # The forward's lse rides the residuals, sparing the backward its
    # logsumexp recompute scan.
    return _chunked_attention_bwd(
        q, k, v, out, g_out, block_k, g_lse=g_lse, lse=lse
    )


flash_attention_with_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _attention_reference(q, k, v):
    """XLA attention on (B, T, H, D), f32 internally — the vjp recompute.
    The lse output gets a zero cotangent through the [0], so the vjp is
    identical to the pre-lse body."""
    return _attention_with_lse_ref(q, k, v)[0]


# below this T the full-recompute backward (one fused XLA attention vjp) is
# fastest and its (B,H,T,T) scores are small; above it the chunked backward
# keeps memory at O(T·block) so training stays feasible at the lengths the
# forward kernel exists for
_BWD_FULL_T = 1024


def _chunked_attention_bwd(
    q, k, v, out, g, block_k: int, g_lse=None, lse=None
):
    """Flash-style backward: O(T·block) memory, never materializes scores.

    Standard decomposition (dV = Pᵀ dO; dS = P ∘ (dP − D) with
    D = rowsum(dO ∘ O); dQ/dK from dS) evaluated per key block under
    `lax.scan`, with the softmax normalizer recomputed by an online
    logsumexp pass — the same recurrence the forward kernel runs.
    All inputs (B, T, H, D); f32 internally; returns grads in input dtype.

    ``g_lse`` (B, H, T) is the cotangent of the log-sum-exp output when
    backpropagating through flash_attention_with_lse: ∂lse/∂s_k = p_k,
    so it folds into the same bracket — dS = P ∘ (dP − D + g_lse).
    ``lse`` (B, H, T), when the caller saved the forward kernel's value,
    skips the online-logsumexp recompute scan (one QKᵀ pass per block).
    """
    in_dtype = q.dtype
    bhtd = lambda x: x.transpose(0, 2, 1, 3).astype(jnp.float32)
    qh, kh, vh, oh, gh = map(bhtd, (q, k, v, out, g))
    b, h, t, d = qh.shape
    scale = d**-0.5
    n_blocks = t // block_k
    blocked = lambda x: x.reshape(b, h, n_blocks, block_k, d).transpose(
        2, 0, 1, 3, 4
    )
    kb, vb = blocked(kh), blocked(vh)  # (n, B, H, bk, D)

    if lse is not None:
        lse = lse.astype(jnp.float32)[..., None]  # (B, H, T, 1)
    else:
        def lse_step(carry, kblk):
            m, l = carry
            s = jnp.einsum(
                "bhtd,bhkd->bhtk", qh, kblk,
                preferred_element_type=jnp.float32,
            ) * scale
            blk_max = s.max(-1, keepdims=True)
            new_m = jnp.maximum(m, blk_max)
            l = l * jnp.exp(m - new_m) + jnp.exp(s - new_m).sum(
                -1, keepdims=True
            )
            return (new_m, l), None

        m0 = jnp.full((b, h, t, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, t, 1), jnp.float32)
        (m, l), _ = jax.lax.scan(lse_step, (m0, l0), kb)
        lse = m + jnp.log(l)  # (B, H, T, 1)
    d_vec = (gh * oh).sum(-1, keepdims=True)  # rowsum(dO ∘ O)
    if g_lse is not None:
        d_vec = d_vec - g_lse.astype(jnp.float32)[..., None]

    def bwd_step(dq, blk):
        kblk, vblk = blk
        s = jnp.einsum(
            "bhtd,bhkd->bhtk", qh, kblk,
            preferred_element_type=jnp.float32,
        ) * scale
        p = jnp.exp(s - lse)  # (B, H, T, bk)
        dv = jnp.einsum(
            "bhtk,bhtd->bhkd", p, gh, preferred_element_type=jnp.float32
        )
        dp = jnp.einsum(
            "bhtd,bhkd->bhtk", gh, vblk,
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - d_vec)
        dq = dq + scale * jnp.einsum(
            "bhtk,bhkd->bhtd", ds, kblk,
            preferred_element_type=jnp.float32,
        )
        dk = scale * jnp.einsum(
            "bhtk,bhtd->bhkd", ds, qh, preferred_element_type=jnp.float32
        )
        return dq, (dk, dv)

    dq, (dks, dvs) = jax.lax.scan(
        bwd_step, jnp.zeros_like(qh), (kb, vb)
    )
    unblock = lambda x: x.transpose(1, 2, 0, 3, 4).reshape(b, h, t, d)
    to_bthd = lambda x: x.transpose(0, 2, 1, 3).astype(in_dtype)
    return to_bthd(dq), to_bthd(unblock(dks)), to_bthd(unblock(dvs))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, block_q: int = 128, block_k: int = 128):
    """Fused attention, (B, T, H, D) layout, bidirectional.

    ``block_q``/``block_k`` must divide T (use `pick_block`); gradients
    flow via an XLA-recompute backward, so this drop-in replaces
    `full_attention` under `jax.grad`.
    """
    b, t, h, d = q.shape
    if d < MIN_HEAD_DIM:
        raise ValueError(
            f"flash_attention requires head_dim >= {MIN_HEAD_DIM}, "
            f"got {d} (sub-lane head dims fault the TPU kernel; use "
            "full_attention)"
        )
    if t % block_q or t % block_k:
        # a non-dividing block would silently attend over only
        # (t // block) * block positions — refuse loudly instead
        raise ValueError(
            f"block_q={block_q}/block_k={block_k} must divide T={t} "
            "(use pick_block)"
        )
    to_bht = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    out = _flash_bht(to_bht(q), to_bht(k), to_bht(v), block_q, block_k)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, block_q, block_k):
    out = flash_attention(q, k, v, block_q, block_k)
    return out, (q, k, v, out)


def _flash_bwd(block_q, block_k, residuals, g):
    q, k, v, out = residuals
    if q.shape[1] <= _BWD_FULL_T:
        _, vjp = jax.vjp(_attention_reference, q, k, v)
        return vjp(g)
    return _chunked_attention_bwd(q, k, v, out, g, block_k)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Block-diagonal (packed-window) attention.
#
# The raw-HAR transformer attends over short windows (T≈200 samples → 25
# post-patch tokens).  Packing ``p`` windows into one sequence of length
# p·seg under a block-diagonal mask is mathematically per-window
# attention — each window sees only itself — but it changes what the MXU
# sees: one (B/p, p·seg, E) activation stream for every dense/norm pass,
# and an attention whose score tiles can either stay per-window
# (the fused kernel below — zero off-diagonal work, scores never leave
# VMEM) or fill large masked tiles (the XLA path — fewer, bigger GEMMs).
# Both are exact; which is faster is measured per-shape (the packed rows
# of ``scripts/mfu_tune.py transformer`` write the numbers that pick the
# bench lane's route).
# ---------------------------------------------------------------------------


def _fold_segments(x, seg: int):
    """(B, T, H, D) → (B·T/seg, seg, H, D): contiguity-preserving."""
    b, t, h, d = x.shape
    return x.reshape(b * (t // seg), seg, h, d)


def segment_flash_attention(q, k, v, seg: int):
    """Block-diagonal attention via the Pallas kernel, (B, T, H, D).

    Segments of length ``seg`` (T % seg == 0) attend only within
    themselves.  Folding segments into the batch dimension makes each
    segment exactly one kernel block — grid (B·n_seg·H, 1, 1) — so the
    diagonal is computed with no off-diagonal score work, the softmax
    state never leaves VMEM, and the existing custom_vjp backward
    applies per segment unchanged.
    """
    b, t, h, d = q.shape
    if t % seg:
        raise ValueError(f"segment length {seg} must divide T={t}")
    if seg < 8 or seg % 8:
        raise ValueError(
            f"segment length {seg} must be a multiple of 8 (the kernel's "
            "sublane block granularity); use segment_attention"
        )
    out = flash_attention(
        _fold_segments(q, seg), _fold_segments(k, seg),
        _fold_segments(v, seg), block_q=seg, block_k=seg,
    )
    return out.reshape(b, t, h, d)


def segment_attention(q, k, v, seg: int):
    """Block-diagonal attention via one masked XLA einsum, (B, T, H, D).

    The big-tile route: scores for the whole packed sequence are one
    (B, H, T, T) f32 GEMM with an additive block-diagonal mask — p× the
    diagonal's FLOPs, but large MXU tiles instead of per-window crumbs,
    and XLA fuses mask+softmax into the score pass.  Exact (identical
    softmax over each window's finite row support).
    """
    b, t, h, d = q.shape
    if t % seg:
        raise ValueError(f"segment length {seg} must divide T={t}")
    scale = d**-0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    seg_id = jnp.arange(t, dtype=jnp.int32) // seg
    mask = seg_id[:, None] == seg_id[None, :]
    s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(q.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)
