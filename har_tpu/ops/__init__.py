from har_tpu.ops.metrics import (
    classification_report,
    confusion_matrix,
    multiclass_metrics,
    binary_metrics,
    regression_metrics,
)

__all__ = [
    "classification_report",
    "confusion_matrix",
    "multiclass_metrics",
    "binary_metrics",
    "regression_metrics",
]
