"""Confidence calibration: ECE measurement + temperature scaling.

The reference reports accuracy-style metrics only (`Main/main.py:132-195`
— no notion of whether predicted probabilities mean anything).  A
deployed recognizer's probabilities DRIVE decisions (the serving path
smooths them; a monitoring UI thresholds them), and neural nets are
routinely overconfident — so the framework ships the standard remedy:

  ``expected_calibration_error``  — binned |confidence − accuracy| gap,
    the number that says whether "0.9" means 90%.
  ``fit_temperature``  — the single post-hoc scalar T that minimizes
    validation NLL of ``logits / T`` (Guo et al.'s temperature scaling:
    cannot change argmax, so accuracy is untouched while calibration
    improves).  1-D problem → derivative-free golden-section search on
    a jitted NLL; no optimizer state, deterministic.
  ``TemperatureScaledModel``  — ClassifierModel wrapper applying T
    inside the probability computation, so a calibrated model drops
    into evaluation, serving, or export unchanged.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def expected_calibration_error(
    probability: np.ndarray, labels: np.ndarray, bins: int = 15
) -> dict:
    """Standard top-label ECE with equal-width confidence bins.

    Returns {"ece", "bin_confidence", "bin_accuracy", "bin_count"} so a
    report can render the reliability diagram, not just the scalar.
    """
    probability = np.asarray(probability, np.float64)
    labels = np.asarray(labels)
    conf = probability.max(axis=-1)
    correct = (probability.argmax(axis=-1) == labels).astype(np.float64)
    # right-inclusive bins over (0, 1]; confidence is >= 1/C > 0
    edges = np.linspace(0.0, 1.0, bins + 1)
    idx = np.clip(np.digitize(conf, edges[1:-1], right=True), 0, bins - 1)
    count = np.bincount(idx, minlength=bins).astype(np.float64)
    conf_sum = np.bincount(idx, weights=conf, minlength=bins)
    acc_sum = np.bincount(idx, weights=correct, minlength=bins)
    nonzero = count > 0
    bin_conf = np.where(nonzero, conf_sum / np.maximum(count, 1), 0.0)
    bin_acc = np.where(nonzero, acc_sum / np.maximum(count, 1), 0.0)
    ece = float(
        (count / count.sum() * np.abs(bin_conf - bin_acc)).sum()
    )
    return {
        "ece": ece,
        "bin_confidence": bin_conf,
        "bin_accuracy": bin_acc,
        "bin_count": count.astype(np.int64),
    }


def fit_temperature(
    logits: np.ndarray,
    labels: np.ndarray,
    *,
    bounds: tuple[float, float] = (0.05, 20.0),
    tol: float = 1e-4,
) -> float:
    """The T minimizing mean NLL of ``softmax(logits / T)`` on held-out
    data.  NLL(T) is smooth and unimodal in log T for this 1-D family,
    so golden-section search over log-space converges without gradients
    or state."""
    import jax
    import jax.numpy as jnp
    import optax

    logits = jnp.asarray(logits, jnp.float32)
    labels = jnp.asarray(labels, jnp.int32)

    @jax.jit
    def nll(log_t):
        scaled = logits / jnp.exp(log_t)
        return optax.softmax_cross_entropy_with_integer_labels(
            scaled, labels
        ).mean()

    lo, hi = (float(np.log(b)) for b in bounds)
    inv_phi = (np.sqrt(5.0) - 1.0) / 2.0
    a, b = lo, hi
    c = b - inv_phi * (b - a)
    d = a + inv_phi * (b - a)
    fc, fd = float(nll(c)), float(nll(d))
    while (b - a) > tol:
        if fc < fd:
            b, d, fd = d, c, fc
            c = b - inv_phi * (b - a)
            fc = float(nll(c))
        else:
            a, c, fc = c, d, fd
            d = a + inv_phi * (b - a)
            fd = float(nll(d))
    return float(np.exp((a + b) / 2.0))


@dataclasses.dataclass
class TemperatureScaledModel:
    """ClassifierModel wrapper: probabilities from ``logits / T``.

    Argmax is invariant under positive scaling, so predictions (and
    accuracy) equal the base model's; only the confidence changes.
    Exportable (har_tpu.export) when the base is a neural model: the
    temperature bakes into the artifact's softmax.
    """

    model: object
    temperature: float

    @property
    def num_classes(self) -> int:
        return self.model.num_classes

    @property
    def scaler(self):
        # surfaced so export_model derives example_shape as it would
        # from the base model
        return getattr(self.model, "scaler", None)

    def transform(self, data):
        preds = self.model.transform(data)
        return _rescaled(preds, self.temperature)

    def predict_fn(self):
        """x → (logits, calibrated probs): the export hook.  The base
        must be a neural model (module+params); T bakes in as a
        constant so the artifact ships calibrated."""
        import jax

        from har_tpu.export import make_predict_core

        inner = getattr(self.model, "inner", self.model)
        core = make_predict_core(inner.module, self.scaler)
        params = inner.params
        t = float(self.temperature)

        def predict(x):
            logits, _ = core(params, x)
            return logits, jax.nn.softmax(logits / t, axis=-1)

        return predict


def _rescaled(preds, temperature: float):
    """Predictions with probabilities recomputed from raw/T — reuses
    the forward pass the caller already paid for."""
    import jax
    import jax.numpy as jnp

    from har_tpu.models.base import Predictions

    scaled = np.asarray(preds.raw, np.float32) / temperature
    probs = np.asarray(jax.nn.softmax(jnp.asarray(scaled), axis=-1))
    return Predictions.from_raw(preds.raw, probs)


def calibrate(model, data, *, bins: int = 15):
    """(TemperatureScaledModel, report) from held-out examples.

    The report carries before/after ECE and the fitted T so callers can
    log the improvement; fitting and measuring on the same held-out set
    is the standard protocol (T is a single scalar — overfit-proof).
    """
    preds = model.transform(data)
    raw = np.asarray(preds.raw, np.float64)
    if raw.size and raw.min() >= -1e-6 and np.allclose(
        raw.sum(axis=-1), 1.0, atol=1e-3
    ):
        # forests/ensembles put vote FRACTIONS in raw
        # (Predictions.from_raw(probs, probs)); softmax(probs/T) over
        # [0,1] values would silently flatten every confidence instead
        # of calibrating it
        raise ValueError(
            "model's raw scores are probabilities (votes), not logits — "
            "temperature scaling applies to logit-producing models "
            "(neural families, logistic regression)"
        )
    labels = np.asarray(
        data.label if hasattr(data, "label") else data[1]
    )
    before = expected_calibration_error(
        preds.probability, labels, bins=bins
    )
    t = fit_temperature(preds.raw, labels)
    scaled = TemperatureScaledModel(model, t)
    # after-ECE from the SAME forward pass: probabilities are a pure
    # function of the logits already in hand
    after = expected_calibration_error(
        _rescaled(preds, t).probability, labels, bins=bins
    )
    return scaled, {
        "temperature": round(t, 4),
        "ece_before": round(before["ece"], 4),
        "ece_after": round(after["ece"], 4),
    }
