"""Fused histogram-matmul Pallas kernel for tree induction.

The tree builder's hot op (har_tpu/models/tree.py `grow_level`) is

    hist = mᵀ @ one_hot(bins)        # (W·C, d·B)

where ``m`` is the per-row (node, class, weight) one-hot and
``one_hot(bins)`` is the (n, d·B) bin indicator.  The XLA path
materializes that indicator once in HBM — ~1 GB at the reference's
3,100-dim one-hot feature space (n=5,418, B=32, bf16) — and re-reads it
every level.  This kernel never materializes it: per (feature-tile,
row-tile) grid step it expands the int32 bin ids into the indicator
*in VMEM* and immediately contracts it on the MXU, accumulating output
tiles across row-tiles.

The expansion itself is MXU work, not a gather: with lane index
``c = f·B + b``, the gathered bin id ``bins[r, c//B]`` is
``bins_f32 @ G`` for the constant one-hot spread matrix
``G[f, c] = (c//B == f)``, and the indicator is then an elementwise
compare with ``c % B``.  Two matmuls per tile, zero HBM temporaries.

Constraints (host wrapper `hist_matmul` handles both): d padded to a
multiple of the 128-lane feature tile, n padded to the row tile with
zero-weight rows.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# one shim for the pltpu.TPUCompilerParams → CompilerParams rename,
# shared with the flash kernel so the two can't drift
from har_tpu.ops.flash_attention import _CompilerParams

# feature tile must keep the bins block's lane dim at 128; the row tile is
# sized so the two (NT, DT·B) f32 VMEM temporaries fit comfortably
_DT = 128
_NT = 256

# Validated max_bins envelope.  The kernel's per-grid-step VMEM
# footprint scales with _NT·_DT·max_bins·4 B (the expanded indicator and
# its compare operands): ~4 MB per temporary at B=32 — measured working
# — but ~17 MB at B=128, past a TPU core's ~16 MB VMEM, where the
# Mosaic compile faults the toolchain (artifacts/hist_bench.json,
# workload dt_numeric13_depth6_bins128: "tpu_compile_helper subprocess
# exit code 1").  Shapes beyond the measured-good envelope are rejected
# host-side with a clean error instead of a compiler crash.
MAX_BINS_SUPPORTED = 32


def _hist_kernel(bins_ref, m_ref, out_ref, *, max_bins: int):
    i = pl.program_id(1)  # row-tile index (accumulation axis)
    nt, dt = bins_ref.shape
    dtb = dt * max_bins

    # constant spread matrix G[f, c] = (c // B == f)
    f_of_c = jax.lax.broadcasted_iota(jnp.int32, (dt, dtb), 1) // max_bins
    f_row = jax.lax.broadcasted_iota(jnp.int32, (dt, dtb), 0)
    spread = (f_of_c == f_row).astype(jnp.float32)

    expanded = jax.lax.dot_general(
        bins_ref[:].astype(jnp.float32),
        spread,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (NT, DTB): bin id of column c's feature, exact for ids < 2^24
    b_of_c = (
        jax.lax.broadcasted_iota(jnp.int32, (nt, dtb), 1) % max_bins
    ).astype(jnp.float32)
    indicator = (expanded == b_of_c).astype(jnp.float32)

    tile = jax.lax.dot_general(
        m_ref[:],
        indicator,
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (WC, DTB)

    @pl.when(i == 0)
    def _():
        out_ref[:] = tile

    @pl.when(i != 0)
    def _():
        out_ref[:] += tile


@functools.partial(jax.jit, static_argnames=("max_bins",))
def _hist_padded(bins, m, max_bins: int):
    n, d = bins.shape
    wc = m.shape[1]
    return pl.pallas_call(
        functools.partial(_hist_kernel, max_bins=max_bins),
        out_shape=jax.ShapeDtypeStruct((wc, d * max_bins), jnp.float32),
        grid=(d // _DT, n // _NT),
        in_specs=[
            pl.BlockSpec((_NT, _DT), lambda j, i: (i, j)),
            pl.BlockSpec((_NT, wc), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec(
            (wc, _DT * max_bins), lambda j, i: (0, j)
        ),
        # feature tiles are independent; row tiles accumulate into the
        # same output block and must stay sequential
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=jax.default_backend() != "tpu",
    )(bins, m)


def hist_matmul(bins: jax.Array, m: jax.Array, max_bins: int) -> jax.Array:
    """``mᵀ @ one_hot(bins)`` without materializing the one-hot.

    bins: (n, d) int32 bin ids in [0, max_bins); m: (n, WC) f32 row
    statistics.  Returns (WC, d·max_bins) f32 — identical (up to f32
    summation order) to the XLA one-hot matmul in tree.py.

    Raises ValueError for max_bins > MAX_BINS_SUPPORTED (uniformly, on
    every backend — CPU interpret mode would "work", but a shape that
    crash-compiles on the target hardware must not pass tests
    elsewhere).
    """
    if max_bins > MAX_BINS_SUPPORTED:
        raise ValueError(
            f"pallas hist kernel supports max_bins <= "
            f"{MAX_BINS_SUPPORTED} (got {max_bins}): larger bin counts "
            "exceed the kernel's per-tile VMEM budget and fault the TPU "
            "compiler (measured: artifacts/hist_bench.json, "
            "dt_numeric13_depth6_bins128).  Use the XLA one-hot matmul "
            "path (use_pallas_hist=False, the default auto policy) for "
            "this shape."
        )
    n, d = bins.shape
    d_pad = -(-d // _DT) * _DT
    n_pad = -(-n // _NT) * _NT
    if d_pad != d:
        bins = jnp.pad(bins, ((0, 0), (0, d_pad - d)))
    if n_pad != n:
        # padded rows get zero statistics → contribute nothing
        bins = jnp.pad(bins, ((0, n_pad - n), (0, 0)))
        m = jnp.pad(m, ((0, n_pad - n), (0, 0)))
    out = _hist_padded(bins, m, max_bins)
    return out[:, : d * max_bins]
