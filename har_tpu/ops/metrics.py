"""One-pass jitted evaluation metrics.

The reference evaluates each model with three MLlib evaluator families plus
hand-rolled counts, costing ~14 separate distributed jobs per model block
(reference Main/main.py:132-195; SURVEY §3.5).  Here the full battery —
confusion matrix, accuracy, weighted precision/recall/F1, areaUnderROC /
areaUnderPR, rmse/mse/r2/mae, correct/wrong counts — is computed in ONE jit
on device.

Formulas follow MLlib's MulticlassMetrics / BinaryClassificationMetrics /
RegressionMetrics documentation:
  - weighted P/R/F1 weight per-class scores by true-class frequency;
    per-class precision with an empty predicted-class is 0.
  - areaUnderROC / areaUnderPR via the score-sorted cumulative curve
    (trapezoidal ROC; PR with the (0, p1) anchor point MLlib uses).
  - regression metrics treat (label, prediction) as real numbers — the
    reference applies them to class indices, which we reproduce.

All shapes static; an optional boolean mask supports padded batches.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def confusion_matrix(
    labels: jax.Array,
    predictions: jax.Array,
    num_classes: int,
    mask: jax.Array | None = None,
) -> jax.Array:
    """(num_classes, num_classes) counts, rows = true class."""
    w = jnp.ones_like(labels, dtype=jnp.float32) if mask is None else mask.astype(jnp.float32)
    flat = labels.astype(jnp.int32) * num_classes + predictions.astype(jnp.int32)
    counts = jax.ops.segment_sum(w, flat, num_segments=num_classes * num_classes)
    return counts.reshape(num_classes, num_classes)


def multiclass_metrics(cm: jax.Array) -> dict[str, jax.Array]:
    """accuracy, weighted precision/recall/F1 and per-class curves from a
    confusion matrix."""
    total = cm.sum()
    tp = jnp.diagonal(cm)
    actual = cm.sum(axis=1)  # per true class
    predicted = cm.sum(axis=0)  # per predicted class
    precision = jnp.where(predicted > 0, tp / jnp.maximum(predicted, 1), 0.0)
    recall = jnp.where(actual > 0, tp / jnp.maximum(actual, 1), 0.0)
    f1 = jnp.where(
        precision + recall > 0,
        2 * precision * recall / jnp.maximum(precision + recall, 1e-30),
        0.0,
    )
    weights = actual / jnp.maximum(total, 1)
    correct = tp.sum()
    return {
        "accuracy": correct / jnp.maximum(total, 1),
        "weightedPrecision": (weights * precision).sum(),
        "weightedRecall": (weights * recall).sum(),
        "f1": (weights * f1).sum(),
        "precision_per_class": precision,
        "recall_per_class": recall,
        "f1_per_class": f1,
        "count_total": total,
        "count_correct": correct,
        "count_wrong": total - correct,
    }


def binary_metrics(
    scores: jax.Array,
    positive: jax.Array,
    mask: jax.Array | None = None,
) -> dict[str, jax.Array]:
    """areaUnderROC and areaUnderPR from raw scores.

    `positive` is a {0,1} indicator of the positive class.  Sorting the
    scores descending and accumulating TP/FP reproduces MLlib's threshold
    sweep; ties are handled by trapezoids over cumulative counts.
    """
    w = jnp.ones_like(scores) if mask is None else mask.astype(scores.dtype)
    pos = positive.astype(scores.dtype) * w
    order = jnp.argsort(-scores)
    pos_sorted = pos[order]
    w_sorted = w[order]
    tp = jnp.cumsum(pos_sorted)
    fp = jnp.cumsum(w_sorted - pos_sorted)
    p = jnp.maximum(tp[-1], 1e-30)
    n = jnp.maximum(fp[-1], 1e-30)
    tpr = jnp.concatenate([jnp.zeros(1, scores.dtype), tp / p])
    fpr = jnp.concatenate([jnp.zeros(1, scores.dtype), fp / n])
    auroc = jnp.trapezoid(tpr, fpr)
    # PR curve: precision at each cut; anchored at recall=0 with the first
    # point's precision (MLlib's (0, p1) anchor).
    prec = tp / jnp.maximum(tp + fp, 1e-30)
    rec = tp / p
    prec_anchor = jnp.concatenate([prec[:1], prec])
    rec_anchor = jnp.concatenate([jnp.zeros(1, scores.dtype), rec])
    aupr = jnp.trapezoid(prec_anchor, rec_anchor)
    return {"areaUnderROC": auroc, "areaUnderPR": aupr}


def regression_metrics(
    labels: jax.Array, predictions: jax.Array, mask: jax.Array | None = None
) -> dict[str, jax.Array]:
    w = jnp.ones_like(labels, dtype=jnp.float32) if mask is None else mask.astype(jnp.float32)
    n = jnp.maximum(w.sum(), 1)
    y = labels.astype(jnp.float32)
    yhat = predictions.astype(jnp.float32)
    err = (y - yhat) * w
    mse = (err**2).sum() / n
    mae = jnp.abs(err).sum() / n
    mean_y = (y * w).sum() / n
    ss_tot = ((y - mean_y) ** 2 * w).sum()
    ss_res = (err**2).sum()
    return {
        "mse": mse,
        "rmse": jnp.sqrt(mse),
        "mae": mae,
        "r2": 1.0 - ss_res / jnp.maximum(ss_tot, 1e-30),
    }


@functools.partial(jax.jit, static_argnames=("num_classes", "positive_class"))
def classification_report(
    labels: jax.Array,
    raw_scores: jax.Array,
    num_classes: int,
    positive_class: int = 1,
    mask: jax.Array | None = None,
) -> dict[str, jax.Array]:
    """The full evaluation battery in one compiled pass.

    Args:
      labels: (n,) integer class labels.
      raw_scores: (n, num_classes) raw model scores (logits/probabilities/
        votes) — argmax gives the prediction.
      positive_class: class treated as positive for the binary AUC metrics
        (the reference's BinaryClassificationEvaluator reads score index 1).
    """
    predictions = jnp.argmax(raw_scores, axis=-1)
    cm = confusion_matrix(labels, predictions, num_classes, mask)
    out: dict[str, jax.Array] = {"confusion_matrix": cm}
    out.update(multiclass_metrics(cm))
    out.update(
        binary_metrics(
            raw_scores[:, positive_class],
            (labels == positive_class).astype(jnp.float32),
            mask,
        )
    )
    out.update(regression_metrics(labels, predictions.astype(jnp.float32), mask))
    return out


def evaluate(labels, raw_scores, num_classes, positive_class=1) -> dict[str, float]:
    """Host evaluation battery in float64 — the report/CSV path.

    Mirrors the jitted :func:`classification_report` formulas but computes
    in double precision from exact integer counts, so the emitted values
    equal MLlib's to the last digit (the reference CSVs carry full f64
    reprs).  The binary block reproduces MLlib's
    BinaryClassificationEvaluator semantics on multiclass data exactly
    (reference Main/main.py:135-143 applies it to 6-class labels):
    score = rawPrediction[1], positive = label > 0.5 (every non-class-0
    row!), and ROC/PR curves over DISTINCT thresholds — tie groups form
    one curve point, which changes areaUnderPR vs per-row accumulation.

    The jitted battery stays for in-graph/device callers (CV sweeps).
    """
    import numpy as np

    # numpy<2 has no np.trapezoid (ADVICE r2: unbounded numpy dep)
    _trapezoid = getattr(np, "trapezoid", None) or np.trapz
    y = np.asarray(labels).astype(np.int64)
    raw = np.asarray(raw_scores, np.float64)
    pred = raw.argmax(-1)
    n = len(y)
    cm = np.zeros((num_classes, num_classes), np.float64)
    np.add.at(cm, (y, pred), 1.0)

    total = cm.sum()
    tp = np.diagonal(cm)
    actual = cm.sum(axis=1)
    predicted = cm.sum(axis=0)
    with np.errstate(divide="ignore", invalid="ignore"):
        precision = np.where(predicted > 0, tp / np.maximum(predicted, 1), 0.0)
        recall = np.where(actual > 0, tp / np.maximum(actual, 1), 0.0)
        f1 = np.where(
            precision + recall > 0,
            2 * precision * recall / np.maximum(precision + recall, 1e-300),
            0.0,
        )
    correct = float(tp.sum())
    # MulticlassMetrics' weighted aggregates fold ``metric(c) * count(c)
    # / labelCount`` over labelCountByClass — a scala immutable HashMap
    # iterated in hash-trie order — so the CSVs' full-f64 reprs only
    # match MLlib with the same per-term arithmetic and the same
    # accumulation order (numpy's pairwise sum differs in the last ulp).
    from har_tpu.data.spark_random import scala_int_trie_order

    label_count = max(total, 1.0)
    w_precision = 0.0
    w_recall = 0.0
    w_f1 = 0.0
    for c in scala_int_trie_order(range(num_classes)):
        cnt = float(actual[c])
        w_precision += float(precision[c]) * cnt / label_count
        w_recall += float(recall[c]) * cnt / label_count
        w_f1 += float(f1[c]) * cnt / label_count

    # --- MLlib binary evaluator (distinct-threshold curves) -------------
    scores = raw[:, positive_class]
    pos = (y > 0.5).astype(np.float64)
    order = np.argsort(-scores, kind="stable")
    s_sorted, p_sorted = scores[order], pos[order]
    # last index of each distinct score = one curve point per threshold
    if n:
        last = np.nonzero(np.diff(s_sorted) != 0)[0]
        bounds = np.concatenate([last, [n - 1]])
        tp_c = np.cumsum(p_sorted)[bounds]
        fp_c = (np.arange(1, n + 1, dtype=np.float64) - np.cumsum(p_sorted))[
            bounds
        ]
        p_tot = max(pos.sum(), 1e-300)
        n_tot = max(n - pos.sum(), 1e-300)
        tpr = np.concatenate([[0.0], tp_c / p_tot])
        fpr = np.concatenate([[0.0], fp_c / n_tot])
        auroc = float(_trapezoid(tpr, fpr))
        prec_c = tp_c / np.maximum(tp_c + fp_c, 1e-300)
        rec_c = tp_c / p_tot
        aupr = float(
            _trapezoid(
                np.concatenate([prec_c[:1], prec_c]),
                np.concatenate([[0.0], rec_c]),
            )
        )
    else:  # pragma: no cover - empty input
        auroc = aupr = 0.0

    # --- regression over class indices (reference applies it so) --------
    yf, pf = y.astype(np.float64), pred.astype(np.float64)
    err = yf - pf
    mse = float((err**2).mean()) if n else 0.0
    mae = float(np.abs(err).mean()) if n else 0.0
    ss_tot = float(((yf - yf.mean()) ** 2).sum()) if n else 0.0
    r2 = 1.0 - float((err**2).sum()) / max(ss_tot, 1e-300)

    return {
        "confusion_matrix": cm.tolist(),
        "accuracy": correct / max(total, 1.0),
        "weightedPrecision": w_precision,
        "weightedRecall": w_recall,
        "f1": w_f1,
        "precision_per_class": precision.tolist(),
        "recall_per_class": recall.tolist(),
        "f1_per_class": f1.tolist(),
        "count_total": float(total),
        "count_correct": correct,
        "count_wrong": float(total) - correct,
        "areaUnderROC": auroc,
        "areaUnderPR": aupr,
        "mse": mse,
        "rmse": float(np.sqrt(mse)),
        "mae": mae,
        "r2": r2,
    }
