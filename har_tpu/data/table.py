"""A minimal columnar table.

This replaces the reference's Spark DataFrame layer (reference
Main/main.py:16-47) for *host-side* work only: column selection, group
counts, summary stats, row filtering.  Anything per-row and numeric moves to
device as a dense array; the table never crosses into jit.
"""

from __future__ import annotations

from typing import Iterable, Mapping, Sequence

import numpy as np

from har_tpu.data.schema import ColumnType, Schema


class Table:
    """Immutable dict-of-numpy-columns with a schema."""

    def __init__(self, columns: Mapping[str, np.ndarray], schema: Schema):
        if set(columns) != set(schema.names):
            raise ValueError("columns do not match schema names")
        lengths = {len(v) for v in columns.values()}
        if len(lengths) > 1:
            raise ValueError(f"ragged columns: {lengths}")
        self._columns = dict(columns)
        self.schema = schema

    # -- basic accessors ----------------------------------------------------
    def __len__(self) -> int:
        return len(next(iter(self._columns.values()))) if self._columns else 0

    @property
    def num_rows(self) -> int:
        return len(self)

    @property
    def column_names(self) -> tuple[str, ...]:
        return self.schema.names

    def column(self, name: str) -> np.ndarray:
        return self._columns[name]

    def __getitem__(self, name: str) -> np.ndarray:
        return self._columns[name]

    # -- relational ops (host side) ----------------------------------------
    def select(self, names: Sequence[str]) -> "Table":
        schema = Schema(
            names=tuple(names),
            types=tuple(self.schema.type_of(n) for n in names),
        )
        return Table({n: self._columns[n] for n in names}, schema)

    def drop(self, names: Iterable[str]) -> "Table":
        dropped = set(names)
        keep = [n for n in self.schema.names if n not in dropped]
        return self.select(keep)

    def take(self, indices: np.ndarray) -> "Table":
        return Table(
            {n: v[indices] for n, v in self._columns.items()}, self.schema
        )

    def head(self, n: int = 5) -> "Table":
        return self.take(np.arange(min(n, len(self))))

    def group_count(self, name: str, descending: bool = True) -> list[tuple[str, int]]:
        """groupBy(name).count().orderBy(count) (reference Main/main.py:35-38)."""
        values, counts = np.unique(self._columns[name], return_counts=True)
        order = np.argsort(-counts if descending else counts, kind="stable")
        return [(str(values[i]), int(counts[i])) for i in order]

    def describe(self, names: Sequence[str] | None = None) -> dict[str, dict[str, float]]:
        """count/mean/stddev/min/max per numeric column, MLlib-style
        (sample stddev, ddof=1 — matches DataFrame.describe)."""
        if names is None:
            names = [
                n
                for n, t in zip(self.schema.names, self.schema.types)
                if t is not ColumnType.STRING
            ]
        out: dict[str, dict[str, float]] = {}
        for n in names:
            col = self._columns[n].astype(np.float64)
            out[n] = {
                "count": float(len(col)),
                "mean": float(col.mean()) if len(col) else float("nan"),
                "stddev": float(col.std(ddof=1)) if len(col) > 1 else float("nan"),
                "min": float(col.min()) if len(col) else float("nan"),
                "max": float(col.max()) if len(col) else float("nan"),
            }
        return out

    def numeric_matrix(self, names: Sequence[str], dtype=np.float32) -> np.ndarray:
        """Stack numeric columns into an (n_rows, len(names)) dense matrix."""
        return np.stack(
            [self._columns[n].astype(dtype) for n in names], axis=1
        )

    def with_column(self, name: str, values: np.ndarray, ctype: ColumnType) -> "Table":
        cols = dict(self._columns)
        cols[name] = values
        if name in self.schema.names:
            types = tuple(
                ctype if n == name else t
                for n, t in zip(self.schema.names, self.schema.types)
            )
            schema = Schema(self.schema.names, types)
        else:
            schema = Schema(
                self.schema.names + (name,), self.schema.types + (ctype,)
            )
        return Table(cols, schema)
