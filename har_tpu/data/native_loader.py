"""ctypes bridge to the native C++ CSV loader (native/csvloader.cpp).

Builds ``libharcsv.so`` with g++ on first use (cached next to the source;
pybind11 isn't available in this image, so the library exposes a plain C
ABI).  ``read_csv_native`` returns the same Table the pure-Python loader
produces — identical schema-inference semantics, verified by tests — and
``har_tpu.data.csv_loader.read_csv(engine="auto")`` prefers it when the
toolchain is present, falling back to Python otherwise.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from har_tpu.data.schema import ColumnType, Schema
from har_tpu.data.table import Table

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SRC = os.path.join(_NATIVE_DIR, "csvloader.cpp")
_SO = os.path.join(_NATIVE_DIR, "libharcsv.so")

_lock = threading.Lock()
_lib = None
_build_error: str | None = None


def _build() -> str | None:
    """Compile the shared library if stale; returns error string or None."""
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return None
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
        _SRC, "-o", _SO,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"g++ unavailable: {e}"
    if proc.returncode != 0:
        return f"native build failed: {proc.stderr[-500:]}"
    return None


def _load_lib():
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        err = _build()
        if err is not None:
            _build_error = err
            return None
        lib = ctypes.CDLL(_SO)
        lib.csv_load.restype = ctypes.c_void_p
        lib.csv_load.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.csv_error.restype = ctypes.c_char_p
        lib.csv_error.argtypes = [ctypes.c_void_p]
        lib.csv_ncols.restype = ctypes.c_int
        lib.csv_ncols.argtypes = [ctypes.c_void_p]
        lib.csv_nrows.restype = ctypes.c_int64
        lib.csv_nrows.argtypes = [ctypes.c_void_p]
        lib.csv_colname.restype = ctypes.c_char_p
        lib.csv_colname.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.csv_coltype.restype = ctypes.c_int
        lib.csv_coltype.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.csv_numeric.restype = None
        lib.csv_numeric.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.csv_ints.restype = None
        lib.csv_ints.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.csv_string_at.restype = ctypes.c_char_p
        lib.csv_string_at.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int64,
        ]
        lib.csv_string_col_bytes.restype = ctypes.c_int64
        lib.csv_string_col_bytes.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.csv_string_col_packed.restype = None
        lib.csv_string_col_packed.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
        ]
        lib.csv_free.restype = None
        lib.csv_free.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load_lib() is not None


_CTYPE_MAP = {0: ColumnType.INT, 1: ColumnType.DOUBLE, 2: ColumnType.STRING}


def read_csv_native(path: str, num_threads: int = 0) -> Table:
    lib = _load_lib()
    if lib is None:
        raise RuntimeError(f"native loader unavailable: {_build_error}")
    handle = lib.csv_load(path.encode(), num_threads)
    try:
        err = lib.csv_error(handle)
        if err:
            raise FileNotFoundError(err.decode())
        ncols = lib.csv_ncols(handle)
        nrows = lib.csv_nrows(handle)
        names, types, cols = [], [], {}
        for c in range(ncols):
            name = lib.csv_colname(handle, c).decode()
            ctype = _CTYPE_MAP[lib.csv_coltype(handle, c)]
            names.append(name)
            types.append(ctype)
            if ctype is ColumnType.STRING:
                nbytes = lib.csv_string_col_bytes(handle, c)
                buf = ctypes.create_string_buffer(nbytes)
                lib.csv_string_col_packed(handle, c, buf)
                values = buf.raw[: nbytes - 1].split(b"\0") if nbytes else []
                cols[name] = np.asarray(
                    [v.decode() for v in values], dtype=object
                )
            elif ctype is ColumnType.INT:
                buf = np.empty(nrows, np.int64)
                lib.csv_ints(
                    handle, c,
                    buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                )
                cols[name] = buf
            else:
                buf = np.empty(nrows, np.float64)
                lib.csv_numeric(
                    handle, c,
                    buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                )
                cols[name] = buf
        return Table(cols, Schema(tuple(names), tuple(types)))
    finally:
        lib.csv_free(handle)
