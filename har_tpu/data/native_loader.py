"""ctypes bridge to the native C++ CSV loader (native/csvloader.cpp).

Builds ``libharcsv.so`` with g++ on first use (cached next to the source;
pybind11 isn't available in this image, so the library exposes a plain C
ABI).  ``read_csv_native`` returns the same Table the pure-Python loader
produces — identical schema-inference semantics, verified by tests — and
``har_tpu.data.csv_loader.read_csv(engine="auto")`` prefers it when the
toolchain is present, falling back to Python otherwise.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from har_tpu.data._native_build import NativeLib
from har_tpu.data.schema import ColumnType, Schema
from har_tpu.data.table import Table

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)


def _configure(lib: ctypes.CDLL) -> None:
    lib.csv_load.restype = ctypes.c_void_p
    lib.csv_load.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.csv_error.restype = ctypes.c_char_p
    lib.csv_error.argtypes = [ctypes.c_void_p]
    lib.csv_ncols.restype = ctypes.c_int
    lib.csv_ncols.argtypes = [ctypes.c_void_p]
    lib.csv_nrows.restype = ctypes.c_int64
    lib.csv_nrows.argtypes = [ctypes.c_void_p]
    lib.csv_colname.restype = ctypes.c_char_p
    lib.csv_colname.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.csv_coltype.restype = ctypes.c_int
    lib.csv_coltype.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.csv_numeric.restype = None
    lib.csv_numeric.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.csv_ints.restype = None
    lib.csv_ints.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.csv_string_at.restype = ctypes.c_char_p
    lib.csv_string_at.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int64,
    ]
    lib.csv_string_col_bytes.restype = ctypes.c_int64
    lib.csv_string_col_bytes.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.csv_string_col_packed.restype = None
    lib.csv_string_col_packed.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
    ]
    lib.csv_free.restype = None
    lib.csv_free.argtypes = [ctypes.c_void_p]


_NATIVE = NativeLib(
    src=os.path.join(_NATIVE_DIR, "csvloader.cpp"),
    so=os.path.join(_NATIVE_DIR, "libharcsv.so"),
    configure=_configure,
)


def native_available() -> bool:
    return _NATIVE.available()


_CTYPE_MAP = {0: ColumnType.INT, 1: ColumnType.DOUBLE, 2: ColumnType.STRING}


def read_csv_native(path: str, num_threads: int = 0) -> Table:
    lib = _NATIVE.load()
    if lib is None:
        raise RuntimeError(f"native loader unavailable: {_NATIVE.build_error}")
    handle = lib.csv_load(path.encode(), num_threads)
    try:
        err = lib.csv_error(handle)
        if err:
            raise FileNotFoundError(err.decode())
        ncols = lib.csv_ncols(handle)
        nrows = lib.csv_nrows(handle)
        names, types, cols = [], [], {}
        for c in range(ncols):
            name = lib.csv_colname(handle, c).decode()
            ctype = _CTYPE_MAP[lib.csv_coltype(handle, c)]
            names.append(name)
            types.append(ctype)
            if ctype is ColumnType.STRING:
                nbytes = lib.csv_string_col_bytes(handle, c)
                buf = ctypes.create_string_buffer(nbytes)
                lib.csv_string_col_packed(handle, c, buf)
                values = buf.raw[: nbytes - 1].split(b"\0") if nbytes else []
                cols[name] = np.asarray(
                    [v.decode() for v in values], dtype=object
                )
            elif ctype is ColumnType.INT:
                buf = np.empty(nrows, np.int64)
                lib.csv_ints(
                    handle, c,
                    buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                )
                cols[name] = buf
            else:
                buf = np.empty(nrows, np.float64)
                lib.csv_numeric(
                    handle, c,
                    buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                )
                cols[name] = buf
        return Table(cols, Schema(tuple(names), tuple(types)))
    finally:
        lib.csv_free(handle)
