"""spark-csv-compatible schema inference.

The reference reads its CSV with ``inferschema='true'`` through
``com.databricks.spark.csv`` (reference Main/main.py:18-20).  That package
types each column by attempting, over *all* rows, the narrowest type in the
chain int → long → double → string.  Fidelity here matters: the WISDM
``XPEAK/YPEAK/ZPEAK`` columns contain ``?`` sentinel values, so they infer as
*strings* and flow into the one-hot path, producing the 3,100-dim feature
space (SURVEY §2 F/G).  Were they parsed as doubles, the feature space would
collapse to 13 dims and none of the reference numbers would reproduce.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Sequence

import numpy as np


class ColumnType(enum.Enum):
    INT = "int"
    DOUBLE = "double"
    STRING = "string"

    @property
    def spark_name(self) -> str:
        """Type name as Spark's printSchema spells it (result.txt:4-17)."""
        return "integer" if self is ColumnType.INT else self.value


def _is_int(value: str) -> bool:
    try:
        int(value)
        return True
    except ValueError:
        return False


def _is_double(value: str) -> bool:
    try:
        float(value)
        return True
    except ValueError:
        return False


def infer_column_type(values: Sequence[str]) -> ColumnType:
    """Narrowest of int → double → string that parses every value."""
    current = ColumnType.INT
    for v in values:
        if current is ColumnType.INT:
            if _is_int(v):
                continue
            current = ColumnType.DOUBLE
        if current is ColumnType.DOUBLE:
            if _is_double(v):
                continue
            return ColumnType.STRING
    return current


@dataclasses.dataclass(frozen=True)
class Schema:
    names: tuple[str, ...]
    types: tuple[ColumnType, ...]

    def __post_init__(self):
        if len(self.names) != len(self.types):
            raise ValueError("names and types length mismatch")

    def type_of(self, name: str) -> ColumnType:
        return self.types[self.names.index(name)]

    def numpy_dtype(self, name: str):
        t = self.type_of(name)
        if t is ColumnType.INT:
            return np.int64
        if t is ColumnType.DOUBLE:
            return np.float64
        return object


def infer_schema(names: Sequence[str], columns: Sequence[Sequence[str]]) -> Schema:
    return Schema(
        names=tuple(names),
        types=tuple(infer_column_type(col) for col in columns),
    )
