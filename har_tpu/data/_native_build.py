"""Shared g++-build-and-load scaffolding for the native C++ libraries.

Each native component (csvloader, rawloader) is a single translation unit
with a plain C ABI, compiled on first use and cached next to its source
(pybind11 isn't in this image, so callers bind symbols via ctypes).  This
module owns the build/staleness/locking logic so the per-library bridges
only declare their symbol tables.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable


class NativeLib:
    """Lazily built, process-cached ctypes library handle."""

    def __init__(
        self,
        src: str,
        so: str,
        configure: Callable[[ctypes.CDLL], None],
        extra_flags: tuple[str, ...] = (),
    ):
        self._src = src
        self._so = so
        self._configure = configure
        self._extra_flags = extra_flags
        self._lock = threading.Lock()
        self._lib: ctypes.CDLL | None = None
        self.build_error: str | None = None

    def _build(self) -> str | None:
        """Compile if stale; returns an error string or None."""
        try:
            if os.path.exists(self._so) and os.path.getmtime(
                self._so
            ) >= os.path.getmtime(self._src):
                return None
        except OSError as e:  # source missing alongside a shipped .so
            if os.path.exists(self._so):
                return None
            return f"native source unavailable: {e}"
        cmd = [
            "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
            *self._extra_flags, self._src, "-o", self._so,
        ]
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=120
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            return f"g++ unavailable: {e}"
        if proc.returncode != 0:
            return f"native build failed: {proc.stderr[-500:]}"
        return None

    def load(self) -> ctypes.CDLL | None:
        with self._lock:
            if self._lib is not None or self.build_error is not None:
                return self._lib
            err = self._build()
            if err is not None:
                self.build_error = err
                return None
            try:
                lib = self._load_and_configure()
            except (OSError, AttributeError):
                # a stale shipped .so (e.g. checked out with arbitrary
                # mtimes so the staleness check passed) may miss newer
                # symbols — force ONE rebuild from the present source
                # before degrading to unavailable (never raise through
                # every consumer's available() fallback)
                try:
                    os.remove(self._so)
                except OSError:
                    pass
                err = self._build()
                if err is not None:
                    self.build_error = err
                    return None
                try:
                    lib = self._load_and_configure()
                except (OSError, AttributeError) as e:
                    self.build_error = f"native library unusable: {e}"
                    return None
            self._lib = lib
            return self._lib

    def _load_and_configure(self) -> ctypes.CDLL:
        lib = ctypes.CDLL(self._so)
        self._configure(lib)
        return lib

    def available(self) -> bool:
        return self.load() is not None
