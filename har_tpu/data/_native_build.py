"""Shared g++-build-and-load scaffolding for the native C++ libraries.

Each native component (csvloader, rawloader) is a single translation unit
with a plain C ABI, compiled on first use and cached next to its source
(pybind11 isn't in this image, so callers bind symbols via ctypes).  This
module owns the build/staleness/locking logic so the per-library bridges
only declare their symbol tables.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Callable

# Exported by every .so we build (see _build): the sha256 of the source
# it was compiled from.  _load_and_configure verifies it against the
# on-disk source, so a stale shipped binary can never masquerade as
# current — git checkouts don't preserve mtimes, which made the old
# mtime-only staleness check unsound for committed .so files.
_HASH_SYMBOL = "har_native_source_hash"


class NativeLib:
    """Lazily built, process-cached ctypes library handle."""

    def __init__(
        self,
        src: str,
        so: str,
        configure: Callable[[ctypes.CDLL], None],
        extra_flags: tuple[str, ...] = (),
    ):
        self._src = src
        self._so = so
        self._configure = configure
        self._extra_flags = extra_flags
        self._lock = threading.Lock()
        self._lib: ctypes.CDLL | None = None
        self.build_error: str | None = None

    def _source_hash(self) -> str | None:
        try:
            with open(self._src, "rb") as f:
                return hashlib.sha256(f.read()).hexdigest()
        except OSError:
            return None

    def _build(self, force: bool = False) -> str | None:
        """Compile if absent (or force=True); returns an error string or None.

        Existence is the only fast-path here — true staleness (source
        edited since the .so was built) is caught by the embedded-hash
        check in _load_and_configure, which retries with force=True.
        The compile goes to a temp path and lands via os.replace, so a
        failed rebuild never destroys a working shipped binary and no
        process can dlopen a half-written one.
        """
        if not force and os.path.exists(self._so):
            return None
        if not os.path.exists(self._src):
            return "native source unavailable"
        src_hash = self._source_hash()
        if src_hash is None:
            return "native source unreadable"
        # a tiny second TU embeds the source hash as an exported symbol,
        # so the binary itself carries its provenance.  The non-brace
        # extern "C" form is load-bearing: it implies `extern` storage,
        # without which a namespace-scope const char[] has internal
        # linkage and never reaches the dynamic symbol table.
        hash_cpp = (
            f'extern "C" const char {_HASH_SYMBOL}[] = "{src_hash}";\n'
        )
        so_dir = os.path.dirname(self._so) or "."
        hash_src = tmp_so = None
        try:
            try:
                fd, hash_src = tempfile.mkstemp(suffix=".cpp")
                with os.fdopen(fd, "w") as tmp:
                    tmp.write(hash_cpp)
                tmp_so = os.path.join(
                    so_dir,
                    f".{os.path.basename(self._so)}.{os.getpid()}.tmp",
                )
            except OSError as e:  # unwritable temp dir degrades, not raises
                return f"native build staging failed: {e}"
            cmd = [
                "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
                *self._extra_flags, self._src, hash_src, "-o", tmp_so,
            ]
            try:
                proc = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=120
                )
            except (OSError, subprocess.TimeoutExpired) as e:
                return f"g++ unavailable: {e}"
            if proc.returncode != 0:
                return f"native build failed: {proc.stderr[-500:]}"
            try:
                os.replace(tmp_so, self._so)
            except OSError as e:
                return f"native library install failed: {e}"
            return None
        finally:
            for path in (hash_src, tmp_so):
                if path is None:
                    continue
                try:
                    os.remove(path)
                except OSError:
                    pass

    def load(self) -> ctypes.CDLL | None:
        with self._lock:
            if self._lib is not None or self.build_error is not None:
                return self._lib
            err = self._build()
            if err is not None:
                self.build_error = err
                return None
            try:
                lib = self._load_and_configure()
            except (OSError, AttributeError):
                # a stale shipped .so (hash mismatch, missing provenance
                # symbol, or missing newer symbols) — force ONE rebuild
                # from the present source before degrading to unavailable
                # (never raise through every consumer's available()
                # fallback).  The stale binary stays on disk until the
                # replacement lands (os.replace in _build).
                err = self._build(force=True)
                if err is not None:
                    self.build_error = err
                    return None
                try:
                    lib = self._load_and_configure()
                except (OSError, AttributeError) as e:
                    self.build_error = f"native library unusable: {e}"
                    return None
            self._lib = lib
            return self._lib

    def _load_and_configure(self) -> ctypes.CDLL:
        lib = ctypes.CDLL(self._so)
        try:
            return self._verify_and_configure(lib)
        except Exception:
            # unmap the rejected library: dlopen caches by pathname, so
            # without dlclose the forced rebuild would reload THIS stale
            # mapping instead of the fresh binary
            try:
                import _ctypes

                _ctypes.dlclose(lib._handle)
            except Exception:
                pass
            raise

    def _verify_and_configure(self, lib: ctypes.CDLL) -> ctypes.CDLL:
        # provenance check: the hash baked in at build time must match the
        # present source.  A shipped .so predating the hash symbol raises
        # AttributeError, a mismatched one OSError — both land in load()'s
        # single forced-rebuild path.  If the source is gone entirely
        # (binary-only install), the shipped binary is all there is: trust it.
        src_hash = self._source_hash()
        if src_hash is not None:
            try:
                arr = (ctypes.c_char * (len(src_hash) + 1)).in_dll(
                    lib, _HASH_SYMBOL
                )
            except ValueError as e:  # symbol absent: pre-hash-era binary
                raise OSError(
                    f"native library lacks provenance symbol: {e}"
                ) from e
            embedded = arr.value.decode("ascii", "replace")
            if embedded != src_hash:
                raise OSError(
                    f"stale native library {self._so}: built from source "
                    f"{embedded[:12]}…, current source is {src_hash[:12]}…"
                )
        self._configure(lib)
        return lib

    def available(self) -> bool:
        return self.load() is not None
