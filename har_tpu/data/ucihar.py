"""UCI-HAR (smartphone) dataset adapter.

The reference paper's second benchmark (paper §4: "KAGGLE Data-set" —
UCI-HAR smartphones, 561 precomputed features, 6 classes; BASELINE.md:
LR+CV reaches 91.9% accuracy there).  The repo itself ships only WISDM;
this adapter accepts the published "UCI HAR Dataset" layout so the same
pipeline runs both benchmarks:

  <root>/train/X_train.txt        561 fixed-width scientific-notation
                                  columns per row (3-digit exponents,
                                  e.g. " 2.8858451e-001")
  <root>/train/y_train.txt        labels 1..6, one per line
  <root>/train/subject_train.txt  subject ids 1..30, one per line
  <root>/test/...                 same three files
  <root>/features.txt             "1 tBodyAcc-mean()-X" … (561 rows,
                                  names NOT unique in the published file)
  <root>/activity_labels.txt      "1 WALKING" … "6 LAYING"

``root`` may be the directory that CONTAINS "UCI HAR Dataset" too (the
published zip's layout); subject/features/activity files are optional —
the loader degrades to the canonical defaults when they're absent.

Returned as a Table with FEAT_0..FEAT_560 double columns (+ SUBJECT when
shipped) + ACTIVITY string labels, so StringIndexer/VectorAssembler/
report layers treat it exactly like WISDM.  ``write_ucihar_fixture``
emits this exact byte format so tests exercise the real parser contract
offline (the environment cannot fetch the published archive).
"""

from __future__ import annotations

import os

import numpy as np

from har_tpu.data.schema import ColumnType, Schema
from har_tpu.data.table import Table

# canonical UCI-HAR activity names, label order 1..6
UCIHAR_ACTIVITIES = (
    "WALKING",
    "WALKING_UPSTAIRS",
    "WALKING_DOWNSTAIRS",
    "SITTING",
    "STANDING",
    "LAYING",
)

NUM_FEATURES = 561


def _to_table(
    x: np.ndarray,
    y: np.ndarray,
    subjects: np.ndarray | None = None,
    activities: tuple[str, ...] = UCIHAR_ACTIVITIES,
) -> Table:
    names = [f"FEAT_{i}" for i in range(x.shape[1])]
    types = [ColumnType.DOUBLE] * x.shape[1]
    cols = {f"FEAT_{i}": x[:, i] for i in range(x.shape[1])}
    if subjects is not None:
        names.append("SUBJECT")
        types.append(ColumnType.INT)
        cols["SUBJECT"] = np.asarray(subjects, np.int64)
    names.append("ACTIVITY")
    types.append(ColumnType.STRING)
    cols["ACTIVITY"] = np.asarray(
        [activities[int(lab) - 1] for lab in y], dtype=object
    )
    return Table(cols, Schema(tuple(names), tuple(types)))


def _resolve_root(root: str) -> str:
    """Accept the dir holding train/test or the published zip's nesting.

    The marker is train/X_train.txt, not a bare train/ directory — any
    ML-style checkout has a train/ folder, and a false positive here
    turns resolve_ucihar_root's graceful skip into a FileNotFoundError
    deep inside the parity lane.
    """
    for cand in (root, os.path.join(root, "UCI HAR Dataset")):
        if os.path.isfile(os.path.join(cand, "train", "X_train.txt")):
            return cand
    raise FileNotFoundError(
        f"no UCI-HAR train/X_train.txt under {root!r} "
        "(or its 'UCI HAR Dataset' subdirectory)"
    )


def _read_indexed_names(path: str) -> tuple[str, ...] | None:
    """'<index> <name>' files (features.txt / activity_labels.txt)."""
    if not os.path.exists(path):
        return None
    names = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                names.append(line.split(maxsplit=1)[1])
    return tuple(names)


def resolve_ucihar_root() -> str | None:
    """Locate a real 'UCI HAR Dataset' tree, or None.

    Probes $HAR_TPU_UCIHAR_ROOT first, then conventional data dirs.  The
    paper-parity lane (har_tpu.parity.ucihar_parity_lane, VERDICT r3
    item 5) keys off this: present → run LR+CV and check the published
    ≈0.91 accuracy; absent → skip with a clear message.  The offline
    environment cannot fetch the archive, so the lane stays falsifiable
    without being runnable here.
    """
    candidates = [
        os.environ.get("HAR_TPU_UCIHAR_ROOT"),
        ".",
        "./data",
        os.path.expanduser("~/data"),
    ]
    for cand in candidates:
        if not cand:
            continue
        try:
            return _resolve_root(cand)
        except FileNotFoundError:
            continue
    return None


def load_ucihar(root: str, split: str = "all") -> Table:
    """Load train/test/all splits from a published-layout UCI-HAR tree."""
    root = _resolve_root(root)
    parts = {"train": ["train"], "test": ["test"], "all": ["train", "test"]}[
        split
    ]
    activities = (
        _read_indexed_names(os.path.join(root, "activity_labels.txt"))
        or UCIHAR_ACTIVITIES
    )
    features = _read_indexed_names(os.path.join(root, "features.txt"))
    xs, ys, subs = [], [], []
    for part in parts:
        d = os.path.join(root, part)
        x = np.loadtxt(os.path.join(d, f"X_{part}.txt"), dtype=np.float64)
        if features is not None and x.shape[1] != len(features):
            raise ValueError(
                f"X_{part}.txt has {x.shape[1]} columns but features.txt "
                f"names {len(features)}"
            )
        xs.append(x)
        ys.append(
            np.loadtxt(os.path.join(d, f"y_{part}.txt"), dtype=np.int64)
        )
        sub_path = os.path.join(d, f"subject_{part}.txt")
        if os.path.exists(sub_path):
            subs.append(np.loadtxt(sub_path, dtype=np.int64))
    subjects = np.concatenate(subs) if len(subs) == len(parts) else None
    return _to_table(
        np.concatenate(xs), np.concatenate(ys), subjects, activities
    )


def format_ucihar_value(v: float) -> str:
    """One X_*.txt field: 7-decimal scientific notation with the published
    files' 3-digit exponent (' 2.8858451e-001' / '-9.9527860e-001')."""
    mantissa, exp = f"{float(v):.7e}".split("e")
    return f"{mantissa}e{exp[0]}{exp[1:].lstrip('0').zfill(3)}"


def write_ucihar_fixture(
    root: str,
    n_train: int = 64,
    n_test: int = 32,
    seed: int = 0,
    num_features: int = NUM_FEATURES,
) -> str:
    """Write a byte-faithful "UCI HAR Dataset" tree with synthetic data.

    Reproduces the published archive's on-disk contract: the nested
    directory name, fixed-width space-padded X columns with 3-digit
    exponents, per-line y/subject files, features.txt (561 indexed names,
    including the real file's duplicated-name quirk) and
    activity_labels.txt.  Returns the nested dataset root.
    """
    rng = np.random.default_rng((seed, 561))
    base = os.path.join(root, "UCI HAR Dataset")
    means = rng.normal(0.0, 1.5, size=(6, num_features))
    os.makedirs(base, exist_ok=True)
    with open(os.path.join(base, "activity_labels.txt"), "w") as f:
        for i, name in enumerate(UCIHAR_ACTIVITIES, start=1):
            f.write(f"{i} {name}\n")
    with open(os.path.join(base, "features.txt"), "w") as f:
        for i in range(1, num_features + 1):
            # the published file repeats names (fBodyAcc-bandsEnergy()
            # blocks); reproduce the quirk so loaders can't assume
            # uniqueness
            name = f"tBodyAcc-mean()-{'XYZ'[i % 3]}" if i % 7 == 0 else (
                f"feat-{i}()"
            )
            f.write(f"{i} {name}\n")
    for part, n in (("train", n_train), ("test", n_test)):
        d = os.path.join(base, part)
        os.makedirs(d, exist_ok=True)
        y = rng.integers(1, 7, size=n)
        subjects = rng.integers(1, 31, size=n)
        x = np.clip(
            means[y - 1] + rng.normal(0.0, 1.0, size=(n, num_features)),
            -10,
            10,
        )
        with open(os.path.join(d, f"X_{part}.txt"), "w") as f:
            for row in x:
                f.write(
                    " ".join(
                        format_ucihar_value(v).rjust(16) for v in row
                    )
                    + "\n"
                )
        with open(os.path.join(d, f"y_{part}.txt"), "w") as f:
            f.writelines(f"{int(v)}\n" for v in y)
        with open(os.path.join(d, f"subject_{part}.txt"), "w") as f:
            f.writelines(f"{int(v)}\n" for v in subjects)
    return base


def synthetic_ucihar(n_rows: int = 2000, seed: int = 0) -> Table:
    """Synthetic stand-in with the UCI-HAR shape (tests / no-data envs)."""
    rng = np.random.default_rng((seed, 20907))
    y = rng.integers(1, 7, size=n_rows)
    means = rng.normal(0.0, 1.5, size=(6, NUM_FEATURES))
    x = means[y - 1] + rng.normal(0.0, 1.0, size=(n_rows, NUM_FEATURES))
    return _to_table(x, y)


def ucihar_feature_set(table: Table):
    """Table → FeatureSet (features already numeric; label via indexer)."""
    from har_tpu.features.string_indexer import StringIndexer
    from har_tpu.features.wisdm_pipeline import FeatureSet

    feat_cols = [c for c in table.column_names if c.startswith("FEAT_")]
    x = np.stack([np.asarray(table[c], np.float32) for c in feat_cols], 1)
    y = np.asarray(
        StringIndexer("ACTIVITY", "label").fit(table).transform(table)["label"],
        np.int32,
    )
    return FeatureSet(features=x, label=y)
