"""UCI-HAR (smartphone) dataset adapter.

The reference paper's second benchmark (paper §4: "KAGGLE Data-set" —
UCI-HAR smartphones, 561 precomputed features, 6 classes; BASELINE.md:
LR+CV reaches 91.9% accuracy there).  The repo itself ships only WISDM;
this adapter accepts the standard UCI-HAR layout so the same pipeline
runs both benchmarks:

  <root>/train/X_train.txt   whitespace-separated 561-feature rows
  <root>/train/y_train.txt   labels 1..6
  <root>/test/X_test.txt, <root>/test/y_test.txt
  (or a single CSV with a 'label'/'Activity' column)

Returned as a Table with FEAT_0..FEAT_560 double columns + ACTIVITY
string labels, so StringIndexer/VectorAssembler/report layers treat it
exactly like WISDM.
"""

from __future__ import annotations

import os

import numpy as np

from har_tpu.data.schema import ColumnType, Schema
from har_tpu.data.table import Table

# canonical UCI-HAR activity names, label order 1..6
UCIHAR_ACTIVITIES = (
    "WALKING",
    "WALKING_UPSTAIRS",
    "WALKING_DOWNSTAIRS",
    "SITTING",
    "STANDING",
    "LAYING",
)

NUM_FEATURES = 561


def _to_table(x: np.ndarray, y: np.ndarray) -> Table:
    names = [f"FEAT_{i}" for i in range(x.shape[1])] + ["ACTIVITY"]
    types = [ColumnType.DOUBLE] * x.shape[1] + [ColumnType.STRING]
    cols = {f"FEAT_{i}": x[:, i] for i in range(x.shape[1])}
    cols["ACTIVITY"] = np.asarray(
        [UCIHAR_ACTIVITIES[int(lab) - 1] for lab in y], dtype=object
    )
    return Table(cols, Schema(tuple(names), tuple(types)))


def load_ucihar(root: str, split: str = "all") -> Table:
    """Load train/test/all splits from a UCI-HAR directory tree."""
    parts = {"train": ["train"], "test": ["test"], "all": ["train", "test"]}[
        split
    ]
    xs, ys = [], []
    for part in parts:
        xs.append(
            np.loadtxt(os.path.join(root, part, f"X_{part}.txt"), dtype=np.float64)
        )
        ys.append(
            np.loadtxt(os.path.join(root, part, f"y_{part}.txt"), dtype=np.int64)
        )
    return _to_table(np.concatenate(xs), np.concatenate(ys))


def synthetic_ucihar(n_rows: int = 2000, seed: int = 0) -> Table:
    """Synthetic stand-in with the UCI-HAR shape (tests / no-data envs)."""
    rng = np.random.default_rng((seed, 20907))
    y = rng.integers(1, 7, size=n_rows)
    means = rng.normal(0.0, 1.5, size=(6, NUM_FEATURES))
    x = means[y - 1] + rng.normal(0.0, 1.0, size=(n_rows, NUM_FEATURES))
    return _to_table(x, y)


def ucihar_feature_set(table: Table):
    """Table → FeatureSet (features already numeric; label via indexer)."""
    from har_tpu.features.string_indexer import StringIndexer
    from har_tpu.features.wisdm_pipeline import FeatureSet

    feat_cols = [c for c in table.column_names if c.startswith("FEAT_")]
    x = np.stack([np.asarray(table[c], np.float32) for c in feat_cols], 1)
    y = np.asarray(
        StringIndexer("ACTIVITY", "label").fit(table).transform(table)["label"],
        np.int32,
    )
    return FeatureSet(features=x, label=y)
