"""Host→device prefetching for streamed training data.

The scanned trainer keeps the whole dataset device-resident; for datasets
larger than HBM the streaming path feeds per-step batches from host
memory instead.  Spark hides this cost in its per-partition task pipeline
(executors deserialize the next partition while computing the current
one); the TPU-native equivalent is a small device-side buffer:
``jax.device_put`` is async, so issuing the next ``size`` transfers
before the current step's result is consumed overlaps PCIe/DMA with MXU
compute.

Cited behavior replaced: the reference streams nothing (its 5,418-row
dataset lives in executor memory, SURVEY §2 S); this exists for the
framework's larger-than-HBM regime.
"""

from __future__ import annotations

import collections
from typing import Callable, Iterable, Iterator, TypeVar

import jax

T = TypeVar("T")


def prefetch_to_device(
    iterator: Iterable[T],
    size: int = 2,
    transfer: Callable[[T], T] | None = None,
) -> Iterator[T]:
    """Yield items already on device, keeping ``size`` transfers in flight.

    ``transfer`` maps a host item to device arrays (default:
    ``jax.device_put`` on the whole pytree).  ``size=2`` (double
    buffering) suffices to hide transfer latency behind compute; larger
    sizes only add HBM pressure.
    """
    if size < 1:
        raise ValueError("prefetch size must be >= 1")
    put = transfer if transfer is not None else jax.device_put
    queue: collections.deque = collections.deque()
    it = iter(iterator)
    try:
        while True:
            while len(queue) < size:
                queue.append(put(next(it)))
            yield queue.popleft()
    except StopIteration:
        while queue:
            yield queue.popleft()
