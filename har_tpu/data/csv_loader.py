"""CSV ingestion with spark-csv semantics.

Replaces the reference's ``com.databricks.spark.csv`` read (reference
Main/main.py:18-20): header row, full-pass schema inference, typed columns.

A native C++ fast path (native/csvloader.cpp via har_tpu/data/native_loader,
loaded through ctypes) parses files on worker threads when the toolchain is
available; the pure-Python path is authoritative and always available.
"""

from __future__ import annotations

import csv as _csv
from typing import Sequence

import numpy as np

from har_tpu.data.schema import ColumnType, Schema, infer_schema
from har_tpu.data.table import Table


def _columns_to_table(names: Sequence[str], columns: list[list[str]]) -> Table:
    schema = infer_schema(names, columns)
    out = {}
    for name, col in zip(names, columns):
        t = schema.type_of(name)
        if t is ColumnType.INT:
            out[name] = np.array([int(v) for v in col], dtype=np.int64)
        elif t is ColumnType.DOUBLE:
            out[name] = np.array([float(v) for v in col], dtype=np.float64)
        else:
            out[name] = np.array(col, dtype=object)
    return Table(out, schema)


def read_csv(
    path: str,
    header: bool = True,
    infer: bool = True,
    engine: str = "auto",
) -> Table:
    """Read a CSV file into a columnar Table.

    `header=True, infer=True` matches the reference's read options
    (Main/main.py:18-20).  Without inference every column is a string.

    engine: "auto" uses the multithreaded C++ parser when the toolchain is
    available (building it on first use), "native" requires it, "python"
    forces the pure-Python path.  Both produce identical Tables (tested).
    """
    if engine not in ("auto", "native", "python"):
        raise ValueError(f"unknown CSV engine {engine!r}")
    if engine == "native" and not (header and infer):
        raise ValueError(
            "engine='native' supports only header=True, infer=True"
        )
    if engine in ("auto", "native") and header and infer:
        try:
            from har_tpu.data.native_loader import (
                native_available,
                read_csv_native,
            )

            if native_available():
                return read_csv_native(path)
            if engine == "native":
                raise RuntimeError("native CSV engine unavailable")
        except Exception as exc:
            if engine == "native":
                raise
            # engine="auto": fall back to the Python parser, but never
            # silently — a native-parser regression must stay visible
            import warnings

            warnings.warn(
                "native CSV loader failed "
                f"({type(exc).__name__}: {exc}); falling back to the "
                "Python parser",
                RuntimeWarning,
                stacklevel=2,
            )
    with open(path, newline="") as f:
        reader = _csv.reader(f)
        rows = list(reader)
    if not rows:
        raise ValueError(f"empty CSV: {path}")
    if header:
        names, data = rows[0], rows[1:]
    else:
        names = [f"_c{i}" for i in range(len(rows[0]))]
        data = rows
    columns = [[row[i] for row in data] for i in range(len(names))]
    if not infer:
        schema = Schema(tuple(names), tuple(ColumnType.STRING for _ in names))
        return Table(
            {n: np.array(c, dtype=object) for n, c in zip(names, columns)},
            schema,
        )
    return _columns_to_table(names, columns)
