"""On-device augmentation for raw (B, T, 3) tri-axial accelerometer windows.

Standard HAR augmentations (jitter, per-axis scaling, 3-D rotation, time
masking), written as pure-JAX transforms so they run INSIDE the compiled
training step — no host round-trip per batch, fused with the forward pass
by XLA.  The reference has no augmentation (its windows are pre-collapsed
to summary features, SURVEY §2 S); this exists for the raw-window neural
configs (BASELINE.json 3/5) where generalization comes from exactly these
invariances: sensor noise (jitter), device placement/orientation
(rotation), per-device gain (scaling), and dropout-like occlusion (time
masking).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp


def _random_rotations(key: jax.Array, n: int, max_angle: float, dtype):
    """(n, 3, 3) rotation matrices: uniform random axis, angle ~ U(0, max)."""
    k_axis, k_angle = jax.random.split(key)
    axis = jax.random.normal(k_axis, (n, 3), dtype)
    axis = axis / jnp.maximum(
        jnp.linalg.norm(axis, axis=-1, keepdims=True), 1e-8
    )
    angle = jax.random.uniform(
        k_angle, (n,), dtype, minval=0.0, maxval=max_angle
    )
    c, s = jnp.cos(angle), jnp.sin(angle)
    x, y, z = axis[:, 0], axis[:, 1], axis[:, 2]
    # Rodrigues' rotation formula, batched
    zero = jnp.zeros_like(x)
    k_cross = jnp.stack(
        [
            jnp.stack([zero, -z, y], -1),
            jnp.stack([z, zero, -x], -1),
            jnp.stack([-y, x, zero], -1),
        ],
        -2,
    )  # (n, 3, 3)
    eye = jnp.eye(3, dtype=dtype)
    outer = axis[:, :, None] * axis[:, None, :]
    return (
        c[:, None, None] * eye
        + s[:, None, None] * k_cross
        + (1 - c)[:, None, None] * outer
    )


@dataclasses.dataclass(frozen=True)
class WindowAugment:
    """Composable augmentation policy; call as ``aug(key, x)`` per batch.

    Every transform is applied per window with independent randomness;
    zero-valued knobs disable their transform, so the default is a
    moderate policy and ``WindowAugment(0, 0, 0, 0)`` is the identity.
    """

    jitter_std: float = 0.03
    scale_std: float = 0.05
    max_rotation: float = 0.2  # radians
    time_mask_fraction: float = 0.1

    def __call__(self, key: jax.Array, x: jax.Array) -> jax.Array:
        if x.ndim != 3:
            raise ValueError(
                "window augmentation expects (batch, time, channels) "
                f"windows, got shape {tuple(x.shape)} — tabular feature "
                "models (e.g. mlp) cannot train with --augment"
            )
        b, t, c = x.shape
        kj, ks, kr, km = jax.random.split(key, 4)
        if self.jitter_std > 0:
            x = x + self.jitter_std * jax.random.normal(kj, x.shape, x.dtype)
        if self.scale_std > 0:
            scale = 1.0 + self.scale_std * jax.random.normal(
                ks, (b, 1, c), x.dtype
            )
            x = x * scale
        if self.max_rotation > 0 and c == 3:
            rot = _random_rotations(kr, b, self.max_rotation, x.dtype)
            x = jnp.einsum("btc,bdc->btd", x, rot)
        if self.time_mask_fraction > 0:
            span = max(1, int(round(t * self.time_mask_fraction)))
            start = jax.random.randint(km, (b, 1), 0, t - span + 1)
            pos = jnp.arange(t)[None, :]
            mask = (pos >= start) & (pos < start + span)
            x = jnp.where(mask[:, :, None], 0.0, x)
        return x


def build_augment(name: str | None) -> Callable | None:
    """Config-string → augmentation policy (None / "none" → no-op)."""
    if name is None or name == "none":
        return None
    if name == "raw_windows":
        return WindowAugment()
    raise ValueError(f"unknown augmentation policy {name!r}")
