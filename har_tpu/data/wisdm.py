"""WISDM v1.1 transformed-dataset adapter.

The dataset is 5,418 ten-second windows × 46 columns, 6 activity classes
(reference Main/wisdm_main_ver_0.0/data/wisdm_data.csv; SURVEY §2 S).  The
reference drops ``USER`` and the 30 histogram-bin columns ``X0..Z9``
(reference Main/main.py:22-26), keeping 15 columns: UID, 10 numeric summary
features, 3 string PEAK features, and the ACTIVITY label.
"""

from __future__ import annotations

import numpy as np

from har_tpu.data.csv_loader import read_csv
from har_tpu.data.table import Table

BINNED_COLUMNS = tuple(
    f"{axis}{i}" for axis in ("X", "Y", "Z") for i in range(10)
)

# Numeric feature columns assembled by the reference (Main/main.py:63-66):
# 3,090 one-hot dims + these 10 = the 3,100-dim vectors in result.txt.
# XAVG is all-zero in the shipped CSV but is still assembled.
WISDM_NUMERIC_COLUMNS = (
    "XAVG",
    "YAVG",
    "ZAVG",
    "XABSDEV",
    "YABSDEV",
    "ZABSDEV",
    "XSTDDEV",
    "YSTDDEV",
    "ZSTDDEV",
    "RESULTANT",
)

# Time-between-peaks columns; contain '?' sentinels so they infer as strings
# and are one-hot encoded (reference Main/main.py:51-58).
WISDM_CATEGORICAL_COLUMNS = ("XPEAK", "YPEAK", "ZPEAK")

LABEL_COLUMN = "ACTIVITY"

ACTIVITIES = (
    "Walking",
    "Jogging",
    "Upstairs",
    "Downstairs",
    "Sitting",
    "Standing",
)


def load_wisdm(
    path: str, drop_binned: bool = True, drop_user: bool = True
) -> Table:
    table = read_csv(path)
    drops: list[str] = []
    if drop_user:
        drops.append("USER")
    if drop_binned:
        drops.extend(BINNED_COLUMNS)
    return table.drop(drops) if drops else table


def numeric_feature_view(
    table: Table,
    include_binned: bool = False,
    missing_value: float = -1.0,
) -> tuple[np.ndarray, tuple[str, ...]]:
    """The *numeric* reading of the WISDM features: PEAK columns parsed as
    floats ('?' → ``missing_value``) instead of one-hot categories.

    The reference's 3,100-dim one-hot space is an artifact of spark-csv
    schema inference reading the PEAK columns (times-between-peaks in ms)
    as strings (SURVEY §2 F).  Treating them as the numbers they are is
    both far smaller and far more informative — the neural models reach
    ~0.87 test accuracy on this 13-dim view vs 0.73 for the reference's
    best classical model on the one-hot space.
    """
    names: list[str] = list(WISDM_NUMERIC_COLUMNS)
    cols = [np.asarray(table[c], np.float64) for c in WISDM_NUMERIC_COLUMNS]
    for c in WISDM_CATEGORICAL_COLUMNS:
        raw = table[c]
        vals = np.array(
            [
                float(v) if v not in ("?", "") else missing_value
                for v in raw
            ],
            np.float64,
        )
        cols.append(vals)
        names.append(c)
    if include_binned:
        for c in BINNED_COLUMNS:
            cols.append(np.asarray(table[c], np.float64))
            names.append(c)
    return np.stack(cols, axis=1).astype(np.float32), tuple(names)
