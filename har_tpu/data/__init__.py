from har_tpu.data.schema import ColumnType, Schema, infer_schema
from har_tpu.data.table import Table
from har_tpu.data.csv_loader import read_csv
from har_tpu.data.split import random_split
from har_tpu.data.spark_split import mllib_vocab, spark_split_indices
from har_tpu.data.wisdm import load_wisdm, WISDM_NUMERIC_COLUMNS, WISDM_CATEGORICAL_COLUMNS
from har_tpu.data.synthetic import synthetic_wisdm
from har_tpu.data.raw_loader import RawStream, load_raw_stream, stream_windows
from har_tpu.data.prefetch import prefetch_to_device

__all__ = [
    "RawStream",
    "prefetch_to_device",
    "load_raw_stream",
    "stream_windows",
    "ColumnType",
    "Schema",
    "infer_schema",
    "Table",
    "read_csv",
    "random_split",
    "spark_split_indices",
    "mllib_vocab",
    "load_wisdm",
    "synthetic_wisdm",
    "WISDM_NUMERIC_COLUMNS",
    "WISDM_CATEGORICAL_COLUMNS",
]
