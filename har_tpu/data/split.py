"""Seeded random train/test split.

The reference uses ``df.randomSplit([0.7, 0.3], seed=2018)`` (reference
Main/main.py:80), which is per-row Bernoulli sampling — split sizes are
random around the requested fractions (3,793/1,625 in the captured run).  We
keep the same semantics (per-row uniform draw against cumulative fraction
boundaries, deterministic under a seed) rather than exact-count slicing, so
behavior under resampling matches Spark's.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from har_tpu.data.table import Table


def split_indices(
    n: int, fractions: Sequence[float], seed: int
) -> list[np.ndarray]:
    fracs = np.asarray(fractions, dtype=np.float64)
    if np.any(fracs < 0):
        raise ValueError("fractions must be non-negative")
    bounds = np.cumsum(fracs / fracs.sum())
    draws = np.random.default_rng(seed).random(n)
    out = []
    lo = 0.0
    for hi in bounds:
        out.append(np.nonzero((draws >= lo) & (draws < hi))[0])
        lo = hi
    # rows drawing exactly 1.0 cannot occur ([0,1) support), so partitions
    # are exhaustive and disjoint.
    return out


def random_split(
    table: Table, fractions: Sequence[float], seed: int
) -> list[Table]:
    return [table.take(idx) for idx in split_indices(len(table), fractions, seed)]
