"""Raw WISDM v1.1 accelerometer stream ingestion (native C++ + fallback).

The reference consumes the *transformed* WISDM CSV; the transform's input
is the raw stream ``WISDM_ar_v1.1_raw.txt`` — records of the form
``user,activity,timestamp,x,y,z;`` separated by ';' and/or newlines.  This
module loads that format into columnar arrays:

  - :func:`read_raw_native` — threaded C++ parser (native/rawloader.cpp,
    ctypes ABI, built with g++ on first use);
  - :func:`read_raw_python` — pure-numpy fallback with the same tolerant
    semantics (malformed records skipped + counted);
  - :func:`load_raw_stream` — ``engine='auto'`` front door;
  - :func:`stream_windows` — group the stream into contiguous
    (user, activity) bouts and segment each into fixed-length windows
    (feeds har_tpu.data.raw_windows.WindowedDataset → the jitted
    featurizer in har_tpu.features.raw_features or the neural models).

Together with the native CSV loader this replaces the ingestion half of
the reference's Spark data layer (reference Main/main.py:16-26; SURVEY
§2b spark-csv row) for both dataset forms.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os

import numpy as np

from har_tpu.data._native_build import NativeLib

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)


@dataclasses.dataclass(frozen=True)
class RawStream:
    """Columnar raw accelerometer stream."""

    user: np.ndarray        # (n,) int32
    activity: np.ndarray    # (n,) int32 ids into activity_names
    activity_names: tuple[str, ...]   # first-appearance order
    timestamp: np.ndarray   # (n,) int64 (nanoseconds in the public file)
    xyz: np.ndarray         # (n, 3) float32
    skipped: int = 0        # malformed records dropped during parse

    def __len__(self) -> int:
        return len(self.user)


def _configure(lib: ctypes.CDLL) -> None:
    lib.raw_load.restype = ctypes.c_void_p
    lib.raw_load.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.raw_error.restype = ctypes.c_char_p
    lib.raw_error.argtypes = [ctypes.c_void_p]
    lib.raw_nrows.restype = ctypes.c_int64
    lib.raw_nrows.argtypes = [ctypes.c_void_p]
    lib.raw_skipped.restype = ctypes.c_int64
    lib.raw_skipped.argtypes = [ctypes.c_void_p]
    lib.raw_num_activities.restype = ctypes.c_int
    lib.raw_num_activities.argtypes = [ctypes.c_void_p]
    lib.raw_activity_name.restype = ctypes.c_char_p
    lib.raw_activity_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
    for fn, ctype in (
        ("raw_users", ctypes.c_int32),
        ("raw_activities", ctypes.c_int32),
        ("raw_timestamps", ctypes.c_int64),
        ("raw_xyz", ctypes.c_float),
    ):
        getattr(lib, fn).restype = None
        getattr(lib, fn).argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctype)
        ]
    lib.raw_free.restype = None
    lib.raw_free.argtypes = [ctypes.c_void_p]


_NATIVE = NativeLib(
    src=os.path.join(_NATIVE_DIR, "rawloader.cpp"),
    so=os.path.join(_NATIVE_DIR, "libharraw.so"),
    configure=_configure,
)


def native_available() -> bool:
    return _NATIVE.available()


def read_raw_native(path: str, num_threads: int = 0) -> RawStream:
    lib = _NATIVE.load()
    if lib is None:
        raise RuntimeError(
            f"native raw loader unavailable: {_NATIVE.build_error}"
        )
    handle = lib.raw_load(path.encode(), num_threads)
    try:
        err = lib.raw_error(handle)
        if err:
            raise FileNotFoundError(err.decode())
        n = lib.raw_nrows(handle)
        names = tuple(
            lib.raw_activity_name(handle, i).decode()
            for i in range(lib.raw_num_activities(handle))
        )
        user = np.empty(n, np.int32)
        act = np.empty(n, np.int32)
        ts = np.empty(n, np.int64)
        xyz = np.empty((n, 3), np.float32)
        lib.raw_users(handle, user.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        lib.raw_activities(
            handle, act.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        )
        lib.raw_timestamps(
            handle, ts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        )
        lib.raw_xyz(handle, xyz.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return RawStream(
            user=user, activity=act, activity_names=names,
            timestamp=ts, xyz=xyz, skipped=int(lib.raw_skipped(handle)),
        )
    finally:
        lib.raw_free(handle)


def read_raw_python(path: str) -> RawStream:
    """Pure-Python reference parser with identical semantics."""
    with open(path, "rb") as f:
        text = f.read().decode("utf-8", errors="replace")
    users, acts, tss, xs, ys, zs = [], [], [], [], [], []
    names: list[str] = []
    vocab: dict[str, int] = {}
    skipped = 0
    for rec in text.replace("\n", ";").split(";"):
        rec = rec.strip()
        if not rec:
            continue
        parts = rec.split(",")
        if len(parts) != 6:
            skipped += 1
            continue
        try:
            uid = int(parts[0])
            ts = int(parts[2])
            fx, fy, fz = float(parts[3]), float(parts[4]), float(parts[5])
        except ValueError:
            skipped += 1
            continue
        act = parts[1]
        if act not in vocab:
            vocab[act] = len(names)
            names.append(act)
        users.append(uid)
        acts.append(vocab[act])
        tss.append(ts)
        xs.append(fx)
        ys.append(fy)
        zs.append(fz)
    return RawStream(
        user=np.asarray(users, np.int32),
        activity=np.asarray(acts, np.int32),
        activity_names=tuple(names),
        timestamp=np.asarray(tss, np.int64),
        xyz=np.stack(
            [np.asarray(xs, np.float32), np.asarray(ys, np.float32),
             np.asarray(zs, np.float32)],
            axis=1,
        ) if users else np.empty((0, 3), np.float32),
        skipped=skipped,
    )


def load_raw_stream(path: str, engine: str = "auto") -> RawStream:
    if engine == "native":
        return read_raw_native(path)
    if engine == "python":
        return read_raw_python(path)
    if engine != "auto":
        raise ValueError(f"unknown engine {engine!r}")
    return read_raw_native(path) if native_available() else read_raw_python(path)


def stream_windows(
    stream: RawStream, window: int = 200, step: int | None = None
):
    """Segment the stream into per-bout fixed windows.

    A *bout* is a maximal run of consecutive samples sharing (user,
    activity); each bout is windowed independently so no window straddles
    a user or activity change (the WISDM transform's segmentation rule).
    Returns a :class:`har_tpu.data.raw_windows.WindowedDataset`.
    """
    from har_tpu.data.raw_windows import WindowedDataset

    step = step or window
    n = len(stream)
    if n == 0:
        return WindowedDataset(
            windows=np.empty((0, window, 3), np.float32),
            labels=np.empty((0,), np.int32),
            class_names=stream.activity_names,
        )
    key = stream.user.astype(np.int64) << 32 | stream.activity.astype(np.int64)
    boundaries = np.flatnonzero(np.diff(key)) + 1
    starts = np.concatenate([[0], boundaries])
    ends = np.concatenate([boundaries, [n]])
    wins, labels = [], []
    for s, e in zip(starts, ends):
        m = (e - s - window) // step + 1
        if m <= 0:
            continue
        idx = s + np.arange(m)[:, None] * step + np.arange(window)[None, :]
        wins.append(stream.xyz[idx])
        labels.append(np.full(m, stream.activity[s], np.int32))
    if not wins:
        return WindowedDataset(
            windows=np.empty((0, window, 3), np.float32),
            labels=np.empty((0,), np.int32),
            class_names=stream.activity_names,
        )
    return WindowedDataset(
        windows=np.concatenate(wins, axis=0),
        labels=np.concatenate(labels),
        class_names=stream.activity_names,
    )
