"""Row-exact replica of the reference's ``randomSplit`` on the WISDM table.

The reference splits the pipeline-transformed dataframe 70/30 with seed 2018
(reference Main/main.py:80) and lands on 3,793 train / 1,625 test rows
(result.txt:105-106).  Spark's ``Dataset.randomSplit`` first sorts every
partition by all orderable output columns to make sampling deterministic —
and in Spark 2.3/2.4 the assembled ``features`` VectorUDT *is* orderable,
comparing as its sqlType struct ``(type, size, indices[], values[])``.  The
effective sort is therefore::

    (label, sparse-vector indices lexicographic, values lexicographic,
     UID, XAVG..RESULTANT, XPEAK..ZPEAK, ACTIVITY)

after which one XORShiftRandom double per row buckets it (train iff
``x < 0.7``).  The captured run used a single partition.  All of this is
reproduced here and validated row-for-row against result.txt (the ten
shown sample UIDs and every prediction-sample UID land in the right
partition).

The split is a property of the *rows*, so every feature view (one-hot,
numeric, GBDT's binned view) shares the membership this module computes.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Sequence

import numpy as np

from har_tpu.data.spark_random import bernoulli_draws, scala_hashmap_key
from har_tpu.data.table import Table
from har_tpu.data.wisdm import (
    LABEL_COLUMN,
    WISDM_CATEGORICAL_COLUMNS,
    WISDM_NUMERIC_COLUMNS,
)


def mllib_vocab(values: Sequence[str]) -> dict[str, int]:
    """value -> StringIndexer index, bit-faithful to MLlib.

    MLlib sorts ``countByValue().toSeq`` stably by descending count; equal
    counts keep the scala ``immutable.HashMap`` trie iteration order, which
    :func:`scala_hashmap_key` reproduces from the Java string hash.
    """
    counts = Counter(values)
    keys = sorted(counts, key=scala_hashmap_key)
    keys.sort(key=lambda v: -counts[v])
    return {v: i for i, v in enumerate(keys)}


@dataclasses.dataclass(frozen=True)
class AssembledRows:
    """The pipeline-transformed frame exactly as MLlib sees it: per-row
    sparse (indices, values) in float64 (VectorAssembler drops explicit
    zeros, actives ascending), the indexed label, and UID — the inputs
    both the split replay and the bit-exact model replays consume."""

    sparse: list[tuple[tuple[int, ...], tuple[float, ...]]]
    label: np.ndarray  # (n,) float64, StringIndexer frequency-desc ids
    uid: np.ndarray  # (n,) int64
    num_features: int
    nums: list[tuple[float, ...]]  # raw numeric column values per row
    cats: list[tuple[str, ...]]  # raw categorical strings per row
    activity: list[str]


def assemble_rows(table: Table) -> AssembledRows:
    """Reproduce the MLlib pipeline output (Main/main.py:51-73) row by row."""
    cats = [
        [str(v) for v in table[c]] for c in WISDM_CATEGORICAL_COLUMNS
    ]
    vocabs = [mllib_vocab(col) for col in cats]
    # dropLast one-hot: a value at the last index encodes as all zeros
    widths = [len(v) - 1 for v in vocabs]
    offsets = np.concatenate(([0], np.cumsum(widths)))
    numeric = [table[c].astype(np.float64) for c in WISDM_NUMERIC_COLUMNS]
    label_vocab = mllib_vocab([str(v) for v in table[LABEL_COLUMN]])
    activity = [str(v) for v in table[LABEL_COLUMN]]
    uid = (
        np.asarray(table["UID"], dtype=np.int64)
        if "UID" in table.column_names
        else np.zeros(len(table), dtype=np.int64)
    )

    base = int(offsets[-1])
    num_features = base + len(numeric)
    sparse = []
    label = np.zeros(len(table), np.float64)
    nums_out: list[tuple[float, ...]] = []
    for j in range(len(table)):
        idx: list[int] = []
        val: list[float] = []
        for k in range(len(vocabs)):
            rank = vocabs[k][cats[k][j]]
            if rank < widths[k]:
                idx.append(int(offsets[k]) + rank)
                val.append(1.0)
        nums = tuple(float(col[j]) for col in numeric)
        for k, v in enumerate(nums):
            if v != 0.0:
                idx.append(base + k)
                val.append(v)
        sparse.append((tuple(idx), tuple(val)))
        label[j] = float(label_vocab[activity[j]])
        nums_out.append(nums)
    return AssembledRows(
        sparse=sparse,
        label=label,
        uid=uid,
        num_features=num_features,
        nums=nums_out,
        cats=[
            tuple(cats[k][j] for k in range(len(cats)))
            for j in range(len(table))
        ],
        activity=activity,
    )


def spark_sort_order(
    table: Table, rows: AssembledRows | None = None
) -> np.ndarray:
    """Original-row indices in the pre-sampling sorted-stream order.

    Pass a precomputed ``assemble_rows(table)`` to avoid re-running the
    pure-Python assembly when the caller already has one."""
    if rows is None:
        rows = assemble_rows(table)

    keys = []
    for j in range(len(rows.sparse)):
        idx, val = rows.sparse[j]
        keys.append(
            (
                rows.label[j],
                idx,
                val,
                rows.uid[j],
                *rows.nums[j],
                *rows.cats[j],
                rows.activity[j],
            )
        )
    return np.asarray(
        sorted(range(len(keys)), key=keys.__getitem__), dtype=np.int64
    )


def spark_split_indices(
    table: Table,
    fractions: Sequence[float],
    seed: int,
    rows: AssembledRows | None = None,
) -> list[np.ndarray]:
    """Split row indices exactly as the reference's randomSplit would.

    Returned index arrays are in sampled-stream (sorted) order, matching
    the row order Spark's train/test dataframes iterate in — so
    ``show(5)``-style report samples line up with result.txt too.
    """
    order = spark_sort_order(table, rows)
    draws = bernoulli_draws(len(order), seed)
    fracs = np.asarray(fractions, dtype=np.float64)
    if np.any(fracs < 0):
        raise ValueError("fractions must be non-negative")
    bounds = np.cumsum(fracs / fracs.sum())
    out = []
    lo = 0.0
    for hi in bounds:
        out.append(order[(draws >= lo) & (draws < hi)])
        lo = hi
    return out
