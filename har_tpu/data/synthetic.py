"""Synthetic WISDM-like data for tests and offline development.

Generates a table with the reference's post-drop column layout (UID, 10
numeric summary features, 3 string PEAK features with '?' sentinels, and a
6-class ACTIVITY label) plus, optionally, raw tri-axial windows for the
neural configs.  Class-conditional Gaussians keep the problem learnable so
accuracy-threshold tests are meaningful without shipping the dataset.
"""

from __future__ import annotations

import numpy as np

from har_tpu.data.schema import ColumnType, Schema
from har_tpu.data.table import Table
from har_tpu.data.wisdm import (
    ACTIVITIES,
    LABEL_COLUMN,
    WISDM_CATEGORICAL_COLUMNS,
    WISDM_NUMERIC_COLUMNS,
)


def synthetic_wisdm(
    n_rows: int = 2000,
    seed: int = 0,
    class_weights: tuple[float, ...] = (0.38, 0.30, 0.12, 0.10, 0.06, 0.04),
    peak_cardinality: int = 40,
    missing_peak_fraction: float = 0.02,
) -> Table:
    rng = np.random.default_rng((seed, 20829))
    n_classes = len(ACTIVITIES)
    labels = rng.choice(n_classes, size=n_rows, p=np.asarray(class_weights))

    # class-conditional means spread enough to be mostly separable
    means = rng.normal(0.0, 3.0, size=(n_classes, len(WISDM_NUMERIC_COLUMNS)))
    cols: dict[str, np.ndarray] = {
        "UID": np.arange(1, n_rows + 1, dtype=np.int64)
    }
    names: list[str] = ["UID"]
    types: list[ColumnType] = [ColumnType.INT]
    for j, name in enumerate(WISDM_NUMERIC_COLUMNS):
        vals = means[labels, j] + rng.normal(0.0, 1.0, size=n_rows)
        if name == "XAVG":  # all-zero int column, as in the shipped CSV
            cols[name] = np.zeros(n_rows, dtype=np.int64)
            types.append(ColumnType.INT)
        else:
            cols[name] = vals
            types.append(ColumnType.DOUBLE)
        names.append(name)
    for name in WISDM_CATEGORICAL_COLUMNS:
        # peaks correlate with the class; some rows carry the '?' sentinel
        base = rng.integers(0, peak_cardinality, size=n_rows)
        raw = (base + labels * peak_cardinality) * 25
        strs = raw.astype(str).astype(object)
        missing = rng.random(n_rows) < missing_peak_fraction
        strs[missing] = "?"
        cols[name] = strs
        names.append(name)
        types.append(ColumnType.STRING)
    cols[LABEL_COLUMN] = np.array(
        [ACTIVITIES[k] for k in labels], dtype=object
    )
    names.append(LABEL_COLUMN)
    types.append(ColumnType.STRING)
    return Table(cols, Schema(tuple(names), tuple(types)))


def synthetic_raw_windows(
    n_rows: int = 512,
    window: int = 200,
    seed: int = 0,
    n_classes: int = 6,
) -> tuple[np.ndarray, np.ndarray]:
    """Raw (n, window, 3) tri-axial windows with class-dependent frequency —
    the input shape for the 1D-CNN / BiLSTM configs (BASELINE.json)."""
    rng = np.random.default_rng((seed, 20829))
    labels = rng.integers(0, n_classes, size=n_rows)
    t = np.arange(window, dtype=np.float32) / 20.0  # 20 Hz
    freq = 0.5 + labels[:, None].astype(np.float32)  # class-coded frequency
    phase = rng.uniform(0, 2 * np.pi, size=(n_rows, 1)).astype(np.float32)
    base = np.sin(2 * np.pi * freq * t[None, :] + phase)
    x = np.stack(
        [
            base + 0.1 * rng.standard_normal((n_rows, window)),
            0.5 * base + 0.1 * rng.standard_normal((n_rows, window)),
            np.cos(2 * np.pi * freq * t[None, :] + phase)
            + 0.1 * rng.standard_normal((n_rows, window)),
        ],
        axis=-1,
    ).astype(np.float32)
    return x, labels.astype(np.int32)
