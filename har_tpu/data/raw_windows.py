"""Raw tri-axial accelerometer streams → fixed-length windows.

The reference consumes WISDM v1.1 *pre-transformed* windows (each row a
10 s @ 20 Hz window already reduced to 43 features — SURVEY §2 S); the raw
stream itself is not shipped.  The neural configs in BASELINE.json train
on raw windows, so this module provides:

  - :func:`make_windows` — sliding-window segmentation of an (n, 3)
    stream (the host-side analogue of WISDM's 10-s segmentation).
  - :func:`synthetic_raw_stream` — a class-conditional signal generator
    (distinct gait frequencies/amplitudes/orientations per activity) used
    for tests and offline development, mirroring the role of
    `har_tpu.data.synthetic` for the transformed table.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from har_tpu.data.wisdm import ACTIVITIES

SAMPLE_HZ = 20
WINDOW_STEPS = 200  # 10 s @ 20 Hz, the WISDM window


@dataclasses.dataclass(frozen=True)
class WindowedDataset:
    """(n, T, 3) float32 windows with integer labels.

    ``class_names[i]`` names label id i (None when the source carries no
    names — e.g. hand-built test fixtures)."""

    windows: np.ndarray
    labels: np.ndarray
    class_names: tuple[str, ...] | None = None

    def __len__(self) -> int:
        return len(self.windows)

    def split(self, fractions, seed: int):
        from har_tpu.data.split import split_indices

        return [
            WindowedDataset(
                self.windows[idx], self.labels[idx], self.class_names
            )
            for idx in split_indices(len(self), fractions, seed)
        ]


def make_windows(
    stream: np.ndarray,
    labels: np.ndarray,
    window: int = WINDOW_STEPS,
    step: int | None = None,
) -> WindowedDataset:
    """Segment an (n, 3) stream into (m, window, 3) windows.

    A window is kept only if every sample in it has the same label (the
    WISDM transform likewise segments within one activity bout).
    """
    step = step or window
    n = (len(stream) - window) // step + 1
    if n <= 0:
        raise ValueError("stream shorter than one window")
    idx = np.arange(window)[None, :] + step * np.arange(n)[:, None]
    wins = stream[idx]  # (n, window, 3)
    labs = labels[idx]
    pure = (labs == labs[:, :1]).all(axis=1)
    return WindowedDataset(
        windows=np.ascontiguousarray(wins[pure], np.float32),
        labels=labs[pure, 0].astype(np.int32),
    )


# (freq Hz, amplitude, gravity orientation xyz) per activity — crude but
# distinct dynamics so models have real signal to learn.
_CLASS_DYNAMICS = {
    "Walking": (2.0, 3.0, (0.0, 9.8, 0.0)),
    "Jogging": (2.8, 7.0, (0.0, 9.8, 0.0)),
    "Upstairs": (1.6, 3.5, (1.5, 9.3, 1.0)),
    "Downstairs": (1.8, 4.0, (-1.5, 9.3, -1.0)),
    "Sitting": (0.0, 0.2, (4.9, 4.9, 6.9)),
    "Standing": (0.0, 0.15, (0.0, 9.8, 0.5)),
}


def _class_axis_stats(table) -> dict[str, dict[str, tuple[float, ...]]]:
    """Per-activity (mean, std, peak-interval-ms) per axis from the
    transformed WISDM table.

    Pulls the reference's own summary columns ({X,Y,Z}AVG / {X,Y,Z}STDDEV /
    {X,Y,Z}PEAK, Main/main.py's feature space): medians per class, ignoring
    the '?' sentinels the shipped CSV uses in the PEAK columns (XAVG is
    all-zero there — that IS the statistic, so x oscillates around 0).
    """
    import numpy as np  # noqa: F811  (self-contained for clarity)

    activity = np.asarray(table["ACTIVITY"], object)
    out: dict[str, dict[str, tuple[float, ...]]] = {}

    def med(col: str, mask) -> float | None:
        try:
            raw = np.asarray(table[col], object)[mask]
        except KeyError:
            return None
        vals = []
        for v in raw:
            try:
                f = float(v)
            except (TypeError, ValueError):
                continue
            if np.isfinite(f):
                vals.append(f)
        return float(np.median(vals)) if vals else None

    for name in np.unique(activity):
        mask = activity == name
        stats = {}
        for key, suffix, default in (
            ("mean", "AVG", 0.0),
            ("std", "STDDEV", 1.0),
            ("peak_ms", "PEAK", 0.0),
        ):
            vals = tuple(
                m if (m := med(f"{axis}{suffix}", mask)) is not None
                else default
                for axis in "XYZ"
            )
            stats[key] = vals
        out[str(name)] = stats
    return out


def calibrated_raw_stream(
    table,
    n_windows: int = 8192,
    seed: int = 0,
    window: int = WINDOW_STEPS,
) -> WindowedDataset:
    """Raw windows whose per-class statistics replay the WISDM table's.

    The reference drops the raw 20 Hz stream (Main/main.py:22-26 keeps
    only the 43 summary features), so the accuracy a raw-window model can
    reach is unobservable on shipped data.  This generator closes the
    loop (VERDICT r3 item 4): each class's windows are synthesized so
    their per-axis mean, standard deviation and dominant peak interval
    match the medians the reference's own transform measured on that
    class — gravity components from {X,Y,Z}AVG, oscillation frequency
    from {X,Y,Z}PEAK (ms between peaks), and amplitude/noise split so the
    per-axis std equals {X,Y,Z}STDDEV (noise takes 35% of the variance).
    Class priors are the table's empirical activity distribution.
    """
    import numpy as np  # noqa: F811

    stats = _class_axis_stats(table)
    activity = np.asarray(table["ACTIVITY"], object)
    names, counts = np.unique(activity, return_counts=True)
    names = [str(n) for n in names]
    priors = counts / counts.sum()

    rng = np.random.default_rng((seed, 20824))
    labels = rng.choice(len(names), size=n_windows, p=priors).astype(np.int32)
    t = np.arange(window, dtype=np.float32) / SAMPLE_HZ
    windows = np.empty((n_windows, window, 3), np.float32)
    for i, lab in enumerate(labels):
        s = stats[names[lab]]
        phase = rng.uniform(0, 2 * np.pi, size=3)
        for axis in range(3):
            mean = s["mean"][axis]
            std = max(s["std"][axis], 1e-3) * rng.uniform(0.9, 1.1)
            peak_ms = s["peak_ms"][axis]
            sigma = np.sqrt(0.35) * std
            amp = np.sqrt(2.0 * (std * std - sigma * sigma))
            if peak_ms and peak_ms > 0:
                freq = 1000.0 / peak_ms * rng.uniform(0.95, 1.05)
                osc = amp * np.sin(2 * np.pi * freq * t + phase[axis])
            else:  # static activity: all variance is noise
                sigma, osc = std, 0.0
            windows[i, :, axis] = (
                mean + osc + rng.normal(0, sigma, size=window)
            )
    return WindowedDataset(
        windows=windows, labels=labels, class_names=tuple(names)
    )


def synthetic_raw_stream(
    n_windows: int = 1000,
    seed: int = 0,
    window: int = WINDOW_STEPS,
    class_weights: tuple[float, ...] = (0.38, 0.30, 0.12, 0.10, 0.06, 0.04),
) -> WindowedDataset:
    """Directly generate labeled windows of synthetic accelerometer data."""
    rng = np.random.default_rng((seed, 20823))
    labels = rng.choice(
        len(ACTIVITIES), size=n_windows, p=np.asarray(class_weights)
    ).astype(np.int32)
    t = np.arange(window, dtype=np.float32) / SAMPLE_HZ
    windows = np.empty((n_windows, window, 3), np.float32)
    for i, lab in enumerate(labels):
        freq, amp, gravity = _CLASS_DYNAMICS[ACTIVITIES[lab]]
        phase = rng.uniform(0, 2 * np.pi, size=3)
        f = freq * rng.uniform(0.9, 1.1)
        a = amp * rng.uniform(0.8, 1.2)
        for axis in range(3):
            osc = a * np.sin(2 * np.pi * f * t + phase[axis]) if f > 0 else 0.0
            # axis-dependent harmonic gives stairs asymmetry
            if f > 0 and axis == 2:
                osc = osc + 0.4 * a * np.sin(2 * np.pi * 2 * f * t)
            windows[i, :, axis] = (
                gravity[axis] + osc + rng.normal(0, 0.4, size=window)
            )
    return WindowedDataset(
        windows=windows, labels=labels, class_names=ACTIVITIES
    )
