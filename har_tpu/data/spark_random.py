"""Bit-faithful ports of the JVM randomness Spark's ``randomSplit`` uses.

The reference splits with ``df.randomSplit([0.7, 0.3], seed=2018)``
(reference Main/main.py:80).  Under the hood (Spark 2.3/2.4) that is:

1. a per-partition ascending sort over every *orderable* output column —
   including the assembled ``features`` vector, whose ``VectorUDT`` sorts as
   its sqlType struct ``(type, size, indices[], values[])``;
2. one ``BernoulliCellSampler`` pass per output split, each re-seeded with
   ``seed + partitionIndex`` and drawing one double per row: a row lands in
   the split whose ``[lo, hi)`` cell contains its draw;
3. the sampler RNG is ``XORShiftRandom``, whose seed is MurmurHash3-mixed —
   over a **64-byte** buffer, because upstream allocates
   ``java.lang.Long.SIZE`` (a bit count) bytes.

This module reproduces 1-3 exactly; :mod:`har_tpu.data.spark_split` builds
the sort keys.  Validated row-for-row against the captured reference run
(result.txt:105-131: counts 3,793/1,625 and all ten shown sample UIDs).

Also here: the Scala ``immutable.HashMap`` iteration-order key.  MLlib's
``StringIndexer`` breaks frequency ties in whatever order
``countByValue().toSeq`` yields — the hash-trie's LSB-first 5-bit-chunk
walk of the improved Java string hash.  ``scala_hashmap_key`` reproduces
it so one-hot indices match MLlib's bit-for-bit.
"""

from __future__ import annotations

import numpy as np

_M64 = (1 << 64) - 1
_M32 = 0xFFFFFFFF

#: MurmurHash3 seed scala.util.hashing uses for byte arrays.
_ARRAY_SEED = 0x3C074A61


def murmur3_bytes(data: bytes, seed: int) -> int:
    """scala.util.hashing.MurmurHash3.bytesHash (x86 32-bit variant)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & _M32

    def rotl(x: int, r: int) -> int:
        return ((x << r) | (x >> (32 - r))) & _M32

    i = 0
    while len(data) - i >= 4:
        k = data[i] | data[i + 1] << 8 | data[i + 2] << 16 | data[i + 3] << 24
        k = (k * c1) & _M32
        k = rotl(k, 15)
        k = (k * c2) & _M32
        h ^= k
        h = rotl(h, 13)
        h = (h * 5 + 0xE6546B64) & _M32
        i += 4
    k = 0
    rem = len(data) - i
    if rem == 3:
        k ^= data[i + 2] << 16
    if rem >= 2:
        k ^= data[i + 1] << 8
    if rem >= 1:
        k ^= data[i]
        k = (k * c1) & _M32
        k = rotl(k, 15)
        k = (k * c2) & _M32
        h ^= k
    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h


def xorshift_hash_seed(seed: int) -> int:
    """Spark XORShiftRandom.hashSeed.

    Upstream allocates ``ByteBuffer.allocate(java.lang.Long.SIZE)`` — 64
    *bytes* (SIZE is in bits) — so the hash runs over the 8 big-endian seed
    bytes followed by 56 zeros.  Reproducing the quirk is load-bearing.
    """
    buf = (seed & _M64).to_bytes(8, "big") + b"\x00" * 56
    low = murmur3_bytes(buf, _ARRAY_SEED)
    high = murmur3_bytes(buf, low)
    return ((high << 32) | low) & _M64


class XORShiftRandom:
    """Spark's org.apache.spark.util.random.XORShiftRandom.

    Subclasses java.util.Random but replaces ``next(bits)`` with a 64-bit
    xorshift; ``nextDouble`` keeps Java's 53-bit construction.
    """

    def __init__(self, seed: int):
        self._state = xorshift_hash_seed(seed)

    def next(self, bits: int) -> int:
        s = self._state
        s ^= (s << 21) & _M64
        s ^= s >> 35
        s ^= (s << 4) & _M64
        self._state = s
        return s & ((1 << bits) - 1)

    def next_double(self) -> float:
        return ((self.next(26) << 27) + self.next(27)) * (2.0 ** -53)


def bernoulli_draws(n: int, seed: int, partition_index: int = 0) -> np.ndarray:
    """The n doubles BernoulliCellSampler draws for one partition.

    Every output split re-runs the same seeded sequence over the partition,
    so one draw per row decides all splits at once (``lo <= x < hi``).
    """
    rng = XORShiftRandom(seed + partition_index)
    return np.fromiter(
        (rng.next_double() for _ in range(n)), dtype=np.float64, count=n
    )


def py2_string_hash(s: str) -> int:
    """CPython 2's 64-bit str hash (signed).

    PySpark params default their ``seed`` to ``hash(type(self).__name__)``
    — e.g. pyspark.ml.tuning.CrossValidator's fold assignment runs SQL
    ``rand(hash('CrossValidator'))``.  Python 2 (the reference's 2019-era
    driver) hashes strings with this deterministic algorithm; Python 3
    randomizes, so replaying the committed run means replaying py2's.
    """
    if not s:
        return 0
    x = (ord(s[0]) << 7) & _M64
    for ch in s:
        x = ((1000003 * x) ^ ord(ch)) & _M64
    x ^= len(s)
    if x == _M64:  # CPython maps -1 to -2
        x = _M64 - 1
    return x - (1 << 64) if x >= (1 << 63) else x


def java_string_hash(s: str) -> int:
    """java.lang.String.hashCode (signed 32-bit)."""
    h = 0
    for ch in s:
        h = (31 * h + ord(ch)) & _M32
    return h - (1 << 32) if h >= (1 << 31) else h


def scala_hash_improve(hcode: int) -> int:
    """scala.collection.immutable.HashMap's hash improver."""
    h = hcode & _M32
    h = (h + (~((h << 9) & _M32) & _M32)) & _M32
    h ^= h >> 14
    h = (h + ((h << 4) & _M32)) & _M32
    return h ^ (h >> 10)


def scala_int_trie_order(keys) -> list[int]:
    """scala immutable.HashMap[Int-hashed key] iteration order.

    The hash trie walks 5-bit chunks of improve(key.##) LSB-first; whole
    doubles 0.0..5.0 hash like their int values (scala unified hashing),
    so MulticlassMetrics' ``labelCountByClass`` map iterates class ids in
    this order — the order its weighted metrics accumulate in.
    """

    def chunk_key(k: int) -> tuple[int, ...]:
        h = scala_hash_improve(k & _M32)
        return tuple((h >> (5 * level)) & 31 for level in range(7))

    return sorted(keys, key=chunk_key)


def scala_hashmap_key(s: str) -> tuple[int, ...]:
    """Sort key reproducing scala immutable.HashMap iteration order.

    The hash trie consumes the improved hash five bits at a time from the
    least-significant end; iteration walks bitmap slots in increasing
    order at each level, i.e. lexicographically over the chunk sequence.
    """
    h = scala_hash_improve(java_string_hash(s))
    return tuple((h >> (5 * level)) & 31 for level in range(7))
