"""HL005 — durability: the registry and the journal must not bypass
the shared fsync discipline (``har_tpu/utils/durable.py``).

The PR-4 registry fix is the ancestor of this rule: ``CURRENT`` and
``NEXT_ID`` were once written with a bare ``os.replace``, which orders
the rename against the file's own data but NOT against the parent
directory — after power loss the directory could resurface the old
pointer (or none).  ``utils/durable.py`` now holds the one correct
sequence (tmp → fsync data → rename → fsync dir); this rule keeps
every durable write in the registry/journal modules on it.

Flagged, inside the durability-critical modules only
(``adapt/registry.py``, ``serve/journal.py``, ``utils/durable.py``):

  - an ``open(..., "w"/"a"/"wb"/"ab")`` whose enclosing function
    WRITES through the handle (``.write`` / ``json.dump`` /
    ``np.savez``) but never calls ``os.fsync`` — buffered bytes the
    page cache may still own at the kill instant.  Opens that only
    stash the handle for a later fsynced flush (the journal's segment
    handle) are not flagged;
  - an ``os.replace(...)`` in a function that syncs neither the parent
    directory (``fsync_dir``/``_fsync_dir``) nor routes through the
    durable helpers (``atomic_write``/``durable_append``) — the
    half-atomic rename the module docstring warns about.
"""

from __future__ import annotations

import ast

from har_tpu.analyze.core import FileContext, Finding, Rule, call_name, walk_functions

_MODULES = (
    "har_tpu/adapt/registry.py",
    "har_tpu/serve/journal.py",
    "har_tpu/utils/durable.py",
)
_WRITE_MODES = ("w", "a", "wb", "ab", "w+", "a+", "xb", "x")
_WRITE_CALLS = {"write", "dump", "savez", "savez_compressed", "writelines"}
_DIR_SYNC_CALLS = {
    "fsync_dir", "_fsync_dir", "atomic_write", "_atomic_write",
    "durable_append", "_durable_append",
}


def _open_mode(node: ast.Call) -> str | None:
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
        return str(node.args[1].value)
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            return str(kw.value.value)
    return None


class DurabilityRule(Rule):
    rule_id = "HL005"
    title = "durability"

    def applies(self, rel: str) -> bool:
        return rel in _MODULES

    def check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        for qual, _cls, fn in walk_functions(ctx.tree):
            calls = [n for n in ast.walk(fn) if isinstance(n, ast.Call)]
            names = {call_name(n) for n in calls}
            has_fsync = any(
                call_name(n) == "fsync"
                for n in calls
                if isinstance(n.func, ast.Attribute)
            )
            writes = bool(names & _WRITE_CALLS)
            dir_synced = bool(names & _DIR_SYNC_CALLS)
            for n in calls:
                if (
                    isinstance(n.func, ast.Name)
                    and n.func.id == "open"
                    and (_open_mode(n) or "r") in _WRITE_MODES
                    and writes
                    and not has_fsync
                ):
                    findings.append(
                        ctx.finding(
                            self.rule_id,
                            n,
                            f"`open(..., {_open_mode(n)!r})` written "
                            "without an fsync in this function — the "
                            "page cache may still own these bytes at "
                            "the kill instant; route the write through "
                            "har_tpu.utils.durable (atomic_write / "
                            "durable_append)",
                            qual,
                        )
                    )
                elif (
                    isinstance(n.func, ast.Attribute)
                    and n.func.attr == "replace"
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "os"
                    and not dir_synced
                ):
                    findings.append(
                        ctx.finding(
                            self.rule_id,
                            n,
                            "`os.replace(...)` without a parent-"
                            "directory fsync — after power loss the "
                            "directory can resurface the old entry; "
                            "use utils.durable.atomic_write or follow "
                            "with fsync_dir",
                            qual,
                        )
                    )
        return findings
