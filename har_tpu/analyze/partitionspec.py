"""HL007 — partition-spec coverage: every sharded program in the
parallel package declares where its arguments live, every axis name a
``PartitionSpec`` mentions is a declared mesh axis, and spec builders
actually shard the >1-D kernels they exist to shard.

This is the static half of the ``match_partition_rules`` sharding
layer (the shared train/serve rule tables in ``parallel/rules.py``):
the invariants the tables encode are checked without importing jax —

  1. **specs for all args.**  A ``shard_map(...)`` must declare BOTH
     ``in_specs`` and ``out_specs``; when ``in_specs`` is a literal
     tuple and the wrapped callable resolves in the call graph to a
     fixed-arity function, the tuple length must match its positional
     parameter count (a silently-recycled spec after an added argument
     is exactly the drift this catches).  A bare ``jax.jit(...)`` in
     ``har_tpu/parallel/*.py`` with NO shardings is a finding unless
     (a) it wraps a ``shard_map`` product (the specs live inside), or
     (b) it carries the reviewed ``# harlint: spec-ok`` annotation —
     the placement-driven-GSPMD pattern (inputs arrive sharded and XLA
     propagates), which is correct but must be a visible, reviewed
     contract, not a default.  Declaring only one of ``in_shardings``/
     ``out_shardings`` is flagged the same way.

  2. **axis names exist.**  Every axis a ``P(...)``/``PartitionSpec``
     names — as a string literal, a ``*_AXIS`` constant (resolved
     through the import map), or a parameter default — must be one of
     the axes the parallel package declares (``mesh.py``'s
     ``DP/TP/DP_DCN`` plus the ``EP``/``PP`` linear-mesh axes).  An
     axis typo does not error at spec-construction time; it surfaces
     later as a mesh-resolution failure or, worse, silent replication.

  3. **no implicit full replication of a >1-D kernel.**  A spec
     builder (a function named ``*specs*``, e.g.
     ``dense_alternating_specs``) whose assigned/returned specs never
     include a ≥2-dim ``P`` carrying a real axis has lost its kernel
     branch — every 2-D kernel falls through to ``P()`` and the model
     silently serves fully replicated.  Likewise a ``shard_map`` whose
     literal ``in_specs`` are ALL empty ``P()`` maps nothing.

  4. **rule tables audit against their reference trees.**  The
     ``match_partition_rules`` layer (``parallel/rules.py``) declares
     literal rule TABLES (``RULE_TABLES``) and a canonical reference
     param tree per family (``REFERENCE_TREES``: ``(path, ndim,
     "shard"|"rep")`` rows).  The audit resolves every reference leaf
     through the table first-match-wins, exactly like the runtime
     matcher, and demands: the table ends in a replicating ``(r".*",
     P())`` catch-all (so an unmatched leaf replicates by policy
     instead of raising in production); every reference leaf is
     claimed by some rule; a "shard" leaf's claiming rule carries a
     declared axis (a DELETED kernel rule drops the leaf to the
     catch-all — the silent-full-replication regression); a "rep"
     leaf's claiming rule is axis-free; and every non-catch-all rule
     is the first-match winner of at least one reference leaf (a
     catch-all hoisted to the front starves every later rule — all
     dead, one finding each).

Scope: ``har_tpu/parallel/*.py`` + ``har_tpu/serve/dispatch.py`` (the
serving-side placement).  Pure stdlib, like every harlint rule.
"""

from __future__ import annotations

import ast

from har_tpu.analyze.core import (
    FileContext,
    Finding,
    Rule,
    call_name,
    walk_scopes,
)

_SCOPE_PREFIX = "har_tpu/parallel/"
_SCOPE_FILES = {"har_tpu/serve/dispatch.py"}
_SPEC_NAMES = {"P", "PartitionSpec"}

# The files whose module-level ``*_AXIS`` constants define the declared
# mesh axes this rule validates against.  Path-subset runs (``har lint
# --changed``) load these as support contexts so an edited parallel
# module is judged against the real axis table instead of an empty one
# (see ``run_harlint``).
AXIS_DECLARERS = (
    "har_tpu/parallel/mesh.py",
    "har_tpu/parallel/expert_parallel.py",
    "har_tpu/parallel/pipeline_parallel.py",
)


def _is_jit_ref(expr: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` referenced (not called) — the decorator
    and ``partial(jax.jit, ...)`` spellings."""
    return (isinstance(expr, ast.Attribute) and expr.attr == "jit") or (
        isinstance(expr, ast.Name) and expr.id == "jit"
    )


class PartitionSpecRule(Rule):
    rule_id = "HL007"
    title = "partition-spec coverage"

    def applies(self, rel: str) -> bool:
        return rel.startswith(_SCOPE_PREFIX) or rel in _SCOPE_FILES

    def finalize(self, ctxs: list[FileContext]) -> list[Finding]:
        from har_tpu.analyze.core import Project

        project = self.project or Project(ctxs)
        graph = project.callgraph

        # declared axes: module-level `*_AXIS = "name"` constants across
        # the scope (mesh.py's dp/tp/dp_dcn + expert/pipeline ep/pp)
        declared: dict[str, str] = {}
        for ctx in ctxs:
            for node in ctx.tree.body:
                if (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id.endswith("_AXIS"):
                            declared[node.value.value] = t.id
        axis_list = ", ".join(sorted(declared)) or "<none declared>"

        findings: list[Finding] = []
        for ctx in ctxs:
            # support ctxs (subset runs) contribute their axis table
            # above but are not themselves examined
            if not ctx.support:
                findings.extend(
                    self._check_file(ctx, graph, declared, axis_list)
                )
        return findings

    # ------------------------------------------------------------- file

    def _check_file(self, ctx, graph, declared, axis_list):
        findings: list[Finding] = []
        functions = walk_scopes(ctx.tree)

        def symbol_at(line: int) -> str:
            best = ""
            for qual, node in functions:
                if node.lineno <= line <= (node.end_lineno or node.lineno):
                    best = qual  # innermost wins: keep overwriting
            return best

        def flag(node, msg, symbol=None):
            if ctx.suppressed(node, "spec-ok"):
                ctx.suppression_hits += 1
                return
            findings.append(
                ctx.finding(
                    self.rule_id, node, msg,
                    symbol if symbol is not None
                    else symbol_at(getattr(node, "lineno", 1)),
                )
            )

        def resolve_axis(expr, line) -> list[str]:
            """Axis strings an expression can name; [] when opaque."""
            if expr is None or (
                isinstance(expr, ast.Constant) and expr.value is None
            ):
                return []
            if isinstance(expr, ast.Constant) and isinstance(
                expr.value, str
            ):
                return [expr.value]
            if isinstance(expr, (ast.Tuple, ast.List)):
                out = []
                for e in expr.elts:
                    out.extend(resolve_axis(e, line))
                return out
            if isinstance(expr, ast.BoolOp):
                out = []
                for e in expr.values:
                    out.extend(resolve_axis(e, line))
                return out
            if isinstance(expr, ast.Name):
                got = graph.resolve_const(ctx.rel, expr.id)
                if got is not None:
                    return [got]
                # parameter default: `def f(..., tp_axis=TP_AXIS)`
                for qual, node in functions:
                    if not isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        continue
                    if not (
                        node.lineno <= line
                        <= (node.end_lineno or node.lineno)
                    ):
                        continue
                    a = node.args
                    pos = a.posonlyargs + a.args
                    for p, d in zip(pos[len(pos) - len(a.defaults):],
                                    a.defaults):
                        if p.arg == expr.id:
                            return resolve_axis(d, node.lineno)
                    for p, d in zip(a.kwonlyargs, a.kw_defaults):
                        if d is not None and p.arg == expr.id:
                            return resolve_axis(d, node.lineno)
                return []
            return []

        # ---- P(...) axis-name validation
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in _SPEC_NAMES
            ):
                continue
            axes = []
            for arg in node.args:
                if isinstance(arg, ast.Starred):
                    continue
                for ax in resolve_axis(arg, node.lineno):
                    axes.append((ax, arg))
            for ax, arg in axes:
                if ax not in declared:
                    flag(
                        arg if hasattr(arg, "lineno") else node,
                        f"PartitionSpec axis `{ax}` is not a declared "
                        f"mesh axis (declared: {axis_list}) — a typo "
                        "here surfaces later as a mesh-resolution "
                        "failure or silent replication",
                    )

        def jit_contract(node, kw, spelling, symbol=None):
            """The one reviewed-placement contract, whatever the jit
            spelling (call, decorator, partial): both shardings, or a
            `# harlint: spec-ok` annotation."""
            has_in = "in_shardings" in kw
            has_out = "out_shardings" in kw
            if has_in and has_out:
                return
            if has_in != has_out:
                which = "in_shardings" if has_in else "out_shardings"
                other = "out_shardings" if has_in else "in_shardings"
                flag(
                    node,
                    f"`{spelling}` declares {which} but not {other} — "
                    "half-declared placement leaves the other side "
                    "to silent GSPMD inference; declare both",
                    symbol=symbol,
                )
                return
            flag(
                node,
                f"`{spelling}` in the parallel package with no "
                "in_shardings/out_shardings — placement-driven GSPMD "
                "(inputs arrive sharded, XLA propagates) is a "
                "reviewed pattern: annotate `# harlint: spec-ok` or "
                "declare the shardings",
                symbol=symbol,
            )

        # ---- shard_map / jit call-site checks
        shard_map_products: set[str] = set()
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and call_name(node.value) == "shard_map"
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        shard_map_products.add(t.id)

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            kw = {k.arg: k.value for k in node.keywords if k.arg}
            if name == "shard_map":
                missing = [
                    k for k in ("in_specs", "out_specs") if k not in kw
                ]
                if missing:
                    flag(
                        node,
                        f"`shard_map(...)` without {' / '.join(missing)} "
                        "— every argument and result of a sharded "
                        "program must declare its placement",
                    )
                in_specs = kw.get("in_specs")
                if isinstance(in_specs, ast.Tuple) and in_specs.elts:
                    self._arity_check(
                        ctx, graph, node, in_specs, flag, functions
                    )
                    if all(
                        isinstance(e, ast.Call)
                        and call_name(e) in _SPEC_NAMES
                        and not e.args
                        for e in in_specs.elts
                    ):
                        flag(
                            node,
                            "every `in_specs` entry of this shard_map "
                            "is a fully-replicated `P()` — the map "
                            "shards nothing; at least the batch (or "
                            "parameter) axis must be partitioned",
                        )
            elif (
                name == "partial"
                and ctx.rel.startswith(_SCOPE_PREFIX)
                and any(_is_jit_ref(a) for a in node.args)
            ):
                # `partial(jax.jit, ...)` (usually as a decorator): the
                # wrap is deferred but the shardings live in THESE
                # kwargs — same contract as the direct call form
                jit_contract(node, kw, "partial(jit, ...)")
            elif name == "jit" and ctx.rel.startswith(_SCOPE_PREFIX):
                wrapped = node.args[0] if node.args else None
                if "in_shardings" not in kw and "out_shardings" not in kw and (
                    (
                        isinstance(wrapped, ast.Name)
                        and wrapped.id in shard_map_products
                    )
                    or (
                        isinstance(wrapped, ast.Call)
                        and call_name(wrapped) == "shard_map"
                    )
                ):
                    pass  # jit of a shard_map product (assigned name
                    #       or inline call): the specs live inside
                else:
                    jit_contract(node, kw, "jit(...)")

        # ---- decorator-form bare jit (`@jax.jit` / `@jit`): the same
        # reviewed-placement contract as the call form — HL001/HL006's
        # is_jit_marked already treats these as jit roots, so without
        # this check the decorator spelling is an unreviewed bypass.
        # Call-form decorators (`@jax.jit(...)`, `@partial(jax.jit,
        # ...)`) are ast.Call nodes the walk above already judged.
        if ctx.rel.startswith(_SCOPE_PREFIX):
            for qual, fnode in functions:
                if not isinstance(fnode, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                    continue
                for dec in fnode.decorator_list:
                    if not isinstance(dec, ast.Call) and _is_jit_ref(dec):
                        jit_contract(dec, {}, "@jit", symbol=qual)

        # ---- rule-table audit (the match_partition_rules layer)
        self._table_audit(ctx, declared, flag, resolve_axis)

        # ---- spec-builder replication check (`*specs*` functions)
        for qual, fnode in functions:
            if not isinstance(fnode, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            if "specs" not in fnode.name:
                continue
            produced = []  # P calls in assignment/return value position
            for sub in ast.walk(fnode):
                vals = []
                if isinstance(sub, (ast.Assign, ast.Return)):
                    vals = [sub.value] if sub.value is not None else []
                elif isinstance(sub, ast.AnnAssign) and sub.value:
                    vals = [sub.value]
                for v in vals:
                    for c in ast.walk(v):
                        if (
                            isinstance(c, ast.Call)
                            and isinstance(c.func, ast.Name)
                            and c.func.id in _SPEC_NAMES
                        ):
                            produced.append(c)
            if not produced:
                continue
            def _sharded_multidim(c):
                # a ≥2-dim spec (two positional entries) naming at
                # least one real axis — the kernel-spec shape
                if len(c.args) < 2:
                    return False
                axes = []
                for arg in c.args:
                    axes.extend(resolve_axis(arg, c.lineno))
                return any(ax in declared for ax in axes)
            if not any(_sharded_multidim(c) for c in produced):
                flag(
                    fnode,
                    f"spec builder `{fnode.name}` produces no ≥2-dim "
                    "PartitionSpec carrying a declared axis — every "
                    ">1-D kernel it covers is implicitly FULLY "
                    "REPLICATED (the lost-kernel-branch failure mode); "
                    "restore the sharded kernel spec",
                    symbol=qual,
                )
        return findings

    # ------------------------------------------------------------ tables

    def _table_audit(self, ctx, declared, flag, resolve_axis):
        """Check 4: resolve every REFERENCE_TREES leaf through its
        RULE_TABLES table first-match-wins (mirroring the runtime
        matcher regex-for-regex) and flag unmatched leaves, mis-placed
        claims, dead rules, and a missing/misplaced catch-all."""
        import re

        lits: dict[str, ast.AST] = {}
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        lits[t.id] = node.value
        tables = lits.get("RULE_TABLES")
        refs = lits.get("REFERENCE_TREES")
        if not (isinstance(tables, ast.Dict) and isinstance(refs, ast.Dict)):
            return

        def seq(val):
            """A (possibly Name-indirected) literal tuple/list node."""
            if isinstance(val, ast.Name):
                val = lits.get(val.id)
            return val if isinstance(val, (ast.Tuple, ast.List)) else None

        ref_map = {}
        for k, v in zip(refs.keys, refs.values):
            if isinstance(k, ast.Constant):
                ref_map[k.value] = seq(v)

        for k, v in zip(tables.keys, tables.values):
            family = k.value if isinstance(k, ast.Constant) else None
            table = seq(v)
            if family is None or table is None:
                continue
            ref = ref_map.get(family)
            if ref is None:
                flag(
                    k,
                    f"rule table `{family}` has no REFERENCE_TREES "
                    "entry — the table audit cannot resolve its "
                    "coverage; add the family's canonical param tree",
                )
                continue

            # parse (pattern, P(...)) rows; opaque rows are skipped
            # (generated tables are exercised at runtime, not here)
            rules = []
            for entry in table.elts:
                if not (
                    isinstance(entry, ast.Tuple) and len(entry.elts) == 2
                ):
                    continue
                pat_node, spec_node = entry.elts
                if not (
                    isinstance(pat_node, ast.Constant)
                    and isinstance(pat_node.value, str)
                ):
                    continue
                axes: list[str] = []
                n_entries = 0
                if (
                    isinstance(spec_node, ast.Call)
                    and isinstance(spec_node.func, ast.Name)
                    and spec_node.func.id in _SPEC_NAMES
                ):
                    n_entries = len(spec_node.args)
                    for a in spec_node.args:
                        if isinstance(a, ast.Starred):
                            continue
                        axes.extend(
                            ax
                            for ax in resolve_axis(a, spec_node.lineno)
                            if ax in declared
                        )
                try:
                    compiled = re.compile(pat_node.value)
                except re.error as exc:
                    flag(
                        pat_node,
                        f"rule pattern {pat_node.value!r} in `{family}` "
                        f"does not compile: {exc}",
                    )
                    continue
                rules.append(
                    (pat_node.value, compiled, axes, n_entries, entry)
                )
            if not rules:
                continue

            last_pat, _, last_axes, _, last_node = rules[-1]
            if last_pat != r".*" or last_axes:
                flag(
                    last_node,
                    f"rule table `{family}` does not end in the "
                    'replicating `(r".*", P())` catch-all — an '
                    "unmatched leaf raises at placement time instead "
                    "of replicating by policy",
                )

            winners: set[int] = set()
            for leaf in ref.elts:
                if not (
                    isinstance(leaf, ast.Tuple) and len(leaf.elts) == 3
                ):
                    continue
                path_n, ndim_n, kind_n = leaf.elts
                if not all(
                    isinstance(n, ast.Constant)
                    for n in (path_n, ndim_n, kind_n)
                ):
                    continue
                path, ndim, kind = (
                    path_n.value, ndim_n.value, kind_n.value
                )
                idx = next(
                    (
                        i
                        for i, r in enumerate(rules)
                        if r[1].search(path)
                    ),
                    None,
                )
                if idx is None:
                    flag(
                        leaf,
                        f"reference leaf `{path}` matches no rule in "
                        f"`{family}` — match_partition_rules would "
                        "raise on this family's own canonical tree",
                    )
                    continue
                winners.add(idx)
                pat, _, axes, n_entries, entry = rules[idx]
                if kind == "shard" and not axes:
                    flag(
                        entry,
                        f"sharded reference leaf `{path}` of `{family}` "
                        f"is claimed by replicating rule `{pat}` — the "
                        "kernel it stands for serves FULLY REPLICATED "
                        "(a deleted or shadowed sharding rule)",
                    )
                elif kind == "rep" and axes:
                    flag(
                        entry,
                        f"replicated reference leaf `{path}` of "
                        f"`{family}` is claimed by sharding rule "
                        f"`{pat}` — a leaf meant to replicate would "
                        "be partitioned",
                    )
                if axes and n_entries > ndim:
                    flag(
                        entry,
                        f"rule `{pat}` of `{family}` declares "
                        f"{n_entries} spec entries but claims "
                        f"{ndim}-dim leaf `{path}` — the spec is "
                        "longer than the array rank",
                    )
            for i, (pat, _, axes, _, entry) in enumerate(rules):
                if pat == r".*" or i in winners:
                    continue
                flag(
                    entry,
                    f"rule `{pat}` in `{family}` is the first-match "
                    "winner of no reference-tree leaf — a dead rule "
                    "(shadowed by an earlier pattern, or a stale "
                    "path); every live rule must claim at least one "
                    "canonical leaf",
                )

    # ------------------------------------------------------------ arity

    def _arity_check(self, ctx, graph, call, in_specs, flag, functions):
        wrapped = call.args[0] if call.args else None
        if not isinstance(wrapped, ast.Name):
            return
        fi = None
        # nested def resolved LEXICALLY: the innermost def/class scope
        # enclosing the call, walked outward — never a same-named def
        # from an unrelated function (wrong arity both ways: spurious
        # findings AND masked genuine drift)
        enclosing = ""
        for qual, node in functions:
            if node.lineno <= call.lineno <= (node.end_lineno
                                              or node.lineno):
                enclosing = qual  # innermost wins: keep overwriting
        while enclosing:
            fi = graph.functions.get(
                (ctx.rel, f"{enclosing}.{wrapped.id}")
            )
            if fi is not None:
                break
            enclosing = (
                enclosing.rsplit(".", 1)[0] if "." in enclosing else ""
            )
        if fi is None:
            got = graph.resolve_symbol(ctx.rel, wrapped.id)
            from har_tpu.analyze.callgraph import FuncInfo

            if isinstance(got, FuncInfo):
                fi = got
        if fi is None:
            return
        a = fi.node.args
        if a.vararg is not None:
            return  # *args: arity is dynamic, nothing to pin
        n_pos = len(a.posonlyargs) + len(a.args)
        if len(in_specs.elts) != n_pos:
            flag(
                call,
                f"shard_map in_specs declares {len(in_specs.elts)} "
                f"placements but `{fi.name}` takes {n_pos} positional "
                "arguments — an added argument is silently riding a "
                "recycled spec (declare one spec per argument)",
            )
