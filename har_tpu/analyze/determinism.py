"""HL004 — determinism: the fleet stack's bit-identity pins must not
be one wall-clock read or one unseeded RNG away from flaking.

Three of the stack's strongest guarantees are *bit-identity* pins:
fleet events equal N standalone classifiers (PR 2), pipelined equals
synchronous (PR 5), and pre-crash ∪ post-recovery equals uninterrupted
(PR 4).  All three hold only because every clock is injectable
(``FakeClock``) and every random draw is seeded.  The PR-2 cache
nondeterminism hunt is what one violation costs.

Flagged inside ``har_tpu/serve/`` and ``har_tpu/adapt/``:

  - ``time.time()`` CALLS — wall-clock reads the fake-clock harness
    cannot intercept — and (PR 8) ``time.time`` passed/stored AS A
    CALLABLE: ``self._clock = clock or time.time`` smuggles the same
    wall clock past the old call-only check, one indirection later.
    An injectable default that must be monotonic spells it
    ``clock or time.monotonic`` (still allowed — monotonic/
    perf_counter duration measurement feeds reporting, not
    decisions); a deliberate wall-clock default (the registry's
    ``created_unix`` stamps) carries a reviewed ``disable=HL004``.
  - (PR 8) ``datetime.datetime.now()`` / ``utcnow()`` — the same wall
    clock wearing a different module; previously invisible to the
    ``time.time``-shaped check.
  - stdlib ``random.*`` calls — the process-global RNG, unseedable per
    run without cross-test contamination;
  - legacy global numpy RNG (``np.random.rand`` / ``np.random.seed`` /
    any ``np.random.<fn>`` other than ``default_rng``) and
    ``np.random.default_rng()`` with NO seed — both draw from
    process-global or OS entropy;
  - iteration directly over a ``set`` expression (literal, set
    comprehension, or ``set(...)`` call) — set order is hash-dependent
    across processes, the dict-order trap for session-id collections
    (plain dicts are insertion-ordered and fine; a session-id SET is
    not).  Wrap in ``sorted(...)``.

WALL-CLOCK ALLOWLIST (PR 13): ``har_tpu/serve/net/`` is the one
subtree where the wall-clock findings (``time.time`` calls/references,
``datetime.now``) are DECLARED legal — the transport owns real
deadlines, and the leader lease is a cross-process timestamp that
monotonic clocks cannot express (they are not comparable between
processes).  The allowlist is a path scope, not a suppression: the
RNG and set-iteration findings still apply inside it, and a
``time.time()`` planted anywhere else in ``serve/`` (the engine, the
dispatcher) still fails the gate — acceptance-mutation-pinned against
the real ``serve/engine.py``.
"""

from __future__ import annotations

import ast

from har_tpu.analyze.core import FileContext, Finding, Rule

_SCOPES = ("har_tpu/serve/", "har_tpu/adapt/")
# the declared wall-clock scope: real transport deadlines + the
# cross-process leader lease live here and NOWHERE else
_WALLCLOCK_OK = ("har_tpu/serve/net/",)


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id in ("set", "frozenset")
    )


class DeterminismRule(Rule):
    rule_id = "HL004"
    title = "determinism"

    def applies(self, rel: str) -> bool:
        return any(rel.startswith(s) for s in _SCOPES)

    def check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        # the transport subtree's declared wall-clock legality; every
        # OTHER determinism finding still applies there
        wall_ok = any(ctx.rel.startswith(p) for p in _WALLCLOCK_OK)
        # enclosing-symbol map for readable findings
        symbols: dict[int, str] = {}

        def label(node, qual):
            for sub in ast.walk(node):
                ln = getattr(sub, "lineno", None)
                if ln is not None and ln not in symbols:
                    symbols[ln] = qual

        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                label(node, node.name)

        def flag(node, msg):
            findings.append(
                ctx.finding(
                    self.rule_id, node, msg,
                    symbols.get(getattr(node, "lineno", 0), ""),
                )
            )

        # callable-reference detection: `time.time` appearing OUTSIDE a
        # call's function position (stored as an injectable default,
        # passed as a key fn, ...) is the same wall clock one
        # indirection later — collect the call-position nodes first so
        # the reference walk can exclude them
        call_funcs = {
            id(node.func)
            for node in ast.walk(ctx.tree)
            if isinstance(node, ast.Call)
        }
        for node in ast.walk(ctx.tree):
            if (
                not wall_ok
                and isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "time"
                and node.attr == "time"
                and id(node) not in call_funcs
            ):
                flag(
                    node,
                    "`time.time` stored/passed as a callable — the "
                    "wall clock rides the indirection past the "
                    "FakeClock harness exactly like a direct call; "
                    "default to the injectable clock (or "
                    "`time.monotonic` for durations)",
                )
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                f = node.func
                # datetime.now()/utcnow(): `datetime.now(...)` on the
                # imported class or `datetime.datetime.now(...)` on the
                # module — both are wall clocks the harness cannot fake
                if not wall_ok and f.attr in ("now", "utcnow") and (
                    (
                        isinstance(f.value, ast.Name)
                        and f.value.id == "datetime"
                    )
                    or (
                        isinstance(f.value, ast.Attribute)
                        and f.value.attr == "datetime"
                        and isinstance(f.value.value, ast.Name)
                        and f.value.value.id == "datetime"
                    )
                ):
                    flag(
                        node,
                        f"`datetime.{f.attr}()` — a wall-clock read "
                        "the FakeClock harness cannot intercept (the "
                        "`time.time()` trap in a different module); "
                        "take the injectable clock and derive "
                        "timestamps from it",
                    )
                if isinstance(f.value, ast.Name):
                    if (
                        not wall_ok
                        and f.value.id == "time"
                        and f.attr == "time"
                    ):
                        flag(
                            node,
                            "`time.time()` call — a wall-clock read the "
                            "FakeClock harness cannot intercept; take "
                            "the injectable clock (`self._clock()`) "
                            "instead",
                        )
                    elif f.value.id == "random":
                        flag(
                            node,
                            f"stdlib `random.{f.attr}(...)` — the "
                            "process-global RNG breaks the bit-identity "
                            "pins; draw from a seeded "
                            "`np.random.default_rng(seed)` instead",
                        )
                elif (
                    isinstance(f.value, ast.Attribute)
                    and isinstance(f.value.value, ast.Name)
                    and f.value.value.id in ("np", "numpy")
                    and f.value.attr == "random"
                ):
                    if f.attr != "default_rng":
                        flag(
                            node,
                            f"legacy global `np.random.{f.attr}(...)` — "
                            "unseeded process-global state; use a "
                            "seeded `np.random.default_rng(seed)`",
                        )
                    elif not node.args and not node.keywords:
                        flag(
                            node,
                            "`np.random.default_rng()` without a seed "
                            "draws from OS entropy — pass an explicit "
                            "seed so runs are reproducible",
                        )
            elif isinstance(node, ast.For) and _is_set_expr(node.iter):
                flag(
                    node.iter,
                    "iterating a set — order is hash-dependent across "
                    "processes (the nondeterministic cousin of the "
                    "session-dict-order trap); wrap in `sorted(...)`",
                )
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                for gen in node.generators:
                    if _is_set_expr(gen.iter):
                        flag(
                            gen.iter,
                            "comprehension over a set — order is "
                            "hash-dependent across processes; wrap in "
                            "`sorted(...)`",
                        )
        return findings
