"""HL001 — hot-path host-sync: nothing on the dispatch launch path may
force a device→host synchronization.

The Spark-ML perf study (arXiv 1612.01437, PAPERS.md) found that
serialization + scheduling — not compute — dominates distributed-ML
latency; our analog is a host fetch on the launch path, which stalls
the pipelined dispatch plane (``har_tpu.serve.dispatch``) and erases
the overlap ``FleetConfig.pipeline_depth`` exists to buy.  PR 5 fought
exactly this by hand (the un-fetched launch/retire ticket split); this
rule keeps it won.

What is scanned:

  - the LAUNCH SURFACE: every function/method named ``launch``,
    ``_launch_batch``, ``pad``, ``pad_size``, ``gather`` or ``_place``
    in the fileset, closed over same-class ``self.`` method calls and
    direct module-function calls (``pad_pow2`` reached from
    ``HostScorer.pad``);
  - every ``@jax.jit``-decorated (or ``jax.jit(fn)``-wrapped) function
    body — a host materialization inside a traced body is either a
    tracer error waiting to happen or a silent constant-fold;
  - every function named ``fetch`` — the ONE allowed sink.  A fetch is
    where the host is SUPPOSED to block, but each host-sync line there
    must carry the reviewed ``# harlint: fetch-ok`` annotation, so a
    new sync cannot hide in a fetch body unexamined.

What is flagged: ``.item()``, ``jax.device_get``,
``.block_until_ready()``, ``np.asarray``/``np.array`` (host
materialization of a possibly-device value), and ``float()``/``int()``
over a non-trivial expression (calls/subscripts/attributes — a device
scalar coerced on host; bare-name coercions of scalar locals are not
flagged).  On the launch surface, ``# harlint: host-ok`` marks a
reviewed conversion of host-origin data (e.g. casting the host-side
scaler output before ``device_put``); it never excuses ``.item()`` /
``device_get`` / ``block_until_ready`` — those are real syncs wherever
they appear.
"""

from __future__ import annotations

import ast

from har_tpu.analyze.core import (
    FileContext,
    Finding,
    Rule,
    call_name,
    receiver_name,
    walk_functions,
)

LAUNCH_SURFACE = {
    "launch", "_launch_batch", "pad", "pad_size", "gather", "_place",
}
FETCH_SURFACE = {"fetch"}

_HARD_SYNCS = {"item", "device_get", "block_until_ready"}
_NP_NAMES = {"np", "numpy"}


def _is_jit_marked(node: ast.FunctionDef) -> bool:
    """Decorated with jax.jit / jit / functools.partial(jax.jit, ...)."""
    for dec in node.decorator_list:
        for sub in ast.walk(dec):
            if isinstance(sub, ast.Attribute) and sub.attr == "jit":
                return True
            if isinstance(sub, ast.Name) and sub.id == "jit":
                return True
    return False


def _jit_wrapped_names(tree: ast.Module) -> set[str]:
    """Local defs wrapped via ``jax.jit(forward)`` somewhere in the
    file (the loadgen pattern: define, then jit by name)."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and call_name(node) == "jit"
            and node.args
            and isinstance(node.args[0], ast.Name)
        ):
            names.add(node.args[0].id)
    return names


class HotPathRule(Rule):
    rule_id = "HL001"
    title = "hot-path host-sync"

    def finalize(self, ctxs: list[FileContext]) -> list[Finding]:
        # function tables across the fileset, for the launch closure
        funcs: dict[str, list[tuple[FileContext, str, str | None, ast.FunctionDef]]] = {}
        module_funcs: dict[str, list[tuple[FileContext, str, ast.FunctionDef]]] = {}
        per_ctx: dict[str, list] = {}
        for ctx in ctxs:
            entries = walk_functions(ctx.tree)
            per_ctx[ctx.rel] = entries
            for qual, cls, node in entries:
                funcs.setdefault(node.name, []).append((ctx, qual, cls, node))
                if cls is None and "." not in qual:
                    module_funcs.setdefault(node.name, []).append(
                        (ctx, qual, node)
                    )

        # seed the scan set: launch surface, fetch sinks, jit bodies
        work: list[tuple[FileContext, str, str | None, ast.FunctionDef, str]] = []
        for ctx in ctxs:
            jit_names = _jit_wrapped_names(ctx.tree)
            for qual, cls, node in per_ctx[ctx.rel]:
                if node.name in LAUNCH_SURFACE:
                    work.append((ctx, qual, cls, node, "launch"))
                elif node.name in FETCH_SURFACE:
                    work.append((ctx, qual, cls, node, "fetch"))
                elif _is_jit_marked(node) or (
                    cls is None and node.name in jit_names
                ):
                    work.append((ctx, qual, cls, node, "jit"))

        findings: list[Finding] = []
        seen: set[tuple[str, str]] = set()
        while work:
            ctx, qual, cls, node, mode = work.pop()
            if (ctx.rel, qual) in seen:
                continue
            seen.add((ctx.rel, qual))
            findings.extend(self._scan(ctx, qual, node, mode))
            if mode != "launch":
                continue
            # close the launch surface: self-method calls within the
            # same class, and direct Name calls to module functions
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                if (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                    and cls is not None
                ):
                    for tctx, tqual, tcls, tnode in funcs.get(f.attr, ()):
                        if tcls == cls:
                            work.append((tctx, tqual, tcls, tnode, "launch"))
                elif isinstance(f, ast.Name):
                    for tctx, tqual, tnode in module_funcs.get(f.id, ()):
                        work.append((tctx, tqual, None, tnode, "launch"))
        return findings

    # ------------------------------------------------------------ scan

    def _scan(
        self, ctx: FileContext, qual: str, node: ast.FunctionDef, mode: str
    ) -> list[Finding]:
        where = {
            "launch": "on the dispatch launch path",
            "jit": "inside a @jit body",
            "fetch": "in a retire-side fetch",
        }[mode]
        out: list[Finding] = []

        def flag(sub: ast.AST, what: str, soft: bool) -> None:
            # fetch sinks: any sync is legal WITH the reviewed
            # annotation; launch surface: host-ok covers soft
            # (conversion) flags only; jit bodies: no annotation out
            if mode == "fetch":
                if ctx.suppressed(sub, "fetch-ok"):
                    ctx.suppression_hits += 1
                    return
                msg = (
                    f"{what} {where} without the `# harlint: fetch-ok` "
                    "annotation — a fetch is the one allowed host-sync "
                    "sink, and every sync line in it must be reviewed"
                )
            else:
                if (
                    soft
                    and mode == "launch"
                    and ctx.suppressed(sub, "host-ok")
                ):
                    ctx.suppression_hits += 1
                    return
                msg = (
                    f"{what} {where} forces a host sync — the device "
                    "idles while the host blocks; move it behind the "
                    "retire boundary (or annotate a reviewed "
                    "host-origin conversion with `# harlint: host-ok`)"
                )
            out.append(self.finding_at(ctx, sub, msg, qual))

        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = call_name(sub)
            recv = receiver_name(sub)
            # hard syncs match BOTH spellings: `jax.device_get(h)` /
            # `h.block_until_ready()` attributes AND the bare-name
            # `from jax import device_get` form.  Bare `item(...)` is
            # excluded — as a free function it is always user code, not
            # the ndarray method.
            if name in _HARD_SYNCS and (
                isinstance(sub.func, ast.Attribute)
                or name in ("device_get", "block_until_ready")
            ):
                flag(sub, f"`.{name}()`" if name != "device_get"
                     else "`jax.device_get`", soft=False)
            elif name in ("asarray", "array") and recv in _NP_NAMES:
                flag(sub, f"`np.{name}(...)`", soft=True)
            elif (
                isinstance(sub.func, ast.Name)
                and sub.func.id in ("float", "int")
                and len(sub.args) == 1
                and isinstance(
                    sub.args[0], (ast.Call, ast.Subscript, ast.Attribute)
                )
            ):
                flag(sub, f"`{sub.func.id}(...)` on a computed value",
                     soft=True)
        return out

    @staticmethod
    def finding_at(ctx, node, msg, qual) -> Finding:
        return ctx.finding("HL001", node, msg, qual)
