"""HL001 — hot-path host-sync: nothing on the dispatch launch path may
force a device→host synchronization.

The Spark-ML perf study (arXiv 1612.01437, PAPERS.md) found that
serialization + scheduling — not compute — dominates distributed-ML
latency; our analog is a host fetch on the launch path, which stalls
the pipelined dispatch plane (``har_tpu.serve.dispatch``) and erases
the overlap ``FleetConfig.pipeline_depth`` exists to buy.  PR 5 fought
exactly this by hand (the un-fetched launch/retire ticket split); this
rule keeps it won.

v2 (PR 8): the guarded surface is COMPUTED, not curated.  PR 6's rule
checked a hand-listed name set (``{launch, _launch_batch, pad,
pad_size, gather, _place}``) closed only over same-class ``self.``
calls — a sync two calls below ``launch`` (a scorer constructor
reached through ``_get_scorer`` → ``make_scorer``, an arena method
reached through a typed attribute) sailed through.  Now the scanned
set is the project call graph's reachability closure
(``analyze.callgraph``) from:

  - the LAUNCH ROOTS: every function/method named ``launch`` or
    ``_launch_batch`` — the ``DispatchTicket`` entry points — closed
    over ``self.`` methods (including subclass overrides), typed
    attributes (``self._arena.gather``), locals typed through return
    inference (``scorer = self._get_scorer()``), cross-module imports,
    and closures nested in reached functions (``_attempt_launch``
    handed to ``retry_call``).  Traversal stops at functions named
    ``fetch``/``fetch_fused`` — the allowed sinks, scanned separately;
  - every ``@jax.jit``-decorated (or ``jax.jit(fn)``-wrapped) function
    body — a host materialization inside a traced body is either a
    tracer error waiting to happen or a silent constant-fold.  (The
    closure of jit bodies through the call graph — and shard_map/scan
    bodies — is HL006's jit-purity surface, which reuses this module's
    sync detectors; direct jit bodies stay here for continuity);
  - every function named ``fetch`` or ``fetch_fused`` (the fused
    hot-loop retire) — the allowed sinks.  A fetch is
    where the host is SUPPOSED to block, but each host-sync line there
    must carry the reviewed ``# harlint: fetch-ok`` annotation, so a
    new sync cannot hide in a fetch body unexamined.

What is flagged: ``.item()``, ``jax.device_get``,
``.block_until_ready()``, ``np.asarray``/``np.array`` (host
materialization of a possibly-device value), and ``float()``/``int()``
over a non-trivial expression (calls/subscripts/attributes — a device
scalar coerced on host; bare-name coercions of scalar locals are not
flagged).  On the launch surface, ``# harlint: host-ok`` marks a
reviewed conversion of host-origin data (e.g. casting the host-side
scaler output before ``device_put``); it never excuses ``.item()`` /
``device_get`` / ``block_until_ready`` — those are real syncs wherever
they appear.
"""

from __future__ import annotations

import ast

from har_tpu.analyze.core import (
    FileContext,
    Finding,
    Rule,
    call_name,
    receiver_name,
)

LAUNCH_ROOTS = {"launch", "_launch_batch"}
FETCH_SURFACE = {"fetch", "fetch_fused"}

_HARD_SYNCS = {"item", "device_get", "block_until_ready"}
_NP_NAMES = {"np", "numpy"}


def is_jit_marked(node: ast.FunctionDef) -> bool:
    """Decorated with jax.jit / jit / functools.partial(jax.jit, ...)."""
    for dec in node.decorator_list:
        for sub in ast.walk(dec):
            if isinstance(sub, ast.Attribute) and sub.attr == "jit":
                return True
            if isinstance(sub, ast.Name) and sub.id == "jit":
                return True
    return False


def wrapped_def_nodes(tree: ast.Module, wrappers: set[str]) -> set[int]:
    """AST ``id()``s of the defs wrapped via ``jax.jit(forward)`` /
    ``shard_map(step, ...)`` — the define-then-wrap-by-name pattern, at
    any nesting level.  The referenced Name is resolved LEXICALLY from
    the wrapping call outward (innermost enclosing scope that binds a
    def of that name wins, then the module), exactly like the
    interpreter would — so an unrelated def merely SHARING the name
    elsewhere in the file is never mistaken for a traced body.  A class
    body is its own namespace: ``step_jit = jax.jit(step)`` next to
    ``def step`` in a class body resolves to the member (the
    define-then-wrap-in-class pattern), while functions NESTED inside
    the class resolve through the enclosing function scopes only —
    class namespaces do not participate in closures."""
    out: set[int] = set()

    def shallow(scope: ast.AST):
        # scope's own statements (any block depth): stop at nested
        # def/class boundaries — their interiors are separate scopes
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(
                node,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                stack.extend(ast.iter_child_nodes(node))

    def bound_defs(scope: ast.AST) -> dict[str, ast.AST]:
        defs: dict[str, ast.AST] = {}
        for node in shallow(scope):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
        return defs

    def visit(scope: ast.AST, env: list[dict[str, ast.AST]]) -> None:
        body_env = env + [bound_defs(scope)]
        # the class namespace is visible to the class BODY only —
        # functions nested in the class close over the enclosing
        # function scopes instead
        child_env = env if isinstance(scope, ast.ClassDef) else body_env
        for sub in shallow(scope):
            if (
                isinstance(sub, ast.Call)
                and call_name(sub) in wrappers
                and sub.args
                and isinstance(sub.args[0], ast.Name)
            ):
                target = sub.args[0].id
                for table in reversed(body_env):
                    if target in table:
                        out.add(id(table[target]))
                        break
            elif isinstance(
                sub,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                visit(sub, child_env)

    visit(tree, [])
    return out


def scan_syncs(
    rule_id: str,
    ctx: FileContext,
    qual: str,
    node: ast.FunctionDef,
    mode: str,
    where: str,
    *,
    own_statements_only: bool = False,
    reach_note: str = "",
) -> list[Finding]:
    """The shared host-sync detectors — HL001 runs them over the launch
    reachability closure, direct jit bodies and fetch sinks; HL006
    reuses them over the traced-body closure.  ``mode`` selects the
    annotation contract: ``fetch`` (any sync legal WITH ``fetch-ok``),
    ``launch`` (``host-ok`` covers soft conversions only), anything
    else (no annotation escape, only ``disable=``)."""
    out: list[Finding] = []

    def flag(sub: ast.AST, what: str, soft: bool) -> None:
        if mode == "fetch":
            if ctx.suppressed(sub, "fetch-ok"):
                ctx.suppression_hits += 1
                return
            msg = (
                f"{what} {where} without the `# harlint: fetch-ok` "
                "annotation — a fetch is the one allowed host-sync "
                "sink, and every sync line in it must be reviewed"
            )
        else:
            if soft and mode == "launch" and ctx.suppressed(sub, "host-ok"):
                ctx.suppression_hits += 1
                return
            msg = (
                f"{what} {where} forces a host sync — the device "
                "idles while the host blocks; move it behind the "
                "retire boundary (or annotate a reviewed "
                "host-origin conversion with `# harlint: host-ok`)"
            )
        out.append(
            Finding(
                rule=rule_id,
                path=ctx.rel,
                line=getattr(sub, "lineno", 1),
                message=msg + reach_note,
                symbol=qual,
                snippet=ctx.snippet(getattr(sub, "lineno", 1)),
            )
        )

    for sub in walk_own(node) if own_statements_only else ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        name = call_name(sub)
        recv = receiver_name(sub)
        # hard syncs match BOTH spellings: `jax.device_get(h)` /
        # `h.block_until_ready()` attributes AND the bare-name
        # `from jax import device_get` form.  Bare `item(...)` is
        # excluded — as a free function it is always user code, not
        # the ndarray method.
        if name in _HARD_SYNCS and (
            isinstance(sub.func, ast.Attribute)
            or name in ("device_get", "block_until_ready")
        ):
            flag(sub, f"`.{name}()`" if name != "device_get"
                 else "`jax.device_get`", soft=False)
        elif name in ("asarray", "array") and recv in _NP_NAMES:
            flag(sub, f"`np.{name}(...)`", soft=True)
        elif (
            isinstance(sub.func, ast.Name)
            and sub.func.id in ("float", "int")
            and len(sub.args) == 1
            and isinstance(
                sub.args[0], (ast.Call, ast.Subscript, ast.Attribute)
            )
        ):
            flag(sub, f"`{sub.func.id}(...)` on a computed value",
                 soft=True)
    return out


def walk_own(node: ast.FunctionDef):
    """ast.walk that does NOT descend into nested function defs — for
    scanning a function's own statements when its closures are separate
    graph nodes (HL006's per-function pass)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        sub = stack.pop()
        yield sub
        if not isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(sub))


class HotPathRule(Rule):
    rule_id = "HL001"
    title = "hot-path host-sync"

    def finalize(self, ctxs: list[FileContext]) -> list[Finding]:
        from har_tpu.analyze.core import Project

        project = self.project or Project(ctxs)
        graph = project.callgraph

        launch_roots = [
            fi for fi in graph.functions.values() if fi.name in LAUNCH_ROOTS
        ]
        # the launch surface ends at fetch sinks (scanned separately)
        reach = graph.reachable(
            launch_roots, stop=lambda fi: fi.name in FETCH_SURFACE
        )

        findings: list[Finding] = []
        for key, (parent, root) in reach.items():
            fi = graph.functions[key]
            if fi.ctx.support:
                # subset run: the closure traverses support files (the
                # roots and edges live there) but only requested files
                # are examined
                continue
            note = ""
            if parent is not None:
                chain = graph.chain(reach, key)
                note = (
                    "  [reached from launch root `"
                    + chain[0]
                    + "` via "
                    + " -> ".join(f"`{q}`" for q in chain[1:])
                    + "]"
                )
            # own statements only: a reached function's nested defs are
            # separate entries in the closure (scanning both the parent
            # walk and the nested node would double-flag)
            findings.extend(
                scan_syncs(
                    self.rule_id, fi.ctx, fi.qual, fi.node, "launch",
                    "on the dispatch launch path",
                    own_statements_only=True,
                    reach_note=note,
                )
            )

        # direct jit bodies (decorator or jit-by-name), launch surface
        # taking precedence when both apply; their call-graph closure
        # is HL006's surface
        for ctx in ctxs:
            if ctx.support:
                continue
            jit_nodes = wrapped_def_nodes(ctx.tree, {"jit"})
            for fi in graph.functions.values():
                if fi.rel != ctx.rel or fi.key in reach:
                    continue
                if fi.name in FETCH_SURFACE:
                    findings.extend(
                        scan_syncs(
                            self.rule_id, ctx, fi.qual, fi.node, "fetch",
                            "in a retire-side fetch",
                        )
                    )
                elif is_jit_marked(fi.node) or id(fi.node) in jit_nodes:
                    # nested scans would double-count: a jit-wrapped
                    # def nested under another jit-wrapped def is
                    # already covered by the outer walk.  Ancestors are
                    # the proper dotted prefixes of the qualname (class
                    # segments simply miss the function table)
                    parts = fi.qual.split(".")
                    if fi.parent_qual is not None and any(
                        (g := graph.functions.get(
                            (fi.rel, ".".join(parts[:i]))
                        )) is not None
                        and (
                            is_jit_marked(g.node)
                            or id(g.node) in jit_nodes
                        )
                        for i in range(1, len(parts))
                    ):
                        continue
                    findings.extend(
                        scan_syncs(
                            self.rule_id, ctx, fi.qual, fi.node, "jit",
                            "inside a @jit body",
                        )
                    )
        return findings
