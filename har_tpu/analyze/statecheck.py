"""HL002 — state completeness: every field a snapshotted class assigns
in ``__init__`` must round-trip ``state()`` / ``load_state()``.

The bug class PRs 4–5 patched with back-compat pins: a new FleetStats
counter lands in ``__init__`` and the snapshot path silently forgets
it, so the first crash after the feature ships zeroes it — the
conservation law then "balances" against amnesia.  This rule makes the
omission a gate failure at the commit that introduces the field.

Mechanics: for every class in the fileset that defines BOTH ``state``
and ``load_state``, every public attribute assigned in ``__init__``
must be *mentioned* by both methods.  A mention is a ``self.<name>``
access, a string literal naming the field, or membership in a
class-level string table (``_COUNTERS``/``_STAGES``-style tuples) that
the method references — so the ``getattr(self, k) for k in
self._COUNTERS`` idiom counts, and DELETING a name from the table (or
a key line from ``state()``) immediately un-mentions it.

Escapes: underscore-private attributes are process-local by
convention (``StageHistogram._recent`` — the trailing percentile
window restarts after recovery, documented there), and a public field
that intentionally restarts is annotated ``# harlint: ephemeral`` on
its ``__init__`` line (``FleetStats.sessions`` / ``queue_depth`` —
gauges recomputed during restore).

The static half is paired with a runtime guard: ``FleetStats.
load_state`` warns and counts (``unknown_state_keys``) when a state
dict carries keys this version does not know — a newer writer's state
degrades loudly instead of silently dropping fields.
"""

from __future__ import annotations

import ast

from har_tpu.analyze.core import FileContext, Finding, Rule


def _init_fields(cls: ast.ClassDef, ctx: FileContext) -> list[tuple[str, ast.AST]]:
    init = next(
        (
            n
            for n in cls.body
            if isinstance(n, ast.FunctionDef) and n.name == "__init__"
        ),
        None,
    )
    if init is None:
        return []
    fields = []
    for node in ast.walk(init):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        for t in targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and not t.attr.startswith("_")
            ):
                if ctx.suppressed(node, "ephemeral"):
                    ctx.suppression_hits += 1
                    continue
                fields.append((t.attr, node))
    return fields


def _string_tables(cls: ast.ClassDef) -> dict[str, set[str]]:
    """Class-level assignments of string tuples/lists/sets:
    ``_COUNTERS = ("enqueued", ...)`` -> {"_COUNTERS": {...}}."""
    tables: dict[str, set[str]] = {}
    for node in cls.body:
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, (ast.Tuple, ast.List, ast.Set)):
            continue
        strings = {
            e.value
            for e in node.value.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        }
        if not strings:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                tables[t.id] = strings
    return tables


def _mentions(fn: ast.FunctionDef, tables: dict[str, set[str]]) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            out.add(node.attr)
            if node.attr in tables:  # self._COUNTERS reference
                out.update(tables[node.attr])
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
        elif isinstance(node, ast.Name) and node.id in tables:
            out.update(tables[node.id])
    return out


class StateCompletenessRule(Rule):
    rule_id = "HL002"
    title = "state completeness"

    def check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = {
                n.name: n
                for n in cls.body
                if isinstance(n, ast.FunctionDef)
            }
            if "state" not in methods or "load_state" not in methods:
                continue
            tables = _string_tables(cls)
            state_m = _mentions(methods["state"], tables)
            load_m = _mentions(methods["load_state"], tables)
            for name, node in _init_fields(cls, ctx):
                for method, mentioned in (
                    ("state()", state_m),
                    ("load_state()", load_m),
                ):
                    if name not in mentioned:
                        findings.append(
                            ctx.finding(
                                self.rule_id,
                                node,
                                f"field `{name}` assigned in "
                                f"{cls.name}.__init__ is absent from "
                                f"{method} — it will silently zero "
                                "after a crash recovery; persist it "
                                "with a load default, or annotate a "
                                "deliberately process-local gauge "
                                "with `# harlint: ephemeral`",
                                f"{cls.name}.{name}",
                            )
                        )
        return findings
