"""harlint — AST-based invariant checker for the fleet serving stack.

Eight bespoke rules over ``har_tpu/serve`` + ``har_tpu/adapt`` +
``har_tpu/parallel`` (plus the shared ``serving.py`` /
``utils/durable.py`` / ``utils/backoff.py`` they ride on), each
encoding an invariant that has already cost a shipped bug or a
hand-fought PR:

  HL001  hot-path host-sync      no ``.item()``/``device_get``/
                                 ``block_until_ready``/host
                                 materialization anywhere the project
                                 call graph can reach from the
                                 ``launch``/``_launch_batch`` roots, or
                                 inside ``@jit`` bodies; retire-side
                                 fetches are the one allowed sink
                                 (``# harlint: fetch-ok``)
  HL002  state completeness      every public field a snapshotted class
                                 assigns in ``__init__`` round-trips
                                 ``state()``/``load_state()``
  HL003  journal exhaustiveness  record types ↔ replay handlers ↔
                                 chaos kill points stay in bijection
  HL004  determinism             no wall clocks (called OR passed as
                                 callables), global RNGs, or set-order
                                 iteration where bit-identity pins live
  HL005  durability              registry/journal writes never bypass
                                 the utils/durable fsync discipline
  HL006  jit-purity              nothing reachable from a traced body
                                 (jit/shard_map/scan) mutates captured
                                 state, reads clocks, prints/logs, or
                                 fetches — side effects fire at trace
                                 time only
  HL007  partition-spec coverage shard_map/jit in the parallel package
                                 declare placements for all args, every
                                 PartitionSpec axis is a declared mesh
                                 axis, spec builders actually shard
                                 >1-D kernels
  HL008  stale suppressions      a ``# harlint:`` annotation that no
                                 longer suppresses anything is itself a
                                 finding — reviewed contracts cannot rot

HL001/HL006 share the project-wide call graph (``analyze.callgraph``):
``self.`` methods, typed attributes, return-type-inferred locals,
cross-module imports and nested closures all resolve, so the guarded
surface is computed reachability, not a name list.

Run it as ``har lint`` (text or ``--json``; ``--changed``/``--rule``
for fast pre-commit subsets, ``--stats`` for per-rule timing), or from
code via ``run_harlint``.  The committed ``harlint_baseline.json``
suppresses reviewed pre-existing debt; the release gate fails on any
non-baselined finding and on a lint exceeding its 5 s budget.  See
docs/static_analysis.md.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

from har_tpu.analyze.baseline import (
    DEFAULT_BASELINE,
    apply_baseline,
    load_baseline,
    write_baseline,
)
from har_tpu.analyze.core import (
    DEFAULT_FILESET,
    FileContext,
    Finding,
    Rule,
    discover_files,
    load_contexts,
    run_rules,
)
from har_tpu.analyze.determinism import DeterminismRule
from har_tpu.analyze.durability import DurabilityRule
from har_tpu.analyze.hotpath import HotPathRule
from har_tpu.analyze.journalcheck import JournalExhaustivenessRule
from har_tpu.analyze.jitpurity import JitPurityRule
from har_tpu.analyze.partitionspec import (
    AXIS_DECLARERS as _AXIS_DECLARERS,
    PartitionSpecRule,
)
from har_tpu.analyze.statecheck import StateCompletenessRule
from har_tpu.analyze.suppressions import SuppressionAuditRule


def default_rules() -> list[Rule]:
    return [
        HotPathRule(),
        StateCompletenessRule(),
        JournalExhaustivenessRule(),
        DeterminismRule(),
        DurabilityRule(),
        JitPurityRule(),
        PartitionSpecRule(),
        SuppressionAuditRule(),
    ]


def repo_root() -> Path:
    """The checkout root: the directory holding the ``har_tpu``
    package (where the baseline file and the fileset paths resolve)."""
    return Path(__file__).resolve().parent.parent.parent


@dataclasses.dataclass
class LintReport:
    """One harlint run: fresh findings, suppression accounting, and the
    JSON shape the release gate stamps into artifacts/test_gate.json."""

    findings: list[Finding]  # non-baselined — what fails the gate
    baselined: int
    annotation_suppressed: int
    rules_run: list[str]
    files: int
    baseline_path: str
    baseline_size: int
    rule_ms: dict = dataclasses.field(default_factory=dict)
    callgraph_ms: float = 0.0
    lint_ms: float = 0.0  # in-process rule time; the gate measures the
    #                       fresh-interpreter wall clock around it

    @property
    def ok(self) -> bool:
        return not self.findings

    @property
    def suppressed(self) -> int:
        return self.baselined + self.annotation_suppressed

    @property
    def per_rule(self) -> dict:
        """Fresh-finding counts per rule id, zero-filled over the rules
        that ran — the release gate stamps this so a red rule is
        identifiable from the gate log alone."""
        out = {r: 0 for r in self.rules_run}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "ok": self.ok,
            "rules_run": self.rules_run,
            "files": self.files,
            "findings": len(self.findings),
            "per_rule": self.per_rule,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
            "annotation_suppressed": self.annotation_suppressed,
            "baseline": self.baseline_path,
            "baseline_size": self.baseline_size,
            "rule_ms": self.rule_ms,
            "callgraph_ms": self.callgraph_ms,
            "lint_ms": self.lint_ms,
            "findings_list": [
                {
                    "rule": f.rule,
                    "path": f.path,
                    "line": f.line,
                    "symbol": f.symbol,
                    "message": f.message,
                }
                for f in self.findings
            ],
        }

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        lines.append(
            f"harlint: {len(self.rules_run)} rules over {self.files} "
            f"files — {len(self.findings)} finding(s), "
            f"{self.suppressed} suppressed "
            f"({self.baselined} baseline, "
            f"{self.annotation_suppressed} annotations)"
        )
        return "\n".join(lines)

    def render_stats(self) -> str:
        """``har lint --stats``: per-rule wall time + finding counts,
        so a slow-rule regression is visible before it eats the gate's
        5 s lint budget."""
        per = self.per_rule
        rows = [
            f"  {rule:<7} {self.rule_ms.get(rule, 0.0):>8.1f} ms  "
            f"{per.get(rule, 0):>3} finding(s)"
            for rule in self.rules_run
        ]
        rows.append(
            f"  callgraph build: {self.callgraph_ms:.1f} ms "
            "(inside the first consuming rule's time)"
        )
        rows.append(
            f"  total: {self.lint_ms:.1f} ms over {self.files} files"
        )
        return "\n".join(["harlint --stats (per-rule):"] + rows)


def changed_fileset_paths(
    root: Path | str, ref: str = "HEAD"
) -> list[str]:
    """Repo-relative fileset .py files that differ from ``ref``
    (``git diff --name-only`` of the working tree vs the ref, plus
    untracked files) — the ``har lint --changed`` fast path.  Only
    files the default fileset would lint are returned, so the subset
    run judges exactly what a full run would judge about them."""
    import subprocess

    root = Path(root)
    try:
        proc = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"],
            cwd=root, capture_output=True, text=True, check=True,
        )
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, check=True,
        )
    except (OSError, subprocess.CalledProcessError) as exc:
        raise SystemExit(
            f"har lint --changed: git diff vs {ref!r} failed "
            f"({getattr(exc, 'stderr', exc)})"
        )
    changed = {
        line.strip()
        for out in (proc.stdout, untracked.stdout)
        for line in out.splitlines()
        if line.strip().endswith(".py")
    }
    fileset = {
        f.relative_to(root).as_posix()
        for f in discover_files(root)
    }
    return sorted(changed & fileset)


def lint_sources(
    sources: dict[str, str], rules: list[Rule] | None = None
) -> list[Finding]:
    """Run the rules over in-memory ``{repo-relative-path: source}``
    pairs — the fixture-test entry point (each rule's positive and
    negative snippets are pinned through this)."""
    ctxs = [FileContext(rel, src) for rel, src in sorted(sources.items())]
    findings, _ = run_rules(ctxs, rules or default_rules())
    return findings


def run_harlint(
    root: Path | str | None = None,
    paths=None,
    baseline: Path | str | None = None,
    update_baseline: bool = False,
    rules: list[Rule] | None = None,
) -> LintReport:
    """Lint the checkout: load the fileset, run the rules, apply the
    committed baseline.  ``update_baseline=True`` rewrites the baseline
    to the current findings (they then report as baselined).

    A path-subset run (explicit ``paths``, the ``--changed`` fast
    path) drops HL008 AND HL003 from the default rule list: both
    judge whole-fileset properties — suppression staleness needs
    HL001's launch closure actually computed, and HL003's
    journal-writer ↔ replay-handler ↔ kill-point bijections only hold
    over the full set (recover.py linted alone reports every handler
    as orphaned).  An explicit ``rules`` list is always respected as
    given.

    Subset runs also load SUPPORT files alongside the requested paths:
    when HL007 is in play, the axis-declaring files
    (``_AXIS_DECLARERS``) — the declared-mesh-axes table and
    ``*_AXIS`` constant resolution live in ``mesh.py`` et al., and
    judging an edited ``tensor_parallel.py`` without them
    false-positives the spec-builder check on clean code; when HL001,
    HL003 (forced via ``--rule`` — the default subset list drops it)
    or HL006 is in play, the REST OF THE FILESET — reachability roots
    (the ``launch`` defs, the jit/shard_map wrap sites) and HL003's
    journal writers/kill-point call sites live anywhere in the
    project, so a changed helper judged without its callers would
    pass clean on the very launch-path sync the full
    run flags.  Support files inform the analysis only — per-file
    checks and finalize body scans skip them (so the subset run stays
    cheaper than a full lint and its suppression counts cover the
    requested files only), and they never scope a baseline rewrite."""
    import time as _time

    t_lint0 = _time.perf_counter()
    root = Path(root) if root is not None else repo_root()
    baseline_path = (
        Path(baseline) if baseline is not None else root / DEFAULT_BASELINE
    )
    if rules is None:
        rules = default_rules()
        if paths is not None:
            rules = [
                r for r in rules if r.rule_id not in ("HL003", "HL008")
            ]
    ctxs = load_contexts(root, paths)
    requested_rels = {c.rel for c in ctxs}
    if paths is not None:
        rule_ids = {r.rule_id for r in rules}
        support: set[str] = set()
        if "HL007" in rule_ids:
            support |= {
                p for p in _AXIS_DECLARERS
                if p not in requested_rels and (root / p).is_file()
            }
        if rule_ids & {"HL001", "HL003", "HL006"}:
            support |= {
                f.relative_to(root).as_posix()
                for f in discover_files(root)
            } - requested_rels
        if support:
            support_ctxs = load_contexts(root, sorted(support))
            for c in support_ctxs:
                c.support = True
            ctxs = ctxs + support_ctxs
    findings, stats = run_rules(ctxs, rules)
    findings = [f for f in findings if f.path in requested_rels]
    if update_baseline:
        # scope the rewrite to the (rule × file) coverage this run
        # actually examined: a subset run must not retire other files'
        # reviewed entries (support contexts inform the analysis, they
        # are not examined), and a --rule / --changed run that skipped
        # a rule must not retire that rule's entries anywhere
        write_baseline(
            baseline_path,
            findings,
            linted_files=requested_rels,
            rules_run={r.rule_id for r in rules},
        )
    known = load_baseline(baseline_path)
    # rename eligibility is judged against the FULL fileset on disk,
    # not the (possibly partial) linted subset: an entry's file merely
    # missing from a --changed run is not a rename
    fileset_rels = {
        f.relative_to(root).as_posix() for f in discover_files(root)
    }
    fresh, baselined = apply_baseline(
        findings, known, fileset_files=fileset_rels
    )
    try:
        # repo-relative in reports: the gate log is a committed
        # artifact and must not carry machine-specific paths
        baseline_label = str(baseline_path.relative_to(root))
    except ValueError:
        baseline_label = str(baseline_path)
    return LintReport(
        findings=fresh,
        baselined=baselined,
        annotation_suppressed=stats.annotation_suppressed,
        rules_run=stats.rules_run,
        files=len(requested_rels),
        baseline_path=baseline_label,
        baseline_size=len(known),
        rule_ms=stats.rule_ms,
        callgraph_ms=stats.callgraph_ms,
        lint_ms=round((_time.perf_counter() - t_lint0) * 1e3, 2),
    )


__all__ = [
    "DEFAULT_FILESET",
    "Finding",
    "LintReport",
    "Rule",
    "changed_fileset_paths",
    "default_rules",
    "lint_sources",
    "repo_root",
    "run_harlint",
]
