"""harlint — AST-based invariant checker for the fleet serving stack.

Five bespoke rules over ``har_tpu/serve`` + ``har_tpu/adapt`` (plus the
shared ``serving.py``/``utils/durable.py`` they ride on), each encoding
an invariant that has already cost a shipped bug or a hand-fought PR:

  HL001  hot-path host-sync      no ``.item()``/``device_get``/
                                 ``block_until_ready``/host
                                 materialization on the dispatch launch
                                 path or inside ``@jit`` bodies;
                                 retire-side fetches are the one
                                 allowed sink (``# harlint: fetch-ok``)
  HL002  state completeness      every public field a snapshotted class
                                 assigns in ``__init__`` round-trips
                                 ``state()``/``load_state()``
  HL003  journal exhaustiveness  record types ↔ replay handlers ↔
                                 chaos kill points stay in bijection
  HL004  determinism             no wall clocks, global RNGs, or
                                 set-order iteration where bit-identity
                                 pins live
  HL005  durability              registry/journal writes never bypass
                                 the utils/durable fsync discipline

Run it as ``har lint`` (text or ``--json``), or from code via
``run_harlint``.  The committed ``harlint_baseline.json`` suppresses
reviewed pre-existing debt; the release gate fails on any non-baselined
finding.  See docs/static_analysis.md.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

from har_tpu.analyze.baseline import (
    DEFAULT_BASELINE,
    apply_baseline,
    load_baseline,
    write_baseline,
)
from har_tpu.analyze.core import (
    DEFAULT_FILESET,
    FileContext,
    Finding,
    Rule,
    load_contexts,
    run_rules,
)
from har_tpu.analyze.determinism import DeterminismRule
from har_tpu.analyze.durability import DurabilityRule
from har_tpu.analyze.hotpath import HotPathRule
from har_tpu.analyze.journalcheck import JournalExhaustivenessRule
from har_tpu.analyze.statecheck import StateCompletenessRule


def default_rules() -> list[Rule]:
    return [
        HotPathRule(),
        StateCompletenessRule(),
        JournalExhaustivenessRule(),
        DeterminismRule(),
        DurabilityRule(),
    ]


def repo_root() -> Path:
    """The checkout root: the directory holding the ``har_tpu``
    package (where the baseline file and the fileset paths resolve)."""
    return Path(__file__).resolve().parent.parent.parent


@dataclasses.dataclass
class LintReport:
    """One harlint run: fresh findings, suppression accounting, and the
    JSON shape the release gate stamps into artifacts/test_gate.json."""

    findings: list[Finding]  # non-baselined — what fails the gate
    baselined: int
    annotation_suppressed: int
    rules_run: list[str]
    files: int
    baseline_path: str
    baseline_size: int

    @property
    def ok(self) -> bool:
        return not self.findings

    @property
    def suppressed(self) -> int:
        return self.baselined + self.annotation_suppressed

    def to_json(self) -> dict:
        return {
            "ok": self.ok,
            "rules_run": self.rules_run,
            "files": self.files,
            "findings": len(self.findings),
            "suppressed": self.suppressed,
            "baselined": self.baselined,
            "annotation_suppressed": self.annotation_suppressed,
            "baseline": self.baseline_path,
            "baseline_size": self.baseline_size,
            "findings_list": [
                {
                    "rule": f.rule,
                    "path": f.path,
                    "line": f.line,
                    "symbol": f.symbol,
                    "message": f.message,
                }
                for f in self.findings
            ],
        }

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        lines.append(
            f"harlint: {len(self.rules_run)} rules over {self.files} "
            f"files — {len(self.findings)} finding(s), "
            f"{self.suppressed} suppressed "
            f"({self.baselined} baseline, "
            f"{self.annotation_suppressed} annotations)"
        )
        return "\n".join(lines)


def lint_sources(
    sources: dict[str, str], rules: list[Rule] | None = None
) -> list[Finding]:
    """Run the rules over in-memory ``{repo-relative-path: source}``
    pairs — the fixture-test entry point (each rule's positive and
    negative snippets are pinned through this)."""
    ctxs = [FileContext(rel, src) for rel, src in sorted(sources.items())]
    findings, _ = run_rules(ctxs, rules or default_rules())
    return findings


def run_harlint(
    root: Path | str | None = None,
    paths=None,
    baseline: Path | str | None = None,
    update_baseline: bool = False,
    rules: list[Rule] | None = None,
) -> LintReport:
    """Lint the checkout: load the fileset, run the rules, apply the
    committed baseline.  ``update_baseline=True`` rewrites the baseline
    to the current findings (they then report as baselined)."""
    root = Path(root) if root is not None else repo_root()
    baseline_path = (
        Path(baseline) if baseline is not None else root / DEFAULT_BASELINE
    )
    rules = rules or default_rules()
    ctxs = load_contexts(root, paths)
    findings, stats = run_rules(ctxs, rules)
    if update_baseline:
        # scope the rewrite to the files this run actually examined:
        # a subset run must not retire other files' reviewed entries
        write_baseline(
            baseline_path, findings, linted_files={c.rel for c in ctxs}
        )
    known = load_baseline(baseline_path)
    fresh, baselined = apply_baseline(findings, known)
    try:
        # repo-relative in reports: the gate log is a committed
        # artifact and must not carry machine-specific paths
        baseline_label = str(baseline_path.relative_to(root))
    except ValueError:
        baseline_label = str(baseline_path)
    return LintReport(
        findings=fresh,
        baselined=baselined,
        annotation_suppressed=stats.annotation_suppressed,
        rules_run=stats.rules_run,
        files=stats.files,
        baseline_path=baseline_label,
        baseline_size=len(known),
    )


__all__ = [
    "DEFAULT_FILESET",
    "Finding",
    "LintReport",
    "Rule",
    "default_rules",
    "lint_sources",
    "repo_root",
    "run_harlint",
]
