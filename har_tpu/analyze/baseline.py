"""harlint baseline: committed suppression file for pre-existing debt.

The gate fails on any NON-baselined finding, so new violations can
never land — while debt that predates a rule is recorded (reviewed,
visible, diffable in the PR that admits it) instead of blocking the
gate forever.  Entries are line-number independent (``Finding.key``):
``rule|path|symbol|normalized-snippet`` — moving code around does not
churn the file; changing or fixing the flagged line retires the entry.
``apply_baseline`` additionally matches entries path-agnostically as a
fallback — but only entries whose recorded file no longer exists in
the fileset (a genuine rename/move) — so a rename does not stale a
reviewed entry, while a stale entry for a fixed violation in a
still-present file cannot launder an identical new violation in some
other file.  A file DELETED outright is indistinguishable from a
rename at match time, so its entries stay rename-eligible until the
next full ``--update-baseline`` retires them — the residual window the
near-empty-baseline policy (below) exists to keep closed.

The committed file is expected to stay near-empty: every rule ships
with its real findings fixed at introduction time, and
``har lint --update-baseline`` exists for the rare reviewed exception,
not as a pressure valve.
"""

from __future__ import annotations

import json
from pathlib import Path

from har_tpu.analyze.core import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = "harlint_baseline.json"


def load_baseline(path: Path) -> set[str]:
    """The committed suppression keys (empty set when the file does not
    exist — a missing baseline suppresses nothing)."""
    try:
        data = json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return set()
    return set(data.get("entries") or [])


def apply_baseline(
    findings: list[Finding],
    baseline: set[str],
    fileset_files: set[str] | None = None,
) -> tuple[list[Finding], int]:
    """Split findings into (fresh, n_baselined).

    Matching is two-pass: exact ``rule|path|symbol|snippet`` keys
    first, then a path-agnostic fallback on ``rule|symbol|snippet`` —
    so a reviewed entry survives the file it lives in being renamed or
    moved, not only the ±N-line shifts the line-free key already
    absorbs.  An exact entry covers EVERY finding with its key (the
    baseline file itself is a set, so N identical violating lines in
    one function write one deduplicated entry — it must suppress all N
    or ``--update-baseline`` followed by ``har lint`` goes red with
    zero code change).

    The fallback is deliberately narrow: an entry is eligible only if
    it was not consumed exactly AND its recorded file is not among
    ``fileset_files`` — the files that EXIST in the full fileset, not
    merely the ones a subset run happened to lint (an entry's file
    missing from a ``--changed`` subset is not a rename; judging
    eligibility against a partial set would let any baselined entry
    launder an identical new violation during pre-commit runs).  An
    entry whose original file still exists but no longer triggers is
    RETIRED, not transferable.  (An entry whose file was DELETED is
    the one case this proxy cannot tell from a rename — it remains
    eligible until a full ``--update-baseline`` drops it, which is why
    the baseline is kept near-empty.)  ``fileset_files=None`` (direct fixture
    calls) skips the existence judgement and treats every unconsumed
    entry as rename-eligible.  An eligible entry covers all findings
    sharing its relaxed key — the renamed file keeps its N duplicates
    covered, exactly like the exact pass."""

    def relaxed_key(key: str):
        parts = key.split("|", 3)
        return (parts[0], parts[2], parts[3]) if len(parts) == 4 else None

    used_exact: set[str] = set()
    unmatched: list[Finding] = []
    baselined = 0
    for f in findings:
        k = f.key()
        if k in baseline:
            used_exact.add(k)
            baselined += 1
        else:
            unmatched.append(f)
    relaxed: set = set()
    for e in baseline:
        if e in used_exact:
            continue
        if fileset_files is not None and entry_path(e) in fileset_files:
            continue  # original file still present: not a rename
        rk = relaxed_key(e)
        if rk is not None:
            relaxed.add(rk)
    fresh: list[Finding] = []
    for f in unmatched:
        if relaxed_key(f.key()) in relaxed:
            baselined += 1
        else:
            fresh.append(f)
    return fresh, baselined


def entry_path(entry: str) -> str:
    """The repo-relative file a baseline entry refers to (field 2 of
    ``rule|path|symbol|snippet``)."""
    parts = entry.split("|", 2)
    return parts[1] if len(parts) > 1 else ""


def entry_rule(entry: str) -> str:
    """The rule id a baseline entry refers to (field 1 of
    ``rule|path|symbol|snippet``)."""
    return entry.split("|", 1)[0]


def write_baseline(
    path: Path,
    findings: list[Finding],
    linted_files: set[str] | None = None,
    rules_run: set[str] | None = None,
) -> int:
    """Rewrite the baseline to the given findings' keys (sorted,
    deduplicated).  A run's coverage is (rule × file), and the rewrite
    is scoped to exactly that: an existing entry is preserved when its
    file is OUTSIDE ``linted_files`` OR its rule is OUTSIDE
    ``rules_run`` — an ``--update-baseline`` over a path subset or a
    ``--rule`` filter must never silently retire reviewed suppressions
    it did not re-examine (a ``--rule HL001`` pass produces no HL003
    findings, which is absence of evidence, not a fixed violation).
    ``None`` for either means that axis was fully covered (a
    full-fileset, all-rules run owns every entry).  Returns the entry
    count."""
    entries = {f.key() for f in findings}
    for e in load_baseline(path):
        examined_file = (
            linted_files is None or entry_path(e) in linted_files
        )
        examined_rule = (
            rules_run is None or entry_rule(e) in rules_run
        )
        if not (examined_file and examined_rule):
            entries.add(e)
    entries = sorted(entries)
    Path(path).write_text(
        json.dumps(
            {"version": BASELINE_VERSION, "entries": entries}, indent=1
        )
        + "\n"
    )
    return len(entries)
