"""harlint baseline: committed suppression file for pre-existing debt.

The gate fails on any NON-baselined finding, so new violations can
never land — while debt that predates a rule is recorded (reviewed,
visible, diffable in the PR that admits it) instead of blocking the
gate forever.  Entries are line-number independent (``Finding.key``):
``rule|path|symbol|normalized-snippet`` — moving code around does not
churn the file; changing or fixing the flagged line retires the entry.

The committed file is expected to stay near-empty: every rule ships
with its real findings fixed at introduction time, and
``har lint --update-baseline`` exists for the rare reviewed exception,
not as a pressure valve.
"""

from __future__ import annotations

import json
from pathlib import Path

from har_tpu.analyze.core import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = "harlint_baseline.json"


def load_baseline(path: Path) -> set[str]:
    """The committed suppression keys (empty set when the file does not
    exist — a missing baseline suppresses nothing)."""
    try:
        data = json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return set()
    return set(data.get("entries") or [])


def apply_baseline(
    findings: list[Finding], baseline: set[str]
) -> tuple[list[Finding], int]:
    """Split findings into (fresh, n_baselined)."""
    fresh = [f for f in findings if f.key() not in baseline]
    return fresh, len(findings) - len(fresh)


def entry_path(entry: str) -> str:
    """The repo-relative file a baseline entry refers to (field 2 of
    ``rule|path|symbol|snippet``)."""
    parts = entry.split("|", 2)
    return parts[1] if len(parts) > 1 else ""


def write_baseline(
    path: Path,
    findings: list[Finding],
    linted_files: set[str] | None = None,
) -> int:
    """Rewrite the baseline to the given findings' keys (sorted,
    deduplicated).  ``linted_files`` scopes the rewrite: existing
    entries for files OUTSIDE that set are preserved — an
    ``--update-baseline`` run over a path subset must never silently
    retire reviewed suppressions it did not re-examine (None = a
    full-fileset run, which owns every entry).  Returns the entry
    count."""
    entries = {f.key() for f in findings}
    if linted_files is not None:
        entries |= {
            e
            for e in load_baseline(path)
            if entry_path(e) not in linted_files
        }
    entries = sorted(entries)
    Path(path).write_text(
        json.dumps(
            {"version": BASELINE_VERSION, "entries": entries}, indent=1
        )
        + "\n"
    )
    return len(entries)
