"""HL006 — jit-purity: everything a traced body can reach must be pure.

``jax.jit`` / ``jax.shard_map`` / ``lax.scan`` (and ``nn.scan``) trace
a function ONCE per input shape and replay the captured computation
thereafter.  Any side effect in the traced closure therefore fires at
trace time only — the classic silent-staleness bugs:

  - **mutating closed-over state** (``self.hits += 1``, appending to a
    captured list, ``global``/``nonlocal`` writes): happens once per
    compile, not once per step; counters silently freeze, caches
    silently corrupt;
  - **wall-clock reads** (``time.time()``, ``perf_counter()``): the
    value is constant-folded into the program at trace time — every
    subsequent step sees the trace-time clock;
  - **print / logging**: executes during trace only, then vanishes —
    the debugging trap that makes people think their step "runs once";
  - **host fetches** (``np.asarray`` on a tracer, ``.item()``,
    ``block_until_ready``): a tracer error waiting to happen or a
    silent constant-fold — the same detectors as HL001
    (``hotpath.scan_syncs``), applied through the call graph.

The surface is the call-graph reachability closure
(``analyze.callgraph``) from every traced root: jit-decorated or
jit-by-name-wrapped functions, and functions handed to ``shard_map`` /
``scan`` by name.  DIRECT jit bodies' syncs stay HL001's findings
(continuity with PR 6); this rule owns the purity checks everywhere in
the closure, and the sync detectors for everything deeper than the
direct body — which is exactly the gap the hand-listed v1 surface had.

The DrJAX-style cluster primitives (arXiv 2403.07128, PAPERS.md) and
the ROADMAP's shared train/serve sharding layer both grow this
pure-functional surface; this rule is their static guard.
"""

from __future__ import annotations

import ast

from har_tpu.analyze.core import FileContext, Finding, Rule, call_name
from har_tpu.analyze.hotpath import (
    is_jit_marked,
    scan_syncs,
    walk_own,
    wrapped_def_nodes,
)

_TRACE_WRAPPERS = {"shard_map", "scan"}  # jax.shard_map / lax.scan / nn.scan
_CLOCK_ATTRS = {"time", "monotonic", "perf_counter", "process_time"}
_LOG_RECEIVERS = {"logging", "log", "logger", "_log", "_logger"}
# a receiver merely NAMED `log` may be a list — only the logging verbs
# route to the logging finding; `.append` et al. stay container mutation
_LOG_METHODS = {
    "debug", "info", "warning", "warn", "error", "exception",
    "critical", "log",
}
# conservative mutating-method set for closed-over containers; `update`
# is deliberately absent (optax's `optimizer.update` is pure and
# ubiquitous inside traced bodies)
_MUTATORS = {
    "append", "extend", "insert", "pop", "popleft", "clear",
    "remove", "discard", "add", "setdefault",
}


def _bound_names(t):
    """Names a binding target BINDS — the Name/Tuple/Starred structure
    only.  The base of a Subscript/Attribute target (``d[k] = v``,
    ``obj.x = v``) is a MUTATION of an existing object, not a binding:
    walking into it would classify a closed-over dict as local and mask
    the very write this rule exists to flag."""
    if isinstance(t, ast.Name):
        yield t.id
    elif isinstance(t, ast.Starred):
        yield from _bound_names(t.value)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _bound_names(e)


class JitPurityRule(Rule):
    rule_id = "HL006"
    title = "jit-purity"

    def finalize(self, ctxs: list[FileContext]) -> list[Finding]:
        from har_tpu.analyze.core import Project

        project = self.project or Project(ctxs)
        graph = project.callgraph

        roots, direct_jit = [], set()
        for ctx in ctxs:
            jit_nodes = wrapped_def_nodes(ctx.tree, {"jit"})
            traced_nodes = wrapped_def_nodes(ctx.tree, _TRACE_WRAPPERS)
            for fi in graph.functions.values():
                if fi.rel != ctx.rel:
                    continue
                jit_root = (
                    is_jit_marked(fi.node) or id(fi.node) in jit_nodes
                )
                if jit_root:
                    # HL001 scans these bodies' syncs (full walk,
                    # nested defs included) — remember the whole
                    # subtree so the sync pass below skips it
                    direct_jit.add(fi.key)
                    for g in graph.nested_under(fi):
                        direct_jit.add(g.key)
                if jit_root or id(fi.node) in traced_nodes:
                    roots.append(fi)

        reach = graph.reachable(roots)
        findings: list[Finding] = []
        for key, (parent, root) in reach.items():
            fi = graph.functions[key]
            if fi.ctx.support:
                # subset run: traced roots and call edges in support
                # files still shape the closure, but only requested
                # files' bodies are scanned
                continue
            chain = graph.chain(reach, key)
            note = (
                ""
                if len(chain) == 1
                else (
                    "  [traced via "
                    + " -> ".join(f"`{q}`" for q in chain)
                    + "]"
                )
            )
            findings.extend(self._purity_scan(fi, note))
            if key not in direct_jit:
                findings.extend(
                    scan_syncs(
                        self.rule_id, fi.ctx, fi.qual, fi.node, "jit",
                        "inside a traced (jit/shard_map/scan) closure",
                        own_statements_only=True,
                        reach_note=note,
                    )
                )
        return findings

    # ------------------------------------------------------------ purity

    def _purity_scan(self, fi, note: str) -> list[Finding]:
        ctx, node = fi.ctx, fi.node
        # statement-bound names: assignments, loop targets, withitems,
        # comprehension vars, nested def/class names.  Containers BOUND
        # here are this trace's own values — mutating them is fine;
        # containers that arrive as parameters are the caller's, and
        # mutating those is the same trace-time-only trap as a closure.
        bound: set[str] = set()
        for sub in walk_own(node):
            targets = []
            if isinstance(sub, ast.Assign):
                targets = sub.targets
            elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
                targets = [sub.target]
            elif isinstance(sub, ast.For):
                targets = [sub.target]
            elif isinstance(sub, ast.withitem) and sub.optional_vars:
                targets = [sub.optional_vars]
            elif isinstance(sub, ast.comprehension):
                targets = [sub.target]
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                bound.add(sub.name)
                continue
            for t in targets:
                bound.update(_bound_names(t))

        out: list[Finding] = []

        def flag(sub, msg):
            # no in-rule disable= check: run_rules' _apply_disable owns
            # the generic suppression for every rule, so HL006 gets the
            # same placement semantics (finding line or the comment-only
            # line above) as the other seven
            line = getattr(sub, "lineno", 1)
            out.append(
                Finding(
                    rule=self.rule_id,
                    path=ctx.rel,
                    line=line,
                    message=msg + note,
                    symbol=fi.qual,
                    snippet=ctx.snippet(line),
                )
            )

        for sub in walk_own(node):
            if isinstance(sub, (ast.Global, ast.Nonlocal)):
                flag(
                    sub,
                    f"`{type(sub).__name__.lower()}` write inside a traced "
                    "body — the mutation fires at trace time only "
                    "(once per compiled shape, not once per step); "
                    "thread the value through the carry/return instead",
                )
            elif isinstance(sub, (ast.Assign, ast.AugAssign)):
                targets = (
                    sub.targets if isinstance(sub, ast.Assign)
                    else [sub.target]
                )
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        flag(
                            sub,
                            f"assignment to `self.{t.attr}` inside a "
                            "traced body — jit replays the captured "
                            "computation, so the attribute updates at "
                            "trace time only (a silently-frozen "
                            "counter/cache); mutate outside the traced "
                            "fn or return the value",
                        )
                    elif isinstance(t, ast.Subscript):
                        base = t.value
                        while isinstance(base, (ast.Subscript,
                                                ast.Attribute)):
                            base = base.value
                        if (
                            isinstance(base, ast.Name)
                            and base.id not in bound
                        ):
                            flag(
                                sub,
                                f"subscript write into closed-over "
                                f"`{base.id}` inside a traced body — "
                                "in-place mutation of captured state "
                                "fires at trace time only (tracers are "
                                "immutable; a numpy closure silently "
                                "corrupts); use `.at[...].set(...)` on "
                                "a carried array instead",
                            )
            elif isinstance(sub, ast.Call):
                name = call_name(sub)
                f = sub.func
                if isinstance(f, ast.Name) and f.id == "print":
                    flag(
                        sub,
                        "`print(...)` inside a traced body executes at "
                        "trace time only (once per compiled shape) — "
                        "use `jax.debug.print` for runtime values, or "
                        "log outside the traced fn",
                    )
                elif (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "time"
                    and f.attr in _CLOCK_ATTRS
                ):
                    flag(
                        sub,
                        f"`time.{f.attr}()` inside a traced body is "
                        "constant-folded at trace time — every replayed "
                        "step sees the trace-time clock; measure "
                        "outside the traced fn",
                    )
                elif (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id in _LOG_RECEIVERS
                    and f.attr in _LOG_METHODS
                ):
                    flag(
                        sub,
                        f"`{f.value.id}.{f.attr}(...)` inside a traced "
                        "body executes at trace time only — log outside "
                        "the traced fn",
                    )
                elif (
                    isinstance(f, ast.Attribute)
                    and name in _MUTATORS
                    and isinstance(f.value, ast.Name)
                    and f.value.id not in bound
                    and f.value.id != "self"
                ):
                    flag(
                        sub,
                        f"`.{name}(...)` on closed-over `{f.value.id}` "
                        "inside a traced body — container mutation "
                        "fires at trace time only; thread the value "
                        "through the carry/return instead",
                    )
        return out
