"""HL003 — journal/replay exhaustiveness: record types, replay
handlers and chaos kill points must stay in bijection.

Three closed sets keep the durability layer honest, and each has a
writer side and a consumer side that live in DIFFERENT files — exactly
the shape that drifts:

  1. every journal record type written anywhere in the fleet stack
     (``_jappend({"t": "push", ...})`` in the engine,
     ``journal.append({"t": "adapt", ...})`` in the adaptation
     controller, ``ship_journal.append({"t": "ship_chunk", ...})`` in
     the journal-ship receiver) must have a replay handler in a
     replay module — ``serve/recover.py`` for fleet records, ``serve/
     net/ship.py`` for the ship log's own records (``t == "push"`` /
     ``t == "ship_chunk"`` dispatch) — a recordless handler is dead
     code, a handlerless record is data a crash writes and recovery
     silently drops (the replay loops tolerate unknown types BY DESIGN
     for forward compat, which is precisely why the same-version check
     must be static);
  2. every replay handler must correspond to a written record type;
  3. the kill-point names the chaos matrix enumerates
     (``KILL_POINTS`` + ``ENGINE_KILL_POINTS`` + the cluster, ship,
     replication-tail and gateway tuples in ``serve/chaos.py``) must
     biject with the ``chaos_point("...")`` / ``_chaos("...")`` call
     sites across the stack, and every matrix point needs a
     ``_DEFAULT_AT`` occurrence calibration — a stage boundary without
     a matrix entry is a crash window no chaos run ever exercises;
  4. the gateway pair's ``{"moved": leader_addr}`` receipt has a writer
     side (the standby/drain refusal dict in ``serve/net``) and a
     consumer side (the HA client's ``"moved" in resp`` redirect) —
     losing either turns a declared refusal into a silent hangup (no
     writer) or an unfollowable one (no handler), so the pair is pinned
     in both directions like the record/handler bijection.
"""

from __future__ import annotations

import ast

from har_tpu.analyze.core import FileContext, Finding, Rule, call_name

_CHAOS_NAMES = {"chaos_point", "_chaos"}


def _is_journal_write(node: ast.Call) -> bool:
    """True for the two real journaling spellings: the engine's
    ``self._jappend(...)`` wrapper, and ``<journal>.append(...)`` where
    the receiver's terminal name names a journal (``journal.append``,
    ``self._journal.append``).  A bare ``something.append`` is the
    universal LIST method — an ordinary list of dicts that happen to
    carry a "t" key must never read as a phantom record type."""
    name = call_name(node)
    if name == "_jappend":
        return True
    if name != "append" or not isinstance(node.func, ast.Attribute):
        return False
    recv = node.func.value
    terminal = (
        recv.id if isinstance(recv, ast.Name)
        else recv.attr if isinstance(recv, ast.Attribute)
        else ""
    )
    return "journal" in terminal.lower()


def _record_writes(ctx: FileContext) -> list[tuple[str, ast.AST]]:
    """``("push", node)`` for every journaled dict literal with a
    constant "t" key passed to an append-style call."""
    out = []
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Call)
            and _is_journal_write(node)
            and node.args
            and isinstance(node.args[0], ast.Dict)
        ):
            continue
        d = node.args[0]
        for k, v in zip(d.keys, d.values):
            if (
                isinstance(k, ast.Constant)
                and k.value == "t"
                and isinstance(v, ast.Constant)
                and isinstance(v.value, str)
            ):
                out.append((v.value, node))
    return out


def _replay_handlers(ctx: FileContext) -> list[tuple[str, ast.AST]]:
    """``t == "push"``-style comparisons in the replay dispatch."""
    out = []
    for node in ast.walk(ctx.tree):
        if not (
            isinstance(node, ast.Compare)
            and isinstance(node.left, ast.Name)
            and node.left.id == "t"
            and len(node.ops) == 1
            and isinstance(node.ops[0], ast.Eq)
            and isinstance(node.comparators[0], ast.Constant)
            and isinstance(node.comparators[0].value, str)
        ):
            continue
        out.append((node.comparators[0].value, node))
    return out


def _string_tuple(tree: ast.Module, name: str) -> tuple[set[str], ast.AST | None]:
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets
            )
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            return (
                {
                    e.value
                    for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                },
                node,
            )
    return set(), None


def _dict_keys(tree: ast.Module, name: str) -> set[str]:
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets
            )
            and isinstance(node.value, ast.Dict)
        ):
            return {
                k.value
                for k in node.value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            }
    return set()


class JournalExhaustivenessRule(Rule):
    rule_id = "HL003"
    title = "journal/replay exhaustiveness"

    def finalize(self, ctxs: list[FileContext]) -> list[Finding]:
        findings: list[Finding] = []
        written: dict[str, tuple[FileContext, ast.AST]] = {}
        handled: dict[str, tuple[FileContext, ast.AST]] = {}
        chaos_calls: dict[str, tuple[FileContext, ast.AST]] = {}
        chaos_ctx = None
        declared: set[str] = set()
        declared_node = None
        default_at: set[str] = set()
        matrix_points: set[str] = set()
        retired: set[str] = set()
        retired_node = None
        recover_ctx = None
        moved_writers: list[tuple[FileContext, ast.AST]] = []
        moved_handlers: list[tuple[FileContext, ast.AST]] = []

        for ctx in ctxs:
            base = ctx.rel.rsplit("/", 1)[-1]
            for t, node in _record_writes(ctx):
                written.setdefault(t, (ctx, node))
            # two replay modules: the fleet suffix replay (recover.py)
            # and the ship log's resume replay (net/ship.py) — the ship
            # record family's handlers live beside their writer, and a
            # deleted ship_chunk handler must flag exactly like a
            # deleted fleet handler
            if base in ("recover.py", "ship.py"):
                for t, node in _replay_handlers(ctx):
                    handled.setdefault(t, (ctx, node))
            if base == "recover.py":
                # record types whose writer was superseded (per-event
                # `ack` → group-committed `acks`) but whose journals
                # are still in the field: the handler stays forever,
                # declared — and the declaration is itself pinned both
                # ways below
                recover_ctx = ctx
                retired, retired_node = _string_tuple(
                    ctx.tree, "RETIRED_RECORD_TYPES"
                )
            if base == "chaos.py":
                chaos_ctx = ctx
                kp, kp_node = _string_tuple(ctx.tree, "KILL_POINTS")
                ekp, _ = _string_tuple(ctx.tree, "ENGINE_KILL_POINTS")
                # the cluster control plane's migration points
                # (mid_migration / mid_handoff) join both bijections:
                # they need chaos_point/_chaos call sites AND a
                # _DEFAULT_AT occurrence calibration like any matrix
                # point — a hand-off stage boundary without a matrix
                # entry is a crash window no chaos run exercises
                ckp, _ = _string_tuple(ctx.tree, "CLUSTER_KILL_POINTS")
                # the journal-ship transfer's stage boundaries
                # (mid_ship_send / mid_ship_recv / post_ship_pre_drain)
                # join the same way: dropping one from the declared
                # tuple orphans its call site, deleting a call site
                # orphans the matrix entry
                skp, _ = _string_tuple(ctx.tree, "SHIP_KILL_POINTS")
                # the continuous-replication tail's stage boundaries
                # (mid_tail_recv / mid_tail_remanifest /
                # post_tail_verify, fired inside net/tail.py's pull and
                # finalize loops, run by run_tail_kill_point): same
                # bijection — a tail boundary outside the matrix is a
                # standby-death window no chaos run exercises
                tkp, _ = _string_tuple(ctx.tree, "TAIL_KILL_POINTS")
                # the ingest gateway pair's stage boundaries
                # (mid_frame_recv / post_accept_pre_forward /
                # mid_lease_handoff, fired inside net/gateway.py's
                # admission and drain paths, run by
                # run_gateway_kill_point): same bijection — an edge
                # boundary outside the matrix is a gateway-death
                # window no chaos run exercises
                gkp, _ = _string_tuple(ctx.tree, "GATEWAY_KILL_POINTS")
                declared = kp | ekp | ckp | skp | tkp | gkp
                matrix_points = kp | ckp | skp | tkp | gkp
                declared_node = kp_node
                default_at = _dict_keys(ctx.tree, "_DEFAULT_AT")
            for node in ast.walk(ctx.tree):
                if (
                    isinstance(node, ast.Call)
                    and call_name(node) in _CHAOS_NAMES
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    chaos_calls.setdefault(node.args[0].value, (ctx, node))
            # the moved-receipt bijection lives entirely in the
            # transport package: writers are `{"moved": ...}` dict
            # literals, consumers are `"moved" in resp` membership
            # tests or `.get("moved")` reads
            if "serve/net/" in ctx.rel:
                for node in ast.walk(ctx.tree):
                    if isinstance(node, ast.Dict) and any(
                        isinstance(k, ast.Constant) and k.value == "moved"
                        for k in node.keys
                    ):
                        moved_writers.append((ctx, node))
                    elif (
                        isinstance(node, ast.Compare)
                        and isinstance(node.left, ast.Constant)
                        and node.left.value == "moved"
                        and len(node.ops) == 1
                        and isinstance(node.ops[0], ast.In)
                    ):
                        moved_handlers.append((ctx, node))
                    elif (
                        isinstance(node, ast.Call)
                        and call_name(node) == "get"
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and node.args[0].value == "moved"
                    ):
                        moved_handlers.append((ctx, node))

        # record types <-> replay handlers, both directions
        recover_seen = bool(handled) or any(
            c.rel.endswith("recover.py") for c in ctxs
        )
        if recover_seen:
            for t in sorted(set(written) - set(handled)):
                ctx, node = written[t]
                findings.append(
                    ctx.finding(
                        self.rule_id,
                        node,
                        f"journal record type {t!r} is written here but "
                        "has no replay handler in serve/recover.py — a "
                        "crash would silently drop it (the replay loop "
                        "skips unknown types for forward compat; "
                        "same-version exhaustiveness is this check)",
                    )
                )
            for t in sorted(set(handled) - set(written) - retired):
                ctx, node = handled[t]
                findings.append(
                    ctx.finding(
                        self.rule_id,
                        node,
                        f"replay handler for record type {t!r} matches "
                        "no journaled write in the fleet stack — dead "
                        "recovery code, or the writer was removed "
                        "without its handler (a deliberately kept "
                        "back-compat handler belongs in "
                        "RETIRED_RECORD_TYPES)",
                    )
                )
            # the retirement declaration is pinned both ways: a type
            # with a live writer must not hide behind it, and a retired
            # type that loses its handler breaks every journal still in
            # the field
            for t in sorted(retired & set(written)):
                ctx, node = written[t]
                findings.append(
                    ctx.finding(
                        self.rule_id,
                        node,
                        f"record type {t!r} is declared retired in "
                        "serve/recover.py but is still written here — "
                        "a stale retirement hides a real bijection "
                        "break; drop it from RETIRED_RECORD_TYPES",
                    )
                )
            for t in sorted(retired - set(handled)):
                findings.append(
                    recover_ctx.finding(
                        self.rule_id,
                        retired_node or recover_ctx.tree,
                        f"retired record type {t!r} has no replay "
                        "handler — old journals carrying it would "
                        "silently lose acked state on restore; retired "
                        "types keep their handlers forever",
                    )
                )

        # kill points <-> chaos_point call sites, plus _DEFAULT_AT
        if chaos_ctx is not None and declared:
            for p in sorted(set(chaos_calls) - declared):
                ctx, node = chaos_calls[p]
                findings.append(
                    ctx.finding(
                        self.rule_id,
                        node,
                        f"chaos point {p!r} is instrumented here but "
                        "absent from the chaos matrix (KILL_POINTS / "
                        "ENGINE_KILL_POINTS in serve/chaos.py) — a "
                        "crash window no chaos run exercises",
                    )
                )
            for p in sorted(declared - set(chaos_calls)):
                findings.append(
                    chaos_ctx.finding(
                        self.rule_id,
                        declared_node or chaos_ctx.tree,
                        f"kill point {p!r} is declared in the chaos "
                        "matrix but no `chaos_point(...)`/`_chaos(...)` "
                        "call site exists — the matrix would report it "
                        "as 'never fired'",
                    )
                )
            for p in sorted(matrix_points - default_at):
                findings.append(
                    chaos_ctx.finding(
                        self.rule_id,
                        declared_node or chaos_ctx.tree,
                        f"matrix kill point {p!r} has no _DEFAULT_AT "
                        "occurrence calibration",
                    )
                )

        # the moved receipt, both directions: a transport package that
        # only writes (or only consumes) the receipt has lost half of
        # the declared-failover contract
        if moved_writers and not moved_handlers:
            ctx, node = moved_writers[0]
            findings.append(
                ctx.finding(
                    self.rule_id,
                    node,
                    'a {"moved": ...} receipt is written here but no '
                    'client-side handler ("moved" in resp / '
                    '.get("moved")) exists in serve/net — the '
                    "standby's declared refusal would be unfollowable "
                    "and every failover would strand its clients",
                )
            )
        if moved_handlers and not moved_writers:
            ctx, node = moved_handlers[0]
            findings.append(
                ctx.finding(
                    self.rule_id,
                    node,
                    'a "moved"-receipt handler exists here but nothing '
                    'in serve/net writes a {"moved": ...} refusal — '
                    "dead redirect code, or the standby's declared "
                    "refusal was replaced by a silent hangup",
                )
            )
        return findings
