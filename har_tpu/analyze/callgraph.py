"""Project-wide call graph: the cross-module layer under harlint v2.

PR 6's HL001 guarded the dispatch hot path with a hand-listed name
surface (``{launch, _launch_batch, pad, pad_size, gather, _place}``)
closed over same-class ``self.`` calls — which means a host sync TWO
calls below ``launch`` (say, inside a scorer constructor reached
through ``_get_scorer`` → ``make_scorer``) sailed through unexamined.
The Spark-ML perf study (arXiv 1612.01437) says hidden host /
serialization stalls are exactly what dominates distributed-ML
latency, so the guarded surface must be *computed*, not curated.

This module computes it.  From the lint fileset's parsed ASTs it
builds:

  - a **function table** — every def/method across the fileset, keyed
    ``(repo-relative-path, dotted-qualname)``, with its true enclosing
    class (nested defs record their class, not their parent function);
  - an **import map** per module — ``from har_tpu.serve.dispatch
    import make_scorer`` and ``import har_tpu.serving as s`` both
    resolve to nodes in other files (one re-export hop through
    ``__init__`` is followed);
  - a **class table** with resolved bases, so method lookup walks the
    MRO *and* the overriding subclasses (a ``self._place()`` inside
    ``DeviceScorer.launch`` reaches ``ShardedScorer._place`` too —
    the receiver may be the subclass);
  - a small **type-inference lattice**: the candidate project classes
    of an expression.  ``self._arena = StagingArena(...)`` types the
    attribute; ``scorer = self._get_scorer()`` follows the method's
    ``return`` expressions into ``make_scorer`` and unions the classes
    it can construct — so ``scorer.pad(...)`` resolves to all three
    scorer families.  The lattice is deliberately an over-approximation
    (a lint wants reachability to be sound-ish, not minimal) and gives
    up — resolving to nothing — on receivers it cannot type.

``reachable(roots)`` is then a plain BFS that also pulls in functions
*nested* under a reached function (closures handed to ``retry_call``
or ``lax.scan`` execute as part of their parent).  Rules consume the
graph through ``core.Project`` so one build serves HL001 and HL006.

Pure stdlib (``ast`` only), like everything in ``har_tpu.analyze`` —
the release gate runs this without a jax backend, inside the 5 s lint
budget the gate enforces.
"""

from __future__ import annotations

import ast

from har_tpu.analyze.core import FileContext, call_name

# Expression-type recursion cap (cycles give up, not hang).  The
# flagship chain — `scorer = self._get_scorer()` -> return self._scorer
# -> attr expr `make_scorer(...)` -> return `DeviceScorer()` — costs 7
# levels; 16 leaves headroom for one more indirection hop without
# letting a pathological chain walk forever.
_MAX_DEPTH = 16


class FuncInfo:
    """One function/method definition in the fileset."""

    __slots__ = ("ctx", "rel", "qual", "name", "cls", "node", "parent_qual")

    def __init__(self, ctx, qual, name, cls, node, parent_qual):
        self.ctx = ctx
        self.rel = ctx.rel
        self.qual = qual
        self.name = name
        self.cls = cls  # enclosing ClassInfo key (rel, class qual) or None
        self.node = node
        self.parent_qual = parent_qual  # enclosing function qual or None

    @property
    def key(self):
        return (self.rel, self.qual)

    def __repr__(self):  # debugging aid only
        return f"<fn {self.rel}::{self.qual}>"


class ClassInfo:
    """One class definition: methods, raw base expressions, attr writes."""

    __slots__ = ("ctx", "rel", "qual", "name", "node", "base_exprs",
                 "methods", "attr_exprs")

    def __init__(self, ctx, qual, node):
        self.ctx = ctx
        self.rel = ctx.rel
        self.qual = qual
        self.name = node.name
        self.node = node
        self.base_exprs = list(node.bases)
        self.methods: dict[str, FuncInfo] = {}
        # attr name -> [(FuncInfo of the assigning method, value expr)]
        self.attr_exprs: dict[str, list] = {}

    @property
    def key(self):
        return (self.rel, self.qual)


def _module_name(rel: str) -> str:
    """repo-relative path -> dotted module (har_tpu/serve/__init__.py
    -> har_tpu.serve)."""
    mod = rel[:-3] if rel.endswith(".py") else rel
    parts = mod.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class _Module:
    __slots__ = ("ctx", "rel", "functions", "classes", "imports", "consts")

    def __init__(self, ctx):
        self.ctx = ctx
        self.rel = ctx.rel
        self.functions: dict[str, FuncInfo] = {}  # top-level name -> info
        self.classes: dict[str, ClassInfo] = {}   # top-level name -> info
        # alias -> ("mod", dotted) | ("sym", dotted, original_name)
        self.imports: dict[str, tuple] = {}
        self.consts: dict[str, str] = {}  # module-level string constants


class CallGraph:
    """Functions, classes, imports and resolved call edges for a fileset."""

    def __init__(self, ctxs: list[FileContext]):
        self.functions: dict[tuple, FuncInfo] = {}
        self.classes: dict[tuple, ClassInfo] = {}
        self.modules: dict[str, _Module] = {}       # dotted name -> module
        self._mod_by_rel: dict[str, _Module] = {}
        self._subclasses: dict[tuple, list[ClassInfo]] = {}
        self._edges: dict[tuple, list] = {}         # fn key -> [(call, [FuncInfo])]
        self._locals: dict[tuple, dict] = {}        # fn key -> {name: [exprs]}
        self._params: dict[tuple, set] = {}         # fn key -> param names
        self._returns: dict[tuple, object] = {}     # fn key -> memoized types
        self._capped = False  # a depth-capped computation is incomplete
        for ctx in ctxs:
            self._index_module(ctx)
        self._resolve_bases()

    # ------------------------------------------------------------ build

    def _index_module(self, ctx: FileContext) -> None:
        mod = _Module(ctx)
        dotted = _module_name(ctx.rel)
        self.modules[dotted] = mod
        self._mod_by_rel[ctx.rel] = mod

        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Constant
            ) and isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        mod.consts[t.id] = node.value.value

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = (
                        ("mod", a.name) if a.asname
                        else ("mod", a.name.split(".")[0])
                    )
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:  # relative: resolve against this package
                    pkg = dotted.split(".")
                    if not ctx.rel.endswith("__init__.py"):
                        pkg = pkg[:-1]  # the module's own leaf name
                    pkg = pkg[: len(pkg) - (node.level - 1)]
                    base = ".".join(pkg + ([node.module] if node.module else []))
                for a in node.names:
                    mod.imports[a.asname or a.name] = ("sym", base, a.name)

        # functions + classes, with true class context (class frames only)
        def visit(node, qual_stack, cls_key, fn_qual):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = ".".join(qual_stack + [child.name])
                    fi = FuncInfo(ctx, qual, child.name, cls_key, child,
                                  fn_qual)
                    self.functions[fi.key] = fi
                    if cls_key is not None and fn_qual is None:
                        self.classes[cls_key].methods.setdefault(
                            child.name, fi
                        )
                    if fn_qual is None and cls_key is None:
                        mod.functions.setdefault(child.name, fi)
                    visit(child, qual_stack + [child.name], cls_key, qual)
                elif isinstance(child, ast.ClassDef):
                    cqual = ".".join(qual_stack + [child.name])
                    ci = ClassInfo(ctx, cqual, child)
                    self.classes[ci.key] = ci
                    if fn_qual is None and not qual_stack:
                        mod.classes.setdefault(child.name, ci)
                    visit(child, qual_stack + [child.name], ci.key, None)
                else:
                    visit(child, qual_stack, cls_key, fn_qual)

        visit(ctx.tree, [], None, None)

        # self.<attr> = <expr> writes, per class
        for fi in list(self.functions.values()):
            if fi.rel != ctx.rel or fi.cls is None:
                continue
            ci = self.classes[fi.cls]
            for sub in ast.walk(fi.node):
                if not isinstance(sub, ast.Assign):
                    continue
                for t in sub.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        ci.attr_exprs.setdefault(t.attr, []).append(
                            (fi, sub.value)
                        )

    def _resolve_bases(self) -> None:
        for ci in self.classes.values():
            for b in ci.base_exprs:
                base = self._resolve_class_expr(ci.ctx.rel, b)
                if base is not None:
                    self._subclasses.setdefault(base.key, []).append(ci)

    def _resolve_class_expr(self, rel: str, expr) -> ClassInfo | None:
        if isinstance(expr, ast.Name):
            got = self.resolve_symbol(rel, expr.id)
            if isinstance(got, ClassInfo):
                return got
        elif isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ):
            got = self.resolve_symbol(rel, expr.value.id)
            if isinstance(got, _Module):
                return got.classes.get(expr.attr)
        return None

    # -------------------------------------------------------- resolution

    def resolve_symbol(self, rel: str, name: str, _seen=None):
        """A top-level name in module ``rel`` -> FuncInfo | ClassInfo |
        _Module | str-constant | None.  Follows one import hop (plus
        one re-export hop through a package ``__init__``)."""
        mod = self._mod_by_rel.get(rel)
        if mod is None:
            return None
        if name in mod.functions:
            return mod.functions[name]
        if name in mod.classes:
            return mod.classes[name]
        if name in mod.consts:
            return mod.consts[name]
        target = mod.imports.get(name)
        if target is None:
            return None
        _seen = _seen or set()
        if (rel, name) in _seen:
            return None
        _seen.add((rel, name))
        if target[0] == "mod":
            return self.modules.get(target[1])
        dotted, orig = target[1], target[2]
        tmod = self.modules.get(dotted)
        if tmod is None:
            # `from har_tpu.serve import engine` — the symbol may be a
            # submodule rather than a name inside __init__
            return self.modules.get(f"{dotted}.{orig}")
        got = self.resolve_symbol(tmod.rel, orig, _seen)
        if got is None:
            return self.modules.get(f"{dotted}.{orig}")
        return got

    def resolve_const(self, rel: str, name: str) -> str | None:
        """Module-level string constant by name, following imports —
        HL007 resolves ``P(None, TP_AXIS)`` through this."""
        got = self.resolve_symbol(rel, name)
        return got if isinstance(got, str) else None

    # MRO-ish method lookup: own class, then bases depth-first; with
    # virtual=True the overriding subclasses join (the receiver may be
    # any subclass instance)
    def lookup_method(
        self, ci: ClassInfo, name: str, virtual: bool = True
    ) -> list[FuncInfo]:
        out, seen = [], set()

        def mro(c: ClassInfo):
            if c.key in seen:
                return None
            seen.add(c.key)
            if name in c.methods:
                return c.methods[name]
            for b in c.base_exprs:
                base = self._resolve_class_expr(c.ctx.rel, b)
                if base is not None:
                    got = mro(base)
                    if got is not None:
                        return got
            return None

        got = mro(ci)
        if got is not None:
            out.append(got)
        if virtual:
            stack, visited = [ci], set()
            while stack:
                c = stack.pop()
                if c.key in visited:
                    continue
                visited.add(c.key)
                for sub in self._subclasses.get(c.key, ()):
                    if name in sub.methods:
                        out.append(sub.methods[name])
                    stack.append(sub)
        uniq, keys = [], set()
        for fi in out:
            if fi.key not in keys:
                keys.add(fi.key)
                uniq.append(fi)
        return uniq

    # ---------------------------------------------------- type inference

    def _fn_locals(self, fi: FuncInfo) -> tuple[dict, set]:
        if fi.key not in self._locals:
            assigns: dict[str, list] = {}
            for sub in ast.walk(fi.node):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            assigns.setdefault(t.id, []).append(sub.value)
                elif isinstance(sub, ast.AnnAssign) and isinstance(
                    sub.target, ast.Name
                ) and sub.value is not None:
                    assigns.setdefault(sub.target.id, []).append(sub.value)
            a = fi.node.args
            params = {
                p.arg
                for p in (
                    a.posonlyargs + a.args + a.kwonlyargs
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])
                )
            }
            self._locals[fi.key] = assigns
            self._params[fi.key] = params
        return self._locals[fi.key], self._params[fi.key]

    def expr_types(self, fi: FuncInfo, expr, depth: int = 0) -> set:
        """Candidate project-class keys an expression may evaluate to."""
        if depth > _MAX_DEPTH:
            self._capped = True  # truncated, not resolved-to-nothing
            return set()
        if expr is None:
            return set()
        if isinstance(expr, ast.Call):
            out = set()
            for target in self._resolve_callee(fi, expr, depth + 1):
                if isinstance(target, ClassInfo):
                    out.add(target.key)
                elif isinstance(target, FuncInfo):
                    out |= self.return_types(target, depth + 1)
            return out
        if isinstance(expr, ast.Name):
            # own locals, then each enclosing function's (closure
            # capture: `scorer` inside `_attempt` is `_launch_batch`'s
            # local), then module scope
            holder = fi
            while holder is not None:
                assigns, params = self._fn_locals(holder)
                if expr.id in assigns:
                    out = set()
                    for val in assigns[expr.id]:
                        if val is not expr:
                            out |= self.expr_types(holder, val, depth + 1)
                    return out
                if expr.id in params:
                    return set()
                holder = (
                    self.functions.get((holder.rel, holder.parent_qual))
                    if holder.parent_qual is not None
                    else None
                )
            got = self.resolve_symbol(fi.rel, expr.id)
            return {got.key} if isinstance(got, ClassInfo) else set()
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                if fi.cls is not None:
                    return self._attr_types(
                        self.classes[fi.cls], expr.attr, depth
                    )
                return set()
            out = set()
            for ckey in self.expr_types(fi, expr.value, depth + 1):
                out |= self._attr_types(self.classes[ckey], expr.attr, depth)
            return out
        if isinstance(expr, ast.BoolOp):
            out = set()
            for v in expr.values:
                out |= self.expr_types(fi, v, depth + 1)
            return out
        if isinstance(expr, ast.IfExp):
            return self.expr_types(fi, expr.body, depth + 1) | self.expr_types(
                fi, expr.orelse, depth + 1
            )
        return set()

    def _attr_types(self, ci: ClassInfo, attr: str, depth: int) -> set:
        out, stack, seen = set(), [ci], set()
        while stack:  # own class + bases contribute attr assignments
            c = stack.pop()
            if c.key in seen:
                continue
            seen.add(c.key)
            for owner_fi, val in c.attr_exprs.get(attr, ()):
                out |= self.expr_types(owner_fi, val, depth + 1)
            for b in c.base_exprs:
                base = self._resolve_class_expr(c.ctx.rel, b)
                if base is not None:
                    stack.append(base)
        return out

    def return_types(self, fi: FuncInfo, depth: int = 0) -> set:
        memo = self._returns.get(fi.key)
        if memo == "busy":  # recursion cycle: give up on this branch
            return set()
        if memo is not None:
            return memo
        self._returns[fi.key] = "busy"
        outer_capped = self._capped
        self._capped = False
        out = set()
        for sub in ast.walk(fi.node):
            if isinstance(sub, ast.Return) and sub.value is not None:
                out |= self.expr_types(fi, sub.value, depth + 1)
        if self._capped:
            # the walk hit the depth cap: `out` is a truncation artifact
            # of THIS query's starting depth, not this function's return
            # types — memoizing it would poison every shallower query
            del self._returns[fi.key]
        else:
            self._returns[fi.key] = out
        self._capped = self._capped or outer_capped
        return out

    # --------------------------------------------------------- call edges

    def _resolve_callee(
        self, fi: FuncInfo, call: ast.Call, depth: int = 0
    ) -> list:
        """FuncInfo/ClassInfo targets of one call expression.

        ``depth`` continues the calling type query's depth budget: a
        receiver-type resolution spawned from inside ``expr_types``
        must NOT restart at zero, or two modules whose type lattices
        reference each other (e.g. the serve.dispatch ↔ quantize int8
        tier) recurse past the interpreter limit instead of truncating
        at ``_MAX_DEPTH`` like every other deep chain."""
        f = call.func
        if isinstance(f, ast.Name):
            # lexical scoping: own nested defs first, then each
            # enclosing function's, then module scope
            scope = fi.qual
            while scope is not None:
                cand = self.functions.get((fi.rel, f"{scope}.{f.id}"))
                if cand is not None:
                    return [cand]
                holder = self.functions.get((fi.rel, scope))
                scope = holder.parent_qual if holder is not None else None
            got = self.resolve_symbol(fi.rel, f.id)
            return [got] if isinstance(got, (FuncInfo, ClassInfo)) else []
        if not isinstance(f, ast.Attribute):
            return []
        recv = f.value
        if isinstance(recv, ast.Name):
            if recv.id == "self" and fi.cls is not None:
                return self.lookup_method(self.classes[fi.cls], f.attr)
            got = self.resolve_symbol(fi.rel, recv.id)
            if isinstance(got, _Module):
                fn = got.functions.get(f.attr)
                if fn is not None:
                    return [fn]
                cls = got.classes.get(f.attr)
                return [cls] if cls is not None else []
            if isinstance(got, ClassInfo):
                return self.lookup_method(got, f.attr, virtual=False)
        out = []
        for ckey in self.expr_types(fi, recv, depth + 1):
            out.extend(self.lookup_method(self.classes[ckey], f.attr))
        uniq, keys = [], set()
        for t in out:
            if t.key not in keys:
                keys.add(t.key)
                uniq.append(t)
        return uniq

    def calls_from(self, fi: FuncInfo) -> list:
        """Cached ``(call_node, [FuncInfo targets])`` for one function,
        excluding calls that belong to functions nested inside it (they
        get their own node in the graph)."""
        if fi.key not in self._edges:
            edges = []
            nested_spans = [
                sub for sub in ast.walk(fi.node)
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                and sub is not fi.node
            ]

            def in_nested(node):
                return any(
                    n.lineno <= getattr(node, "lineno", 0)
                    and getattr(node, "end_lineno", 0)
                    <= (n.end_lineno or n.lineno)
                    and n is not node
                    for n in nested_spans
                )

            for sub in ast.walk(fi.node):
                if isinstance(sub, ast.Call) and not in_nested(sub):
                    targets = [
                        t
                        for t in self._resolve_callee(fi, sub)
                        if isinstance(t, FuncInfo)
                        or isinstance(t, ClassInfo)
                    ]
                    # constructor call: edge into __init__
                    expanded = []
                    for t in targets:
                        if isinstance(t, ClassInfo):
                            init = self.lookup_method(
                                t, "__init__", virtual=False
                            )
                            expanded.extend(init)
                        else:
                            expanded.append(t)
                    if expanded:
                        edges.append((sub, expanded))
            self._edges[fi.key] = edges
        return self._edges[fi.key]

    def nested_under(self, fi: FuncInfo) -> list[FuncInfo]:
        prefix = fi.qual + "."
        return [
            g
            for g in self.functions.values()
            if g.rel == fi.rel and g.qual.startswith(prefix)
        ]

    def reachable(self, roots, stop=None) -> dict:
        """BFS closure: ``fn key -> (parent key | None, root key)``.

        ``stop(fi)`` prunes traversal INTO a target (the function is
        not added and not expanded) — HL001 uses it to end the launch
        surface at ``fetch`` sinks, which are scanned separately.
        Nested defs ride with their parent (closures run inside it).
        """
        out: dict = {}
        queue = []
        for fi in roots:
            if fi.key not in out:
                out[fi.key] = (None, fi.key)
                queue.append((fi, fi.key))
        while queue:
            fi, root = queue.pop(0)
            for g in self.nested_under(fi):
                if g.key not in out and not (stop and stop(g)):
                    out[g.key] = (fi.key, root)
                    queue.append((g, root))
            for _call, targets in self.calls_from(fi):
                for t in targets:
                    if t.key in out or (stop and stop(t)):
                        continue
                    out[t.key] = (fi.key, root)
                    queue.append((t, root))
        return out

    def chain(self, reach: dict, key) -> list:
        """Qualname path root → … → key for a ``reachable`` result."""
        names, cur, seen = [], key, set()
        while cur is not None and cur not in seen:
            seen.add(cur)
            names.append(self.functions[cur].qual)
            cur = reach[cur][0]
        return list(reversed(names))
