"""harlint core: files, findings, suppressions, and the rule runner.

The fleet stack (har_tpu.serve + har_tpu.adapt) is held together by
invariants that used to live only in test pins and reviewer memory —
the conservation law, the state()/load_state round-trip rule, the
journal-record/replay-handler bijection, the no-host-sync-on-the-
launch-path rule.  Each has already produced a shipped bug (the PR-4
registry fsync fix, the PR-2 cache nondeterminism hunt).  harlint turns
them into machine-checked gate failures: a rule is an AST visitor over
a fixed fileset, a finding is a (rule, file, line, symbol, message)
record, and the release gate refuses a snapshot with any non-baselined
finding.

Design choices, stated so the rules stay honest:

  - **Pure stdlib.**  ``ast`` + ``json`` only — the linter must run in
    the release gate's subprocess without initializing a jax backend
    (and must never be the reason the gate is slow).
  - **Line-anchored suppressions** (``# harlint: <token>``) are
    reviewed contracts, not escape hatches: ``fetch-ok`` marks the one
    allowed host-sync sink (a retire-side fetch), ``host-ok`` marks a
    reviewed host-origin conversion on the launch path, ``ephemeral``
    marks a stats field that intentionally restarts after recovery,
    ``disable=HL00X`` is the generic last resort.  A token counts on
    the flagged line, anywhere in a multi-line call's span, or on the
    line directly above (so the annotation can carry prose).
  - **Stable baseline keys.**  A finding's baseline key is
    ``rule|path|symbol|normalized-snippet`` — line-number independent,
    so unrelated edits never churn the committed baseline file.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

# the fleet stack: the fileset every rule reasons over by default.
# serving.py rides along because the fleet engine's window assembly,
# smoothing, ingest guard and pad policies live there.
DEFAULT_FILESET = (
    "har_tpu/serve",
    "har_tpu/adapt",
    "har_tpu/serving.py",
    "har_tpu/utils/durable.py",
)

_SUPPRESS_RE = re.compile(r"#\s*harlint:\s*(.+)$")
_KNOWN_TOKENS = {"fetch-ok", "host-ok", "ephemeral"}


def _parse_tokens(comment: str) -> set[str]:
    """Extract harlint tokens from the text after ``# harlint:`` —
    prose is allowed around them (``# harlint: host-ok (slot list)``)."""
    tokens: set[str] = set()
    for word in re.split(r"[\s,()]+", comment.strip()):
        if word in _KNOWN_TOKENS:
            tokens.add(word)
        elif word.startswith("disable="):
            for rule in word[len("disable="):].split(","):
                if rule:
                    tokens.add(f"disable={rule}")
    return tokens


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a file/line/symbol."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    symbol: str = ""  # enclosing function/class qualname
    snippet: str = ""  # normalized source line (baseline key material)

    def key(self) -> str:
        """Line-number-independent identity for the baseline file."""
        return f"{self.rule}|{self.path}|{self.symbol}|{self.snippet}"

    def render(self) -> str:
        sym = self.symbol or "<module>"
        return f"{self.path}:{self.line}: {self.rule} [{sym}] {self.message}"


class FileContext:
    """One parsed source file plus its suppression map."""

    def __init__(self, rel: str, source: str):
        self.rel = rel.replace("\\", "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=self.rel)
        # would-be findings a token (fetch-ok / host-ok / ephemeral)
        # suppressed — rules bump this so the report can account for
        # every reviewed escape, not only `disable=` lines
        self.suppression_hits = 0
        # lineno (1-based) -> set of suppression tokens on that line
        self.suppressions: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                tokens = _parse_tokens(m.group(1))
                if tokens:
                    self.suppressions[i] = tokens

    # ------------------------------------------------------ suppression

    def _node_lines(self, node: ast.AST):
        start = getattr(node, "lineno", 1)
        end = getattr(node, "end_lineno", start) or start
        lines = list(range(start, end + 1))
        # the line directly above joins the annotation surface ONLY
        # when it is a comment-only line (a prose justification block);
        # a trailing token on the previous CODE line must not bleed
        # into this statement
        prev = start - 1
        if (
            prev >= 1
            and prev <= len(self.lines)
            and self.lines[prev - 1].lstrip().startswith("#")
        ):
            lines.insert(0, prev)
        return lines

    def suppressed(self, node: ast.AST, token: str) -> bool:
        return any(
            token in self.suppressions.get(ln, ())
            for ln in self._node_lines(node)
        )

    def rule_disabled(self, node: ast.AST, rule_id: str) -> bool:
        return self.suppressed(node, f"disable={rule_id}")

    # --------------------------------------------------------- helpers

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return " ".join(self.lines[lineno - 1].split())
        return ""

    def finding(
        self, rule: str, node: ast.AST, message: str, symbol: str = ""
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=rule,
            path=self.rel,
            line=line,
            message=message,
            symbol=symbol,
            snippet=self.snippet(line),
        )


def walk_functions(tree: ast.Module):
    """Yield ``(qualname, class_name, node)`` for every function/method
    definition, qualnames dotted through nesting (``Cls.method``)."""
    out = []

    def visit(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ".".join(stack + [child.name])
                cls = stack[-1] if stack else None
                out.append((qual, cls, child))
                visit(child, stack + [child.name])
            elif isinstance(child, ast.ClassDef):
                visit(child, stack + [child.name])
            else:
                visit(child, stack)

    visit(tree, [])
    return out


def call_name(node: ast.Call) -> str | None:
    """The terminal name a call targets: ``foo()`` -> foo,
    ``a.b.foo()`` -> foo."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def receiver_name(node: ast.Call) -> str | None:
    """For ``recv.attr(...)``: the receiver's name when it is a bare
    Name (``np.asarray`` -> "np"); None otherwise."""
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id
    return None


class Rule:
    """Base class: per-file ``check`` plus an optional cross-file
    ``finalize`` (HL003 needs the whole fileset to compare record
    writers against replay handlers)."""

    rule_id = "HL000"
    title = ""

    def applies(self, rel: str) -> bool:
        return True

    def check(self, ctx: FileContext) -> list[Finding]:
        return []

    def finalize(self, ctxs: list[FileContext]) -> list[Finding]:
        return []


@dataclasses.dataclass
class LintStats:
    rules_run: list[str]
    files: int
    annotation_suppressed: int = 0


def run_rules(
    ctxs: list[FileContext], rules: list[Rule]
) -> tuple[list[Finding], LintStats]:
    """Run every rule over the fileset; generic ``disable=`` line
    suppressions are applied here so individual rules never need to."""
    by_rel = {c.rel: c for c in ctxs}
    raw: list[Finding] = []
    for rule in rules:
        for ctx in ctxs:
            if rule.applies(ctx.rel):
                raw.extend(rule.check(ctx))
        raw.extend(rule.finalize([c for c in ctxs if rule.applies(c.rel)]))
    findings: list[Finding] = []
    suppressed = 0
    for f in raw:
        ctx = by_rel.get(f.path)
        check_lines = [f.line]
        if ctx is not None:
            prev = f.line - 1
            # same adjacency rule as token suppression: the preceding
            # line joins the surface only when it is comment-only
            if (
                1 <= prev <= len(ctx.lines)
                and ctx.lines[prev - 1].lstrip().startswith("#")
            ):
                check_lines.append(prev)
        if ctx is not None and any(
            f"disable={f.rule}" in ctx.suppressions.get(ln, ())
            for ln in check_lines
        ):
            suppressed += 1
            continue
        findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    stats = LintStats(
        rules_run=[r.rule_id for r in rules],
        files=len(ctxs),
        annotation_suppressed=suppressed
        + sum(c.suppression_hits for c in ctxs),
    )
    return findings, stats


def discover_files(root: Path, paths=None) -> list[Path]:
    """Resolve the fileset: explicit ``paths`` (files or directories)
    or the default fleet-stack set, as sorted .py files."""
    targets = [root / p for p in (paths or DEFAULT_FILESET)]
    files: list[Path] = []
    for t in targets:
        if t.is_dir():
            files.extend(sorted(t.rglob("*.py")))
        elif t.suffix == ".py" and t.exists():
            files.append(t)
    return files


def load_contexts(root: Path, paths=None) -> list[FileContext]:
    ctxs = []
    for f in discover_files(root, paths):
        rel = f.relative_to(root).as_posix()
        ctxs.append(FileContext(rel, f.read_text()))
    return ctxs
