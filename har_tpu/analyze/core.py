"""harlint core: files, findings, suppressions, and the rule runner.

The fleet stack (har_tpu.serve + har_tpu.adapt) is held together by
invariants that used to live only in test pins and reviewer memory —
the conservation law, the state()/load_state round-trip rule, the
journal-record/replay-handler bijection, the no-host-sync-on-the-
launch-path rule.  Each has already produced a shipped bug (the PR-4
registry fsync fix, the PR-2 cache nondeterminism hunt).  harlint turns
them into machine-checked gate failures: a rule is an AST visitor over
a fixed fileset, a finding is a (rule, file, line, symbol, message)
record, and the release gate refuses a snapshot with any non-baselined
finding.

Design choices, stated so the rules stay honest:

  - **Pure stdlib.**  ``ast`` + ``json`` only — the linter must run in
    the release gate's subprocess without initializing a jax backend
    (and must never be the reason the gate is slow).
  - **Line-anchored suppressions** (``# harlint: <token>``) are
    reviewed contracts, not escape hatches: ``fetch-ok`` marks the one
    allowed host-sync sink (a retire-side fetch), ``host-ok`` marks a
    reviewed host-origin conversion on the launch path, ``ephemeral``
    marks a stats field that intentionally restarts after recovery,
    ``disable=HL00X`` is the generic last resort.  A token counts on
    the flagged line, anywhere in a multi-line call's span, or on the
    line directly above (so the annotation can carry prose).
  - **Stable baseline keys.**  A finding's baseline key is
    ``rule|path|symbol|normalized-snippet`` — line-number independent,
    so unrelated edits never churn the committed baseline file.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

# the fleet stack: the fileset every rule reasons over by default.
# serving.py rides along because the fleet engine's window assembly,
# smoothing, ingest guard and pad policies live there; utils/backoff.py
# because the dispatch retry loop runs ON the launch path (HL001's
# computed reachability follows retry_call's closures); har_tpu/parallel
# because HL006/HL007 guard its traced bodies and partition specs.
DEFAULT_FILESET = (
    "har_tpu/serve",
    "har_tpu/adapt",
    "har_tpu/serving.py",
    "har_tpu/utils/durable.py",
    "har_tpu/utils/backoff.py",
    "har_tpu/parallel",
)

_SUPPRESS_RE = re.compile(r"#\s*harlint:\s*(.+)$")
_KNOWN_TOKENS = {"fetch-ok", "host-ok", "ephemeral", "spec-ok"}


def _parse_tokens(comment: str) -> set[str]:
    """Extract harlint tokens from the text after ``# harlint:`` —
    prose is allowed around them (``# harlint: host-ok (slot list)``)."""
    tokens: set[str] = set()
    for word in re.split(r"[\s,()]+", comment.strip()):
        if word in _KNOWN_TOKENS:
            tokens.add(word)
        elif word.startswith("disable="):
            for rule in word[len("disable="):].split(","):
                if rule:
                    tokens.add(f"disable={rule}")
    return tokens


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a file/line/symbol."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    symbol: str = ""  # enclosing function/class qualname
    snippet: str = ""  # normalized source line (baseline key material)

    def key(self) -> str:
        """Line-number-independent identity for the baseline file."""
        return f"{self.rule}|{self.path}|{self.symbol}|{self.snippet}"

    def render(self) -> str:
        sym = self.symbol or "<module>"
        return f"{self.path}:{self.line}: {self.rule} [{sym}] {self.message}"


class FileContext:
    """One parsed source file plus its suppression map."""

    def __init__(self, rel: str, source: str):
        self.rel = rel.replace("\\", "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=self.rel)
        # support context (path-subset runs): informs cross-file
        # analysis — call-graph edges, traced roots, axis tables — but
        # is never itself examined: per-file checks skip it, finalize
        # rules don't scan its bodies, and its suppression consumption
        # stays out of the report
        self.support = False
        # would-be findings a token (fetch-ok / host-ok / ephemeral)
        # suppressed — rules bump this so the report can account for
        # every reviewed escape, not only `disable=` lines
        self.suppression_hits = 0
        # (lineno, token) pairs that actually suppressed something this
        # run — HL008 audits the complement (annotations that suppress
        # NOTHING are rotted contracts and are themselves findings)
        self.suppression_used: set[tuple[int, str]] = set()
        # lineno (1-based) -> set of suppression tokens on that line
        self.suppressions: dict[int, set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                tokens = _parse_tokens(m.group(1))
                if tokens:
                    self.suppressions[i] = tokens

    # ------------------------------------------------------ suppression

    def _node_lines(self, node: ast.AST):
        start = getattr(node, "lineno", 1)
        end = getattr(node, "end_lineno", start) or start
        lines = list(range(start, end + 1))
        # the line directly above joins the annotation surface ONLY
        # when it is a comment-only line (a prose justification block);
        # a trailing token on the previous CODE line must not bleed
        # into this statement
        prev = start - 1
        if (
            prev >= 1
            and prev <= len(self.lines)
            and self.lines[prev - 1].lstrip().startswith("#")
        ):
            lines.insert(0, prev)
        return lines

    def suppressed(self, node: ast.AST, token: str) -> bool:
        for ln in self._node_lines(node):
            if token in self.suppressions.get(ln, ()):
                self.suppression_used.add((ln, token))
                return True
        return False

    def rule_disabled(self, node: ast.AST, rule_id: str) -> bool:
        return self.suppressed(node, f"disable={rule_id}")

    # --------------------------------------------------------- helpers

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return " ".join(self.lines[lineno - 1].split())
        return ""

    def finding(
        self, rule: str, node: ast.AST, message: str, symbol: str = ""
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=rule,
            path=self.rel,
            line=line,
            message=message,
            symbol=symbol,
            snippet=self.snippet(line),
        )


def walk_functions(tree: ast.Module):
    """Yield ``(qualname, class_name, node)`` for every function/method
    definition, qualnames dotted through nesting (``Cls.method``)."""
    out = []

    def visit(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ".".join(stack + [child.name])
                cls = stack[-1] if stack else None
                out.append((qual, cls, child))
                visit(child, stack + [child.name])
            elif isinstance(child, ast.ClassDef):
                visit(child, stack + [child.name])
            else:
                visit(child, stack)

    visit(tree, [])
    return out


def walk_scopes(tree: ast.Module) -> list[tuple[str, ast.AST]]:
    """``(qualname, node)`` for every def/class scope, pre-order
    (parents before their children), qualnames dotted through nesting
    — the one walker behind symbol labelling (iterate in order and
    let deeper scopes overwrite: innermost wins) and enclosing-scope
    lookups, so the qualname convention cannot drift between rules."""
    out: list[tuple[str, ast.AST]] = []

    def visit(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                out.append((".".join(stack + [child.name]), child))
                visit(child, stack + [child.name])
            else:
                visit(child, stack)

    visit(tree, [])
    return out


def call_name(node: ast.Call) -> str | None:
    """The terminal name a call targets: ``foo()`` -> foo,
    ``a.b.foo()`` -> foo."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def receiver_name(node: ast.Call) -> str | None:
    """For ``recv.attr(...)``: the receiver's name when it is a bare
    Name (``np.asarray`` -> "np"); None otherwise."""
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id
    return None


class Project:
    """Shared cross-file analysis state for one lint run.

    The call graph (``analyze.callgraph``) is built lazily — only runs
    that include a graph-consuming rule (HL001/HL006) pay for it — and
    built ONCE, however many rules then traverse it."""

    def __init__(self, ctxs: list["FileContext"]):
        self.ctxs = ctxs
        self._graph = None
        self.callgraph_ms = 0.0

    @property
    def callgraph(self):
        if self._graph is None:
            import time

            from har_tpu.analyze.callgraph import CallGraph

            t0 = time.perf_counter()
            self._graph = CallGraph(self.ctxs)
            self.callgraph_ms = (time.perf_counter() - t0) * 1e3
        return self._graph


class Rule:
    """Base class: per-file ``check``, an optional cross-file
    ``finalize`` (HL003 compares record writers against replay
    handlers), and an optional ``audit`` that runs AFTER every other
    rule's suppressions have been consumed (HL008 flags the annotations
    nothing consumed).  ``self.project`` (set by ``run_rules``) carries
    the shared call graph."""

    rule_id = "HL000"
    title = ""
    project: Project | None = None

    def applies(self, rel: str) -> bool:
        return True

    def check(self, ctx: FileContext) -> list[Finding]:
        return []

    def finalize(self, ctxs: list[FileContext]) -> list[Finding]:
        return []

    def audit(
        self, ctxs: list[FileContext], ran: list[str]
    ) -> list[Finding]:
        return []


@dataclasses.dataclass
class LintStats:
    rules_run: list[str]
    files: int
    annotation_suppressed: int = 0
    rule_ms: dict[str, float] = dataclasses.field(default_factory=dict)
    callgraph_ms: float = 0.0


def _apply_disable(
    raw: list[Finding], by_rel: dict[str, FileContext]
) -> tuple[list[Finding], int]:
    """Filter generic ``disable=HL00X`` line suppressions, recording
    which (line, token) pairs were consumed."""
    findings: list[Finding] = []
    suppressed = 0
    for f in raw:
        ctx = by_rel.get(f.path)
        check_lines = [f.line]
        if ctx is not None:
            prev = f.line - 1
            # same adjacency rule as token suppression: the preceding
            # line joins the surface only when it is comment-only
            if (
                1 <= prev <= len(ctx.lines)
                and ctx.lines[prev - 1].lstrip().startswith("#")
            ):
                check_lines.append(prev)
        hit = None
        if ctx is not None:
            for ln in check_lines:
                if f"disable={f.rule}" in ctx.suppressions.get(ln, ()):
                    hit = ln
                    break
        if hit is not None:
            ctx.suppression_used.add((hit, f"disable={f.rule}"))
            suppressed += 1
            continue
        findings.append(f)
    return findings, suppressed


def run_rules(
    ctxs: list[FileContext], rules: list[Rule]
) -> tuple[list[Finding], LintStats]:
    """Run every rule over the fileset; generic ``disable=`` line
    suppressions are applied here so individual rules never need to.
    Per-rule wall time is recorded (``har lint --stats`` and the
    release gate's lint budget read it)."""
    import time

    by_rel = {c.rel: c for c in ctxs}
    project = Project(ctxs)
    raw: list[Finding] = []
    rule_ms: dict[str, float] = {}
    for rule in rules:
        rule.project = project
        t0 = time.perf_counter()
        for ctx in ctxs:
            if rule.applies(ctx.rel) and not ctx.support:
                raw.extend(rule.check(ctx))
        raw.extend(rule.finalize([c for c in ctxs if rule.applies(c.rel)]))
        rule_ms[rule.rule_id] = (time.perf_counter() - t0) * 1e3
    findings, suppressed = _apply_disable(raw, by_rel)
    # audit pass: runs after every check/finalize has consumed its
    # suppressions (HL008's staleness question is only answerable then)
    ran = [r.rule_id for r in rules]
    for rule in rules:
        t0 = time.perf_counter()
        audit_raw = rule.audit(
            [c for c in ctxs if rule.applies(c.rel)], ran
        )
        if audit_raw:
            audited, n = _apply_disable(audit_raw, by_rel)
            findings.extend(audited)
            suppressed += n
        rule_ms[rule.rule_id] += (time.perf_counter() - t0) * 1e3
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    stats = LintStats(
        rules_run=ran,
        files=len([c for c in ctxs if not c.support]),
        annotation_suppressed=suppressed
        + sum(c.suppression_hits for c in ctxs if not c.support),
        rule_ms={k: round(v, 2) for k, v in rule_ms.items()},
        callgraph_ms=round(project.callgraph_ms, 2),
    )
    return findings, stats


def discover_files(root: Path, paths=None) -> list[Path]:
    """Resolve the fileset: explicit ``paths`` (files or directories)
    or the default fleet-stack set, as sorted .py files."""
    targets = [root / p for p in (paths or DEFAULT_FILESET)]
    files: list[Path] = []
    for t in targets:
        if t.is_dir():
            files.extend(sorted(t.rglob("*.py")))
        elif t.suffix == ".py" and t.exists():
            files.append(t)
    return files


def load_contexts(root: Path, paths=None) -> list[FileContext]:
    ctxs = []
    for f in discover_files(root, paths):
        rel = f.relative_to(root).as_posix()
        ctxs.append(FileContext(rel, f.read_text()))
    return ctxs
