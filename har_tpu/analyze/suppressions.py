"""HL008 — stale-suppression audit: an annotation that no longer
suppresses anything is itself a finding.

harlint's suppression tokens are REVIEWED CONTRACTS, not comments:
``# harlint: fetch-ok`` says "a human looked at this host sync and
accepted it"; ``ephemeral`` says "this field deliberately restarts
after recovery"; ``spec-ok`` says "this placement-driven jit is
intentional".  When the code under the annotation changes — the sync
removed, the field persisted, the jit given shardings — the annotation
rots: it reads as an active reviewed escape while excusing nothing,
and the NEXT edit on that line inherits a free pass it never earned.
(The exact failure mode baselines have, solved there by keying entries
to the snippet; annotations need this audit instead.)

Mechanics: every rule records which ``(line, token)`` pairs actually
consumed a would-be finding (``FileContext.suppression_used``, written
by ``suppressed()`` and the generic ``disable=`` filter).  This rule
runs in ``run_rules``'s AUDIT pass — strictly after every other rule
has consumed its suppressions — and flags each annotation line whose
token consumed nothing, PROVIDED the token's owning rule ran:

    fetch-ok / host-ok -> HL001      ephemeral -> HL002
    spec-ok            -> HL007      disable=HL00X -> HL00X

(the ownership guard keeps a ``--rule HL004`` subset run from calling
every HL001 annotation stale).  ``run_harlint`` drops this rule on
path-subset runs (``har lint --changed``, explicit paths): staleness
is a whole-fileset property — HL001's launch closure must actually be
computed for its annotations to be judged — exactly as HL003's
bijections only hold over the full set.

A deliberate consequence: an annotation in a file its rule never scans
(a ``host-ok`` in a module the launch surface cannot reach) is flagged
too.  That is the policy working: the reviewed contract claims
protection that is not happening, so either the reachability gap or
the annotation is wrong — both deserve a finding.
"""

from __future__ import annotations

from har_tpu.analyze.core import FileContext, Finding, Rule, walk_scopes

# token -> the rule whose findings it suppresses
TOKEN_OWNERS = {
    "fetch-ok": "HL001",
    "host-ok": "HL001",
    "ephemeral": "HL002",
    "spec-ok": "HL007",
}


class _Anchor:
    """Line-anchored pseudo-node for Finding construction."""

    def __init__(self, lineno: int):
        self.lineno = lineno
        self.end_lineno = lineno


class SuppressionAuditRule(Rule):
    rule_id = "HL008"
    title = "stale suppression"

    def audit(
        self, ctxs: list[FileContext], ran: list[str]
    ) -> list[Finding]:
        ran_set = set(ran)
        findings: list[Finding] = []
        for ctx in ctxs:
            if not ctx.suppressions:
                continue
            symbols = self._symbol_map(ctx)
            for line in sorted(ctx.suppressions):
                for token in sorted(ctx.suppressions[line]):
                    owner = (
                        token.split("=", 1)[1]
                        if token.startswith("disable=")
                        else TOKEN_OWNERS.get(token)
                    )
                    if owner is None or owner not in ran_set:
                        continue  # owning rule didn't run: unjudgeable
                    if owner == self.rule_id:
                        continue  # disable=HL008 is consumed below us
                    if (line, token) in ctx.suppression_used:
                        continue
                    findings.append(
                        ctx.finding(
                            self.rule_id,
                            _Anchor(line),
                            f"stale `# harlint: {token}` — {owner} ran "
                            "and this annotation suppressed nothing "
                            "(the sync/field/spec it reviewed is gone, "
                            "or the line no longer triggers the rule); "
                            "remove it so the reviewed contract cannot "
                            "rot onto a future edit",
                            symbols.get(line, ""),
                        )
                    )
        return findings

    @staticmethod
    def _symbol_map(ctx: FileContext) -> dict[int, str]:
        """line -> innermost enclosing def/class qualname (pre-order
        walk: deeper scopes overwrite their parents' lines)."""
        out: dict[int, str] = {}
        for qual, node in walk_scopes(ctx.tree):
            for ln in range(
                node.lineno, (node.end_lineno or node.lineno) + 1
            ):
                out[ln] = qual
        return out
