"""`har` command-line interface.

Replaces the reference's spark-submit entrypoint (README.md:5-8) with a
real CLI: train/evaluate/predict/sweep/bench subcommands over a dataclass
config (the reference hardcodes every knob in the script — SURVEY §5.6).

Usage:
  python -m har_tpu.cli train    --models lr dt rf --output-dir main_result
  python -m har_tpu.cli train    --models mlp --epochs 150
  python -m har_tpu.cli evaluate --checkpoint models/lr
  python -m har_tpu.cli predict  --checkpoint models/lr --output preds.csv
  python -m har_tpu.cli serve    --sessions 1000
  python -m har_tpu.cli bench
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from har_tpu.config import DataConfig, ModelConfig, RunConfig, TuningConfig


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="har", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    t = sub.add_parser("train", help="train + evaluate models, write report")
    t.add_argument("--dataset", default="wisdm",
                   choices=["wisdm", "wisdm_raw", "ucihar", "synthetic"],
                   help="wisdm_raw = raw tri-axial stream (the view the "
                        "cnn1d/bilstm/transformer models train on)")
    t.add_argument("--data-path", default=None)
    t.add_argument("--models", nargs="+",
                   default=["lr", "dt", "rf"],
                   help="lr dt rf gbt mlp cnn1d bilstm transformer")
    t.add_argument("--train-fraction", type=float, default=0.7)
    t.add_argument("--seed", type=int, default=2018)
    t.add_argument("--split-method", default="auto",
                   choices=["auto", "spark", "bernoulli"],
                   help="train/test draw: spark replays the reference's "
                        "randomSplit row-for-row (WISDM only); auto picks "
                        "it for the wisdm dataset")
    t.add_argument("--no-cv", action="store_true",
                   help="skip the 5-fold CrossValidator pass")
    t.add_argument("--cv-metric", default="accuracy",
                   help="model-selection metric; 'mae' replicates the "
                        "reference's evaluator quirk (SURVEY §2 N)")
    t.add_argument("--epochs", type=int, default=None)
    t.add_argument("--batch-size", type=int, default=None)
    t.add_argument("--learning-rate", type=float, default=None)
    t.add_argument("--checkpoint-dir", default=None,
                   help="snapshot (params, opt_state) here during neural "
                        "training and auto-resume from the newest one")
    t.add_argument("--save-models-dir", default=None,
                   help="persist every fitted model (classical + neural, "
                        "plain + CV-best) under this directory; classical "
                        "checkpoints bundle the fitted pipeline "
                        "vocabularies, `evaluate` scores either kind")
    t.add_argument("--save-every-epochs", type=int, default=None)
    t.add_argument("--augment", default=None,
                   choices=["raw_windows", "none"],
                   help="on-device augmentation inside the train step "
                        "(raw (T,3) window models): jitter, per-axis "
                        "scale, 3-D rotation, time masking")
    t.add_argument("--class-weight", default=None,
                   choices=["balanced"],
                   help="reweigh the neural loss by inverse class "
                        "frequency (minority activities pull equally)")
    t.add_argument("--early-stop-patience", type=int, default=None,
                   help="stop neural training after N epochs without "
                        "val-accuracy improvement, keep the best epoch")
    t.add_argument("--validation-fraction", type=float, default=None,
                   help="rows carved out of training for early stopping")
    t.add_argument("--keep-binned", action="store_true",
                   help="keep the 30 histogram-bin columns X0..Z9 the "
                        "reference drops (Main/main.py:22-26); gbt's "
                        "best-accuracy view")
    t.add_argument("--eda", action="store_true",
                   help="write hexbin pair plots + scatter matrix")
    t.add_argument("--trace-dir", default=None,
                   help="write a TensorBoard-loadable jax.profiler trace "
                        "of the whole run to this directory")
    t.add_argument("--distributed", action="store_true",
                   help="multi-host SPMD: call jax.distributed.initialize "
                        "before any device use (every host runs the same "
                        "command; coordinator/count/id autodetect on Cloud "
                        "TPU pods, or set the flags below)")
    t.add_argument("--coordinator", default=None,
                   help="coordinator host:port (with --distributed)")
    t.add_argument("--num-processes", type=int, default=None,
                   help="total process count (with --distributed)")
    t.add_argument("--process-id", type=int, default=None,
                   help="this host's rank (with --distributed)")
    t.add_argument("--dp", type=int, default=1,
                   help="data-parallel mesh axis for neural training "
                        "(-1 = all devices; batch is sharded over dp, "
                        "gradients psum over ICI)")
    t.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel mesh axis (Megatron-style GSPMD "
                        "shardings over hidden dims)")
    t.add_argument("--output-dir", default="main_result")

    e = sub.add_parser(
        "evaluate",
        help="evaluate a saved checkpoint (or an exported artifact)",
    )
    e_src = e.add_mutually_exclusive_group(required=True)
    e_src.add_argument("--checkpoint")
    e_src.add_argument(
        "--artifact",
        help="score an exported StableHLO artifact directory (har "
             "export output) instead of a checkpoint — the deployed "
             "program itself, no model classes in the loop",
    )
    e.add_argument("--dataset", default=None,
                   choices=["wisdm", "wisdm_raw", "ucihar", "synthetic"],
                   help="defaults to the dataset recorded in the "
                        "checkpoint metadata")
    e.add_argument("--data-path", default=None)
    e.add_argument("--train-fraction", type=float, default=None,
                   help="defaults to the training run's recorded value "
                        "(test split re-derived from it)")
    e.add_argument("--seed", type=int, default=None,
                   help="defaults to the training run's recorded value")

    pr = sub.add_parser(
        "predict",
        help="batch inference from a saved checkpoint (or exported "
             "artifact) → predictions CSV",
    )
    pr_src = pr.add_mutually_exclusive_group(required=True)
    pr_src.add_argument("--checkpoint")
    pr_src.add_argument(
        "--artifact",
        help="predict with an exported StableHLO artifact directory "
             "(har export output) instead of a checkpoint",
    )
    pr.add_argument("--output", default="predictions.csv")
    pr.add_argument("--dataset", default=None,
                    choices=["wisdm", "wisdm_raw", "ucihar", "synthetic"])
    pr.add_argument("--data-path", default=None)
    pr.add_argument("--train-fraction", type=float, default=None,
                    help="defaults to the training run's recorded value")
    pr.add_argument("--seed", type=int, default=None,
                    help="defaults to the training run's recorded value")

    s = sub.add_parser(
        "sweep",
        help="split-ratio sweep (the paper's Table 1/2 experiment): "
             "models × {70-30, 80-20, 90-10}",
    )
    s.add_argument("--dataset", default="wisdm",
                   choices=["wisdm", "wisdm_raw", "ucihar", "synthetic"])
    s.add_argument("--data-path", default=None)
    s.add_argument("--models", nargs="+", default=["lr", "dt", "rf"])
    s.add_argument("--fractions", nargs="+", type=float,
                   default=[0.7, 0.8, 0.9])
    s.add_argument("--seed", type=int, default=2018)
    s.add_argument("--no-cv", action="store_true")
    s.add_argument("--dp", type=int, default=1,
                   help="data-parallel mesh axis for neural models "
                        "(-1 = all devices)")
    s.add_argument("--tp", type=int, default=1)
    s.add_argument("--output-dir", default="main_result")

    st = sub.add_parser(
        "stream",
        help="real-time sliding-window inference: replay a recorded "
             "tri-axial stream (CSV: x,y,z per row) through a saved "
             "checkpoint and emit the activity timeline",
    )
    st.add_argument("--checkpoint", required=True,
                    help="neural checkpoint trained on raw windows")
    st.add_argument("--input", default=None,
                    help="recording CSV (one x,y,z row per 20 Hz sample); "
                         "omit for a synthetic demo recording")
    st.add_argument("--window", type=int, default=None,
                    help="defaults to the checkpoint's recorded training "
                         "window; when the checkpoint records its shape, "
                         "an explicit mismatch is rejected (older "
                         "checkpoints without input_shape are unguarded)")
    st.add_argument("--hop", type=int, default=20)
    st.add_argument("--smoothing", default="ema",
                    choices=["ema", "vote", "none"])
    st.add_argument("--events-csv", default=None,
                    help="write per-event rows (t_index,label,raw_label,"
                         "latency_ms,probabilities...)")
    st.add_argument("--monitor", action="store_true",
                    help="input-drift detection against the checkpoint's "
                         "training statistics; events are stamped and the "
                         "summary carries the final drift report")

    sv = sub.add_parser(
        "serve",
        help="fleet serving smoke: multiplex N concurrent synthetic "
             "20 Hz sessions through the continuous-batching engine "
             "(har_tpu.serve) and report FleetStats + p50/p99 event "
             "latency",
    )
    sv.add_argument("--sessions", type=int, default=1000,
                    help="concurrent sessions to admit and drive")
    sv.add_argument("--windows-per-session", type=int, default=2,
                    help="10 s windows each session streams")
    sv.add_argument("--checkpoint", default=None,
                    help="serve a saved neural checkpoint; default is "
                         "the training-free analytic demo model "
                         "(scheduler-overhead baseline)")
    sv.add_argument("--hop", type=int, default=200,
                    help="emission stride in samples (200 = one "
                         "decision per 10 s window)")
    sv.add_argument("--smoothing", default="ema",
                    choices=["ema", "vote", "none"])
    sv.add_argument("--target-batch", type=int, default=256,
                    help="micro-batcher dispatch size (power-of-two "
                         "padded; at most log2+1 programs compile)")
    sv.add_argument("--pipeline-depth", type=int, default=1,
                    help="dispatch batches in flight on-device before "
                         "the host blocks on a retire: 1 = synchronous "
                         "engine, 2 = double-buffered, >=3 = the "
                         "ticket ring (the device stays busy across a "
                         "slow host round; events, smoothing and "
                         "journal acks stay in the exact synchronous "
                         "order at any depth)")
    sv.add_argument("--fused", action="store_true",
                    help="fused on-device hot loop: scale + score + "
                         "argmax + top-prob in ONE jitted program per "
                         "padded shape, retire fetching only (labels, "
                         "top_probs).  Needs a jitted model "
                         "(--checkpoint or --tier int8 demo) and "
                         "vote/none smoothing (EMA needs full "
                         "probabilities and serves unfused); labels "
                         "are unchanged, off-label event probabilities "
                         "become the compact surrogate (docs/serving.md)")
    sv.add_argument("--tier", default="f32", choices=["f32", "int8"],
                    help="serving tier: int8 = weight-only quantized "
                         "serving (har_tpu.quantize.quantize_serving; "
                         "weights ship int8 to the device, dequant is "
                         "a traced op).  Needs a jitted model — the "
                         "analytic demo model has no device program")
    sv.add_argument("--mesh", type=int, default=0,
                    help="shard each dispatch batch over this many "
                         "devices (jax.devices(); batches pad to "
                         "devices x pow2).  0 = single device.  On a "
                         "CPU host run under XLA_FLAGS=--xla_force_"
                         "host_platform_device_count=N for a dry-run "
                         "mesh.  Needs a jitted model; the analytic "
                         "demo model falls back to host scoring")
    sv.add_argument("--mesh-shape", type=str, default=None,
                    help="2D BxM (batch x model) serving mesh, e.g. "
                         "2x4: batch rows shard over B devices while "
                         "the checkpoint's params place model-parallel "
                         "over M via the partition-rule tables "
                         "(har_tpu.parallel.rules) — serves models "
                         "bigger than one device.  Mutually exclusive "
                         "with --mesh; needs B*M visible devices (same "
                         "dry-run hint as --mesh) and a jitted model")
    sv.add_argument("--workers", type=int, default=0,
                    help="run a multi-worker fleet cluster "
                         "(har_tpu.serve.cluster): sessions partition "
                         "across N journaled FleetServer workers behind "
                         "a consistent-hash router with heartbeat "
                         "failover and journal hand-off migration.  "
                         "0/1 = the single-process engine.  Pairs with "
                         "--kill-worker to demo a mid-run failover; "
                         "--journal names the cluster root (default: a "
                         "temp dir)")
    sv.add_argument("--net", action="store_true",
                    help="with --workers: run the REAL transport "
                         "(har_tpu.serve.net) — each worker an OS "
                         "subprocess (`har serve-worker`) on a loopback "
                         "TCP socket with real clocks, the controller "
                         "speaking length-prefixed CRC-framed RPCs with "
                         "deadlines + retries.  --kill-worker then "
                         "SIGKILLs the actual process and the summary "
                         "carries the rpc counters/rtt alongside the "
                         "conservation verdict")
    sv.add_argument("--kill-worker", default=None,
                    help="with --workers: SIGKILL this worker id (e.g. "
                         "w0) partway through the drive — its sessions "
                         "fail over to the survivors via journal "
                         "hand-off and the summary reports the global "
                         "conservation verdict")
    sv.add_argument("--trace", default=None,
                    choices=["diurnal", "bursty", "storm"],
                    help="elastic traffic mode (har_tpu.serve.traffic): "
                         "instead of N flat sessions, drive a seeded "
                         "arrival process with session connect/"
                         "disconnect churn — a 10x diurnal swing "
                         "(--sessions is the PEAK), Poisson-modulated "
                         "bursts, or a mid-run overnight-cohort "
                         "disconnect storm; slow-client stalls and "
                         "mixed per-session rates included.  The trace "
                         "spec is printed in the summary (replayable "
                         "by seed+params)")
    sv.add_argument("--trace-rounds", type=int, default=96,
                    help="delivery rounds (= one diurnal period) for "
                         "--trace")
    sv.add_argument("--autoscale", action="store_true",
                    help="with --trace: attach the load-adaptive "
                         "capacity controller "
                         "(har_tpu.serve.traffic.autoscale) — "
                         "hysteresis/cooldown policy loop resizing "
                         "target_batch and pipeline_depth online at "
                         "dispatch boundaries (zero-drop, journaled) "
                         "as the swing loads and unloads the engine")
    sv.add_argument("--max-delay-ms", type=float, default=50.0,
                    help="deadline: max time a due window waits for "
                         "batch coalescing")
    sv.add_argument("--monitor", action="store_true",
                    help="attach a per-session DriftMonitor (synthetic "
                         "training stats); drift verdicts flow into "
                         "the multiplexed event stream")
    sv.add_argument("--adapt", action="store_true",
                    help="close the drift loop (har_tpu.adapt): "
                         "per-session monitors feed a fleet-level "
                         "retrain trigger; a candidate shadow-scores "
                         "mirrored live batches and is hot-swapped in "
                         "(zero dropped windows) when the gates pass, "
                         "with automatic rollback on post-swap "
                         "regression.  Implies --monitor.")
    sv.add_argument("--inject-drift", type=float, default=0.0,
                    help="fraction of sessions whose streams shift "
                         "mid-recording (a population-scale sensor "
                         "re-mount) — with --adapt this exercises the "
                         "full retrain→shadow→swap loop")
    sv.add_argument("--registry", default=None,
                    help="model-registry root for --adapt (versioned "
                         "lineage + promotions log); default is a "
                         "temp dir discarded after the run")
    sv.add_argument("--profile-host", action="store_true",
                    help="per-poll host-time breakdown (ingest / "
                         "due-select / gather / retire / journal stage "
                         "histograms, har_tpu.serve.stats.HostProfile) "
                         "stamped into the summary JSON — the "
                         "observability hook the sessions-per-worker "
                         "ceiling curve and host-plane regression "
                         "checks read")
    sv.add_argument("--calibrate-device", action="store_true",
                    help="measure device p50 per dispatched batch "
                         "shape (checkpoint models only) so the stats "
                         "attribute p99 spikes to tunnel vs chip")
    sv.add_argument("--journal", default=None,
                    help="write-ahead journal directory "
                         "(har_tpu.serve.journal): session state, "
                         "pushed samples, scored-event acks and swap "
                         "records become crash-recoverable; pair with "
                         "--resume after a kill")
    sv.add_argument("--resume", action="store_true",
                    help="recover the fleet from --journal DIR "
                         "(snapshot + journal-suffix replay) and resume "
                         "delivery from each session's recovered "
                         "watermark — acked events are never re-emitted")
    sv.add_argument("--journal-flush-every", type=int, default=64,
                    help="journal records buffered between fsync "
                         "batches (acks always flush at poll "
                         "boundaries); bounds the crash loss window")
    sv.add_argument("--journal-snapshot-every", type=int, default=4096,
                    help="journal records between state snapshots; "
                         "bounds recovery replay cost")
    sv.add_argument("--kill-after-polls", type=int, default=0,
                    help="TESTING: os._exit(17) after N scheduler polls "
                         "— a SIGKILL stand-in for crash-recovery "
                         "drills (nothing is flushed beyond what the "
                         "journal already made durable)")
    sv.add_argument("--seed", type=int, default=0)
    sv.add_argument("--inject-drop", type=float, default=0.0,
                    help="probability a delivery chunk is lost")
    sv.add_argument("--inject-delay", type=float, default=0.0,
                    help="probability a chunk is held one round "
                         "(catch-up burst)")
    sv.add_argument("--inject-stall-ms", type=float, default=0.0,
                    help="with --inject-stall-every: dispatch stall "
                         "length (exercises the SLO/degradation "
                         "ladder)")
    sv.add_argument("--inject-stall-every", type=int, default=0,
                    help="stall every Nth dispatch by "
                         "--inject-stall-ms")

    ft = sub.add_parser(
        "finetune",
        help="adapt a saved neural checkpoint to new data (warm start, "
             "checkpoint's own scaler, optional layer freezing); "
             "reports held-out accuracy before/after",
    )
    ft.add_argument("--checkpoint", required=True)
    ft.add_argument("--dataset", default=None,
                    choices=["wisdm", "wisdm_raw", "ucihar", "synthetic"],
                    help="defaults to the checkpoint's recorded dataset")
    ft.add_argument("--data-path", default=None)
    ft.add_argument("--train-fraction", type=float, default=None,
                    help="defaults to the checkpoint's recorded value "
                         "(0.7 for older checkpoints)")
    ft.add_argument("--seed", type=int, default=None,
                    help="split seed; defaults to the checkpoint's "
                         "recorded value (2018 for older checkpoints) — "
                         "a mismatched seed would score 'held-out' rows "
                         "the checkpoint trained on")
    ft.add_argument("--epochs", type=int, default=20)
    ft.add_argument("--learning-rate", type=float, default=3e-4)
    ft.add_argument("--batch-size", type=int, default=256)
    ft.add_argument("--freeze", nargs="+", default=None,
                    help="top-level param modules to freeze "
                         "(e.g. ConvBlock_0 ConvBlock_1)")
    ft.add_argument("--output", default=None,
                    help="save the fine-tuned model as a new checkpoint")

    ex = sub.add_parser(
        "export",
        help="export a saved neural checkpoint as a self-contained "
             "StableHLO predict artifact (params baked in, symbolic "
             "batch dim, multi-platform) — deployable without har_tpu",
    )
    ex.add_argument("--checkpoint", required=True)
    ex.add_argument("--output", required=True,
                    help="artifact directory (predict.stablehlo + meta)")
    ex.add_argument("--platforms", nargs="+", default=["tpu", "cpu"],
                    help="lowerings to embed (default: tpu cpu)")
    ex.add_argument("--example-shape", nargs="+", type=int, default=None,
                    help="per-example feature shape (e.g. 200 3) for "
                         "checkpoints that record neither a scaler nor "
                         "input_shape")
    ex.add_argument("--quantize", default=None, choices=["int8"],
                    help="weight-only int8 quantization before export "
                         "(per-output-channel scales; weights ship int8 "
                         "in the artifact)")

    ln = sub.add_parser(
        "lint",
        help="harlint: AST-based invariant checker for the fleet stack "
             "(HL001 hot-path host-sync via call-graph reachability, "
             "HL002 state completeness, HL003 journal/replay "
             "exhaustiveness, HL004 determinism, HL005 durability, "
             "HL006 jit-purity, HL007 partition-spec coverage, HL008 "
             "stale suppressions); rc 1 on any non-baselined finding",
    )
    ln.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint (repo-relative); "
                         "default is the fleet-stack fileset "
                         "(har_tpu/serve, har_tpu/adapt, har_tpu/"
                         "parallel, serving.py, utils/durable.py, "
                         "utils/backoff.py)")
    ln.add_argument("--json", action="store_true", dest="as_json",
                    help="one JSON report line (the release gate's "
                         "consumption format) instead of text findings")
    ln.add_argument("--baseline", default=None,
                    help="baseline suppression file (default: "
                         "harlint_baseline.json at the checkout root)")
    ln.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current findings "
                         "(reviewed-debt admission; keep it near-empty)")
    ln.add_argument("--check", action="store_true",
                    help="summary only (no per-finding lines); rc is "
                         "the verdict — the release-gate invocation")
    ln.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="lint only fileset files changed vs a git ref "
                         "(default HEAD) — the fast pre-commit run; "
                         "rc/json semantics unchanged.  HL003 and "
                         "HL008 are skipped (their bijection/staleness "
                         "checks only hold over the full fileset); "
                         "the release gate still runs the full set")
    ln.add_argument("--rule", action="append", default=None,
                    metavar="HL00X",
                    help="run only the named rule (repeatable)")
    ln.add_argument("--stats", action="store_true",
                    help="print per-rule timing + file count after the "
                         "report (slow-rule regressions surface before "
                         "they eat the gate's 5s lint budget); with "
                         "--json the timings ride the report's "
                         "rule_ms/callgraph_ms/lint_ms keys")

    # a stub for discoverability: the real parser lives in
    # har_tpu.serve.net.worker (main() intercepts and forwards before
    # this parser ever sees the argv — the worker must not import the
    # whole CLI surface to start)
    sub.add_parser(
        "serve-worker",
        add_help=False,
        help="one FleetServer worker process on a loopback TCP socket "
             "(har_tpu.serve.net) — the subprocess entrypoint behind "
             "`har serve --workers N --net`, the wire chaos matrix and "
             "the release gate; `har serve-worker --help` for flags",
    )

    # same stub pattern as serve-worker: the real parser lives in
    # har_tpu.serve.net.gateway (main() forwards before this parser runs)
    sub.add_parser(
        "serve-gateway",
        add_help=False,
        help="the fleet's ingest front door "
             "(har_tpu.serve.net.gateway): one process clients speak "
             "the wire protocol to — batched push frames, header-only "
             "edge admission/shedding, multiplexed onto running "
             "`har serve-worker` processes; `har serve-gateway --help` "
             "for flags",
    )

    # same stub pattern as serve-worker: the real parser lives in
    # har_tpu.serve.net.ship (main() forwards before this parser runs)
    sub.add_parser(
        "serve-agent",
        add_help=False,
        help="one journal-ship agent per worker host "
             "(har_tpu.serve.net.ship): serves that host's journal "
             "directories to an adopting controller as chunked, "
             "digest-manifested, resumable transfers — the shared-"
             "nothing failover's hand-off currency; with `--follow "
             "WID=HOST:PORT` it becomes a warm standby that tail-"
             "replicates live workers continuously so failover ships "
             "nothing; `har serve-agent --help` for flags",
    )

    sub.add_parser("bench", help="run the headline benchmark (bench.py)")

    pa = sub.add_parser(
        "parity",
        help="reproduce the reference's result.txt byte-for-byte "
             "(bit-exact MLlib replays: LR, LR-CV, DT, RF)",
    )
    pa.add_argument("--data-path", default=None)
    pa.add_argument("--output-dir", default="parity_result")
    pa.add_argument(
        "--blocks",
        nargs="+",
        default=["lr", "lr_cv", "dt", "rf"],
        choices=["lr", "lr_cv", "dt", "rf"],
        help="which reference blocks to run (default: all four)",
    )
    pa.add_argument(
        "--raw",
        action="store_true",
        help="instead of the result.txt replay, run the raw-WISDM "
             "accuracy lane: window a real WISDM_ar_v1.1_raw.txt "
             "(HAR_TPU_WISDM_RAW / ./data, or --data-path), train the "
             "bench CNN, report held-out accuracy vs the 0.97 target",
    )
    return p


def main(argv=None) -> int:
    import sys as _sys

    argv = list(_sys.argv[1:]) if argv is None else list(argv)
    if argv[:1] == ["serve-worker"]:
        # forwarded verbatim: the worker subprocess parses its own
        # flags (har_tpu.serve.net.worker) and must not pay for — or
        # depend on — the rest of the CLI surface
        from har_tpu.serve.net.worker import main as _worker_main

        return _worker_main(argv[1:])
    if argv[:1] == ["serve-gateway"]:
        # forwarding contract as above: the gateway fronts workers —
        # it parses its own flags and starts without the CLI surface
        from har_tpu.serve.net.gateway import main as _gateway_main

        return _gateway_main(argv[1:])
    if argv[:1] == ["serve-agent"]:
        # same forwarding contract as serve-worker: the ship agent is
        # a byte server — it must start without the CLI (or a jax
        # backend) behind it
        from har_tpu.serve.net.ship import main as _agent_main

        return _agent_main(argv[1:])
    args = _parser().parse_args(argv)

    if args.command == "lint":
        # pure-stdlib path by design: `har lint` must run in the
        # release gate without initializing a jax backend
        from har_tpu.analyze import (
            changed_fileset_paths,
            default_rules,
            repo_root,
            run_harlint,
        )

        rules = None
        if args.rule:
            known = {r.rule_id: r for r in default_rules()}
            bad = [r for r in args.rule if r not in known]
            if bad:
                raise SystemExit(
                    f"unknown rule id(s) {', '.join(bad)} — "
                    f"available: {', '.join(sorted(known))}"
                )
            # dedupe, order-preserving: a repeated --rule HL00X must
            # not run the rule twice (doubled findings, doubled rc)
            rules = [known[r] for r in dict.fromkeys(args.rule)]
        paths = args.paths or None
        if args.changed is not None:
            if paths is not None:
                raise SystemExit(
                    "--changed computes its own path subset; drop the "
                    "explicit paths (or drop --changed)"
                )
            paths = changed_fileset_paths(repo_root(), args.changed)
            if not paths:
                if args.as_json:
                    # --json promises one parseable report line even
                    # for the cleanest commit — same shape, zero files
                    from har_tpu.analyze import LintReport

                    print(json.dumps(LintReport(
                        findings=[], baselined=0,
                        annotation_suppressed=0, rules_run=[],
                        files=0, baseline_path="", baseline_size=0,
                    ).to_json()))
                else:
                    print(
                        f"harlint: no fileset files changed vs "
                        f"{args.changed} — nothing to lint"
                    )
                return 0
        report = run_harlint(
            paths=paths,
            baseline=args.baseline,
            update_baseline=args.update_baseline,
            rules=rules,
        )
        if args.as_json:
            print(json.dumps(report.to_json()))
        elif args.check:
            print(
                f"harlint: {len(report.findings)} finding(s), "
                f"{report.suppressed} suppressed"
            )
        else:
            print(report.render())
        if args.stats and not args.as_json:
            print(report.render_stats())
        return 0 if report.ok else 1

    if args.command == "bench":
        import importlib.util

        # probe for the module itself first: an ImportError raised by
        # bench.py's OWN imports is a real dependency problem and must
        # surface as-is, not as "bench.py not found"
        if importlib.util.find_spec("bench") is None:
            raise SystemExit(
                "the benchmark script bench.py lives at the repository "
                "root (it is not part of the installed package); run "
                "`python bench.py` from a checkout"
            )
        import bench

        bench.main()
        return 0

    if args.command == "parity" and args.raw:
        from har_tpu.parity import wisdm_raw_lane

        out = wisdm_raw_lane(args.data_path)
        print(json.dumps(out))
        # a skip is rc 0 (nothing to measure); a run that misses the
        # target still exits 0 — the JSON verdict is the result
        return 0

    if args.command == "parity":
        from har_tpu.parity import parity_run

        config = None
        if args.data_path is not None:
            # output_dir comes from parity_run's positional arg — the
            # single source of truth (it overwrites the config's anyway)
            config = RunConfig(
                data=DataConfig(dataset="wisdm", path=args.data_path)
            )
        out = parity_run(
            args.output_dir, config=config, blocks=tuple(args.blocks)
        )
        print(json.dumps(out))
        return 0

    if args.command == "sweep":
        from har_tpu.config import MeshConfig
        from har_tpu.runner import sweep

        config = RunConfig(
            data=DataConfig(
                dataset=args.dataset, path=args.data_path, seed=args.seed
            ),
            mesh=MeshConfig(dp=args.dp, tp=args.tp),
            output_dir=args.output_dir,
        )
        sweep(
            config,
            models=args.models,  # runner canonicalizes lr/dt/rf/gbt
            fractions=tuple(args.fractions),
            with_cv=not args.no_cv,
        )
        return 0

    if args.command == "predict":
        if args.artifact is not None:
            from har_tpu.export import predict_artifact as _predict

            src = args.artifact
        else:
            from har_tpu.checkpoint import predict_checkpoint as _predict

            src = args.checkpoint
        print(
            json.dumps(
                _predict(
                    src,
                    args.output,
                    args.data_path,
                    dataset=args.dataset,
                    train_fraction=args.train_fraction,
                    seed=args.seed,
                )
            )
        )
        return 0

    if args.command == "finetune":
        from har_tpu.checkpoint import (
            load_model,
            load_model_meta,
            save_model,
        )
        from har_tpu.ops.metrics import evaluate
        from har_tpu.runner import featurize, load_dataset
        from har_tpu.train.trainer import TrainerConfig
        from har_tpu.transfer import fine_tune

        meta = load_model_meta(args.checkpoint)
        if meta.get("format") == "classical":
            raise SystemExit(
                "finetune covers the neural families; classical models "
                "retrain in seconds — use `har train`"
            )
        # the ONE meta→RunConfig derivation (checkpoint.
        # scoring_config_from_meta): same recorded-split defaults and
        # contradiction guards as evaluate/predict, so a --dataset that
        # conflicts with the checkpoint is refused here too
        from har_tpu.checkpoint import scoring_config_from_meta

        config = scoring_config_from_meta(
            meta, args.data_path, args.dataset, args.train_fraction,
            args.seed,
        )
        dataset = config.data.dataset
        seed = config.data.seed
        train_fraction = config.data.train_fraction
        table = load_dataset(config)
        train, test, _ = featurize(config, table)
        model = load_model(args.checkpoint)
        before = evaluate(
            test.label, model.transform(test.features).raw,
            model.num_classes,
        )["accuracy"]
        tuned = fine_tune(
            args.checkpoint,
            train,
            TrainerConfig(
                batch_size=args.batch_size,
                epochs=args.epochs,
                learning_rate=args.learning_rate,
                seed=seed,
            ),
            freeze=tuple(args.freeze or ()),
            model=model,  # already restored for the before-accuracy
        )
        after = evaluate(
            test.label, tuned.transform(test.features).raw,
            tuned.num_classes,
        )["accuracy"]
        saved = None
        if args.output:
            saved = save_model(
                args.output, tuned, meta["model_name"],
                meta.get("model_kwargs"),
                dataset=dataset,
                synthetic_rows=meta.get("synthetic_rows"),
                drop_binned=meta.get("drop_binned"),
                split_method=meta.get("split_method"),
                input_shape=(
                    tuple(meta["input_shape"])
                    if meta.get("input_shape")
                    else None
                ),
                split_seed=seed,
                train_fraction=train_fraction,
            )
        print(
            json.dumps(
                {
                    "accuracy_before": round(float(before), 4),
                    "accuracy_after": round(float(after), 4),
                    "frozen": list(args.freeze or []),
                    "checkpoint": saved,
                }
            )
        )
        return 0

    if args.command == "export":
        import os as _os

        from har_tpu.export import _BLOB, _META, export_checkpoint

        out = export_checkpoint(
            args.checkpoint, args.output,
            platforms=tuple(args.platforms),
            example_shape=(
                tuple(args.example_shape) if args.example_shape else None
            ),
            quantize=args.quantize,
        )
        with open(_os.path.join(out, _META)) as f:
            art_meta = json.load(f)
        print(
            json.dumps(
                {
                    "artifact": out,
                    "bytes": sum(
                        _os.path.getsize(_os.path.join(out, f))
                        for f in _os.listdir(out)
                    ),
                    "program_bytes": _os.path.getsize(
                        _os.path.join(out, _BLOB)
                    ),
                    "platforms": args.platforms,
                    "quantized": art_meta.get("quantization"),
                }
            )
        )
        return 0

    if args.command == "serve":
        import numpy as np

        from har_tpu.serve import (
            AnalyticDemoModel,
            DeliveryFaults,
            DispatchFaults,
            FleetConfig,
            FleetServer,
            drive_fleet,
            synthetic_sessions,
        )

        window, channels = 200, 3
        if args.checkpoint is not None:
            from har_tpu.checkpoint import load_model, load_model_meta

            model = load_model(args.checkpoint)
            # honor the checkpoint's recorded geometry (the same guard
            # StreamingClassifier.from_checkpoint enforces): a pooled
            # CNN would silently score 200-sample windows it was never
            # trained on — serve at the trained shape instead
            try:
                shape = load_model_meta(args.checkpoint).get("input_shape")
            except OSError:
                shape = None
            if shape and len(shape) == 2:
                window, channels = int(shape[0]), int(shape[1])
            if channels != 3:
                raise SystemExit(
                    f"checkpoint records input_shape={shape}; the "
                    "synthetic fleet load generator emits tri-axial "
                    "(n, 3) streams — serve this checkpoint behind a "
                    "matching transport instead"
                )
        else:
            # training-free analytic model: the scheduler-overhead
            # baseline (a checkpoint adds device dispatch on top).
            # --tier int8 / --fused need a device program, so they get
            # the jitted demo MLP instead.
            if args.tier == "int8" or args.fused:
                from har_tpu.serve import JitDemoModel

                model = JitDemoModel(window=window, channels=channels)
            else:
                model = AnalyticDemoModel()
        if args.tier == "int8":
            from har_tpu.quantize import quantize_serving

            try:
                model = quantize_serving(model)
            except ValueError as exc:
                raise SystemExit(
                    f"--tier int8: {exc} — serve a jitted model "
                    "(--checkpoint with a neural family)"
                )
        if args.fused and args.smoothing == "ema":
            raise SystemExit(
                "--fused needs a fused-eligible smoothing mode "
                "(--smoothing vote|none): EMA smoothing consumes the "
                "full probability vector the fused retire never fetches"
            )
        fault_hook = None
        if args.inject_stall_every:
            fault_hook = DispatchFaults(
                stall_every=args.inject_stall_every,
                stall_ms=args.inject_stall_ms,
            )
        mesh = None
        if args.mesh:
            import jax

            from har_tpu.parallel.mesh import create_mesh

            n_dev = len(jax.devices())
            if args.mesh > n_dev:
                raise SystemExit(
                    f"--mesh {args.mesh} needs {args.mesh} devices but "
                    f"only {n_dev} are visible; on a CPU host run "
                    "under XLA_FLAGS=--xla_force_host_platform_device_"
                    f"count={args.mesh} for a dry-run mesh"
                )
            mesh = create_mesh(
                dp=args.mesh, tp=1, devices=jax.devices()[: args.mesh]
            )
        if args.mesh_shape:
            if args.mesh:
                raise SystemExit(
                    "--mesh-shape and --mesh both name a serving mesh; "
                    "pass one (--mesh-shape BxM covers the 1D case as "
                    "Bx1)"
                )
            import re as _re

            m = _re.fullmatch(r"(\d+)x(\d+)", args.mesh_shape.strip())
            if not m or int(m.group(1)) < 1 or int(m.group(2)) < 1:
                raise SystemExit(
                    f"--mesh-shape {args.mesh_shape!r} is not BxM "
                    "(two positive integers, e.g. 2x4)"
                )
            b, mdl = int(m.group(1)), int(m.group(2))
            import jax

            from har_tpu.parallel.mesh import create_mesh

            n_dev = len(jax.devices())
            if b * mdl > n_dev:
                raise SystemExit(
                    f"--mesh-shape {b}x{mdl} needs {b * mdl} devices "
                    f"but only {n_dev} are visible; on a CPU host run "
                    "under XLA_FLAGS=--xla_force_host_platform_device_"
                    f"count={b * mdl} for a dry-run mesh"
                )
            mesh = create_mesh(
                dp=b, tp=mdl, devices=jax.devices()[: b * mdl]
            )
        journal_cfg = None
        if args.journal:
            from har_tpu.serve import JournalConfig

            journal_cfg = JournalConfig(
                flush_every=args.journal_flush_every,
                snapshot_every=args.journal_snapshot_every,
            )
        if args.trace:
            # elastic traffic (har_tpu.serve.traffic): instead of N
            # flat sessions, drive a seeded arrival process with
            # session churn — and, with --autoscale, let the capacity
            # controller walk target_batch / pipeline_depth (/ the
            # mesh, when --mesh names a ladder ceiling) up the swing
            # and back down through FleetServer.resize's zero-drop
            # dispatch-boundary path
            if (
                args.resume
                or args.adapt
                or args.kill_after_polls
                or (args.workers and args.workers > 1)
                or args.net
                or args.monitor
                or args.inject_drift
                or args.inject_drop
                or args.inject_delay
                or args.calibrate_device
            ):
                # refuse, never silently ignore: every one of these
                # flags is serviced only by the steady N-session path
                raise SystemExit(
                    "--trace drives its own churn fleet; it does not "
                    "combine with --workers/--net/--resume/--adapt/"
                    "--kill-after-polls/--monitor/--inject-drift/"
                    "--inject-drop/--inject-delay/--calibrate-device "
                    "(run those modes against the steady N-session "
                    "load)"
                )
            from har_tpu.data.raw_windows import synthetic_raw_stream
            from har_tpu.serve.traffic import (
                AutoscaleConfig,
                CapacityController,
                TraceSpec,
                TrafficTrace,
                drive_trace,
                undeclared_drops,
            )

            # label names only — trace mode builds its own sample pool
            # inside drive_trace, so the steady-state recording corpus
            # is never generated here
            class_names = synthetic_raw_stream(
                n_windows=1, seed=args.seed, window=window
            ).class_names
            rounds = args.trace_rounds
            spec = TraceSpec(
                kind=args.trace,
                peak_sessions=args.sessions,
                swing=10.0,
                rounds=rounds,
                period=rounds,
                # the overnight cohort leaves on the downslope
                storms=(
                    ((int(rounds * 0.65), 0.5),)
                    if args.trace == "storm"
                    else ()
                ),
                burst_prob=0.15 if args.trace == "bursty" else 0.0,
                burst_size=max(2, args.sessions // 8),
                slow_prob=0.02,
                slow_rounds=3,
                rate_mix=(1, 1, 2),
                seed=args.seed,
            )
            trace = TrafficTrace(spec)
            # autoscaled runs START at the controller's floor — the
            # whole point is capacity tracking the swing up from the
            # trough; static runs serve the configured batch throughout.
            # A --target-batch below the default floor LOWERS the floor
            # (never silently unreachable); above it, it is the ceiling.
            floor_tb = min(16, args.target_batch)
            initial_tb = floor_tb if args.autoscale else args.target_batch
            server = FleetServer(
                model,
                window=window,
                channels=channels,
                hop=args.hop,
                smoothing=args.smoothing,
                class_names=class_names,
                config=FleetConfig.for_sessions(
                    # churn can hold leavers through their settle while
                    # arrivals admit: headroom over the peak
                    max(2 * args.sessions, 64),
                    target_batch=initial_tb,
                    max_delay_ms=args.max_delay_ms,
                    pipeline_depth=(
                        1 if args.autoscale else args.pipeline_depth
                    ),
                    fused=args.fused,
                    profile_host=args.profile_host,
                ),
                fault_hook=fault_hook,
                journal=args.journal,
                journal_config=journal_cfg,
                mesh=None if args.autoscale else mesh,
            )
            controller = None
            if args.autoscale:
                ladder = (1,)
                mesh_for = None
                if args.mesh and args.mesh > 1:
                    import jax as _jax

                    from har_tpu.parallel.mesh import create_mesh

                    ladder = (1, args.mesh)
                    mesh_for = lambda d: create_mesh(
                        dp=d, tp=1, devices=_jax.devices()[:d]
                    )
                controller = CapacityController(
                    server,
                    config=AutoscaleConfig(
                        min_target_batch=floor_tb,
                        # the operator's --target-batch IS the ceiling
                        # (floor <= ceiling by construction): the
                        # controller may batch smaller, never bigger
                        max_target_batch=args.target_batch,
                        min_depth=1,
                        max_depth=max(args.pipeline_depth, 2),
                        mesh_ladder=ladder,
                        up_after=1,
                        down_after=3,
                        cooldown_s=0.0,
                    ),
                    mesh_for=mesh_for,
                )
            import time as _time

            t0 = _time.perf_counter()
            events, report = drive_trace(
                server,
                trace,
                on_round=(
                    controller.on_round if controller is not None else None
                ),
            )
            duration = _time.perf_counter() - t0
            snap = server.stats_snapshot()
            acct = snap["accounting"]
            print(
                json.dumps(
                    {
                        "trace": spec.kind,
                        "trace_spec": trace.spec(),
                        "rounds": report.rounds,
                        "peak_active": report.peak_active,
                        "trough_active": report.trough_active,
                        "connects": report.connects,
                        "disconnects": report.disconnects,
                        "storm_disconnects": report.storm_disconnects,
                        "slow_stalls": report.slow_stalls,
                        "n_events": len(events),
                        "enqueued": acct["enqueued"],
                        "scored": acct["scored"],
                        "dropped": acct["dropped"],
                        "undeclared_drops": undeclared_drops(snap),
                        "balanced": acct["balanced"],
                        "windows_per_sec": (
                            round(acct["scored"] / duration, 1)
                            if duration
                            else None
                        ),
                        "event_p99_ms": snap["stages"]["event_ms"].get(
                            "p99_ms"
                        ),
                        "autoscale": (
                            None
                            if controller is None
                            else controller.status()
                        ),
                        "resizes": snap["resizes"],
                        "scale_ups": snap["scale_ups"],
                        "scale_downs": snap["scale_downs"],
                        "target_batch_final": server.config.target_batch,
                        "pipeline_depth_final": (
                            server.config.pipeline_depth
                        ),
                        "host_profile": snap.get("host_profile"),
                        "journal": args.journal,
                    }
                )
            )
            return 0

        recordings, class_names = synthetic_sessions(
            args.sessions,
            windows_per_session=args.windows_per_session,
            window=window,
            seed=args.seed,
        )
        # reference stats come from the CLEAN pool (computed before the
        # drift mutation, so injected drift is drift relative to the
        # trained distribution) — and only when a monitor needs them:
        # a plain `serve` must not duplicate the whole fleet's samples,
        # and the concatenated copy is dropped as soon as the two
        # per-channel moments are out
        monitor_ref = None
        if args.monitor or args.adapt:
            pool = np.concatenate(recordings)
            monitor_ref = (pool.mean(axis=0), pool.std(axis=0))
            del pool
        # a fraction: clamp to [0, 1] so --inject-drift 1.5 means "all
        # sessions", not an index past the recordings list
        n_drifted = int(
            args.sessions * min(max(args.inject_drift, 0.0), 1.0)
        )
        if n_drifted:
            # population-scale sensor re-mount: the first n_drifted
            # sessions' second halves shift far out of distribution
            for i in range(n_drifted):
                rec = recordings[i].copy()
                rec[len(rec) // 2 :] += 25.0
                recordings[i] = rec
        if args.net and not (args.workers and args.workers > 1):
            raise SystemExit(
                "--net is the multi-worker transport; pair it with "
                "--workers N (N >= 2)"
            )
        if args.workers and args.workers > 1:
            # multi-worker control plane (har_tpu.serve.cluster):
            # sessions partition across N journaled FleetServers behind
            # the consistent-hash router; --kill-worker demos a live
            # failover (journal hand-off migration, global conservation)
            if (
                args.resume or args.adapt or args.mesh
                or args.mesh_shape or args.checkpoint
            ):
                raise SystemExit(
                    "--workers drives the analytic demo fleet; it does "
                    "not combine with --resume/--adapt/--mesh/"
                    "--mesh-shape/--checkpoint (each worker is an "
                    "unmodified FleetServer — run those modes "
                    "single-process)"
                )
            if args.net:
                # REAL transport (har_tpu.serve.net): OS subprocess
                # workers on loopback sockets, real clocks, RPC framing
                if args.inject_stall_every or args.monitor:
                    raise SystemExit(
                        "--net workers run in their own processes; "
                        "--inject-stall-*/--monitor are in-process "
                        "harness hooks (run them without --net)"
                    )
                if args.fused or args.tier != "f32":
                    raise SystemExit(
                        "--net workers serve their own named model "
                        "pool (`har serve-worker --model demo`); "
                        "--fused/--tier are per-worker serving knobs "
                        "the wire does not carry yet — run them "
                        "without --net"
                    )
                if args.pipeline_depth != 1 or args.profile_host:
                    # refuse, never silently ignore: launch_workers
                    # does not forward these per-worker knobs yet
                    raise SystemExit(
                        "--net does not carry --pipeline-depth/"
                        "--profile-host to the worker processes yet; "
                        "run them without --net (or start workers "
                        "directly with `har serve-worker`)"
                    )
                import shutil
                import tempfile
                import time as _time

                from har_tpu.serve.net.chaos import (
                    _drive_net_cluster,
                    _net_cluster_config,
                )
                from har_tpu.serve.net.controller import (
                    NetCluster,
                    launch_workers,
                )
                from har_tpu.serve.net.worker import model_pool

                # the controller's failover restores score with THE
                # SAME pool the workers serve (version -> model), so
                # re-derived windows stay bit-identical to acked ones
                pool = model_pool("demo")

                cluster_tmp = None
                root = args.journal
                if root is None:
                    cluster_tmp = root = tempfile.mkdtemp(
                        prefix="har_netcluster_"
                    )
                procs = {}
                try:
                    net_workers = launch_workers(
                        root,
                        args.workers,
                        window=window,
                        hop=args.hop,
                        channels=channels,
                        smoothing=args.smoothing,
                        max_sessions=max(args.sessions, 64),
                        target_batch=args.target_batch,
                        max_delay_ms=args.max_delay_ms,
                        flush_every=args.journal_flush_every,
                        snapshot_every=args.journal_snapshot_every,
                    )
                    procs.update(
                        {w.worker_id: w.process for w in net_workers}
                    )
                    cluster = NetCluster(
                        pool["A"],
                        root,
                        _workers=net_workers,
                        config=_net_cluster_config(),
                        loader=lambda ver: pool.get(ver, pool["A"]),
                    )
                    if args.kill_worker is not None and (
                        args.kill_worker not in cluster.workers
                    ):
                        raise SystemExit(
                            f"--kill-worker {args.kill_worker!r}: "
                            f"cluster workers are "
                            f"{list(cluster.workers)}"
                        )
                    for i in range(args.sessions):
                        cluster.add_session(i)
                    events = []
                    killed = {"done": False}

                    def on_round(c):
                        # a REAL SIGKILL of the named worker process
                        # once windows are flowing — detection, restore
                        # and migration then run on the protocol alone
                        if (
                            args.kill_worker is not None
                            and not killed["done"]
                        ):
                            try:
                                scored = c.accounting()["scored"]
                            except Exception:
                                return
                            if scored > 0:
                                procs[args.kill_worker].kill()
                                killed["done"] = True

                    t0 = _time.perf_counter()
                    _drive_net_cluster(
                        cluster,
                        recordings,
                        [0] * args.sessions,
                        max(map(len, recordings)),
                        args.hop,
                        events,
                        on_round,
                    )
                    duration = _time.perf_counter() - t0
                    stats = cluster.cluster_stats()
                    acct = stats["accounting"]
                    print(
                        json.dumps(
                            {
                                "sessions": args.sessions,
                                "workers": stats["workers"],
                                "transport": "tcp",
                                "n_events": len(events),
                                "enqueued": acct["enqueued"],
                                "scored": acct["scored"],
                                "dropped": acct["dropped"],
                                "pending": acct["pending"],
                                "balanced": acct["balanced"],
                                "windows_per_sec": (
                                    round(acct["scored"] / duration, 1)
                                    if duration
                                    else None
                                ),
                                "failovers": stats["failovers"],
                                "failover_ms": stats["failover_ms"],
                                "migrated_sessions": max(
                                    stats["migrated_sessions"],
                                    stats["migrations"],
                                ),
                                "per_worker_sessions": stats[
                                    "per_worker_sessions"
                                ],
                                "rpc": cluster.transport_stats(),
                                "killed_worker": (
                                    args.kill_worker
                                    if killed["done"]
                                    else None
                                ),
                                "cluster_root": root,
                            }
                        )
                    )
                    cluster.shutdown_workers()
                    cluster.close()
                finally:
                    # a failed drive must not leak worker processes —
                    # and never delete the journal root under live
                    # writers (clean exits already reaped: kill is a
                    # no-op on an exited process)
                    for proc in procs.values():
                        if proc.poll() is None:
                            proc.kill()
                    if cluster_tmp is not None:
                        shutil.rmtree(cluster_tmp, ignore_errors=True)
                return 0
            import shutil
            import tempfile
            import time as _time

            from har_tpu.serve import FakeClock, FleetConfig
            from har_tpu.serve.chaos import _drive_cluster
            from har_tpu.serve.cluster import ClusterConfig, FleetCluster

            cluster_tmp = None
            root = args.journal
            if root is None:
                cluster_tmp = root = tempfile.mkdtemp(
                    prefix="har_cluster_"
                )
            clock = FakeClock()
            # the single-server --inject-stall-* flags apply per
            # worker here (each worker gets its own fault hook on the
            # shared fake clock) — requested fault injection must
            # never be silently dropped
            cluster_fault_hook_for = None
            if args.inject_stall_every:
                cluster_fault_hook_for = lambda wid: DispatchFaults(
                    stall_every=args.inject_stall_every,
                    stall_ms=args.inject_stall_ms,
                    fake_clock=clock,
                )
            cluster = FleetCluster(
                model,
                root,
                workers=args.workers,
                fault_hook_for=cluster_fault_hook_for,
                window=window,
                hop=args.hop,
                channels=channels,
                smoothing=args.smoothing,
                class_names=class_names,
                fleet_config=FleetConfig.for_sessions(
                    args.sessions,
                    target_batch=args.target_batch,
                    max_delay_ms=args.max_delay_ms,
                    pipeline_depth=args.pipeline_depth,
                    fused=args.fused,
                    profile_host=args.profile_host,
                ),
                config=ClusterConfig(
                    lease_s=0.5, probe_base_ms=20.0, probe_cap_ms=200.0
                ),
                journal_config=journal_cfg,
                clock=clock,
            )
            try:
                from har_tpu.monitoring import DriftMonitor

                for i in range(args.sessions):
                    cluster.add_session(
                        i,
                        monitor=(
                            DriftMonitor(*monitor_ref)
                            if monitor_ref is not None
                            else None
                        ),
                    )
                if args.kill_worker is not None and (
                    args.kill_worker not in cluster.workers
                ):
                    raise SystemExit(
                        f"--kill-worker {args.kill_worker!r}: cluster "
                        f"workers are {list(cluster.workers)}"
                    )
                events = []
                cursors = [0] * args.sessions
                killed = {"done": False}

                def on_round(c):
                    # SIGKILL the named worker once windows are flowing
                    # — the failure detector + journal hand-off then
                    # migrate its partition live
                    if (
                        args.kill_worker is not None
                        and not killed["done"]
                        and c.accounting()["scored"] > 0
                        and args.kill_worker in c._workers
                    ):
                        c._workers[args.kill_worker].kill()
                        killed["done"] = True

                t0 = _time.perf_counter()
                _drive_cluster(
                    cluster,
                    recordings,
                    cursors,
                    max(map(len, recordings)),
                    args.hop,
                    clock,
                    events,
                    on_round,
                )
                duration = _time.perf_counter() - t0
                stats = cluster.cluster_stats()
                acct = stats["accounting"]
                print(
                    json.dumps(
                        {
                            "sessions": args.sessions,
                            "workers": stats["workers"],
                            "n_events": len(events),
                            "enqueued": acct["enqueued"],
                            "scored": acct["scored"],
                            "dropped": acct["dropped"],
                            "pending": acct["pending"],
                            "balanced": acct["balanced"],
                            "windows_per_sec": (
                                round(acct["scored"] / duration, 1)
                                if duration
                                else None
                            ),
                            "failovers": stats["failovers"],
                            "migrated_sessions": stats[
                                "migrated_sessions"
                            ],
                            "migration_ms": stats["migration_ms"],
                            "per_worker_sessions": stats[
                                "per_worker_sessions"
                            ],
                            "retired": stats["retired"],
                            "killed_worker": (
                                args.kill_worker
                                if killed["done"]
                                else None
                            ),
                            "cluster_root": root,
                        }
                    )
                )
                cluster.close()
            finally:
                if cluster_tmp is not None:
                    shutil.rmtree(cluster_tmp, ignore_errors=True)
            return 0

        recovered_events = []
        if args.resume:
            if not args.journal:
                raise SystemExit("--resume requires --journal DIR")
            if args.adapt and args.registry is None:
                raise SystemExit(
                    "--resume --adapt needs a durable --registry DIR "
                    "(the registry pointer is what recovery reconciles "
                    "the fleet against)"
                )
            # recovery: snapshot + journal-suffix replay rebuilds the
            # sessions (monitors included) and the pending queue; the
            # synthetic transport then re-delivers from each session's
            # recovered watermark — zero windows lost, zero re-emitted
            server = FleetServer.restore(
                args.journal,
                lambda ver: model,
                fault_hook=fault_hook,
                journal_config=journal_cfg,
                mesh=mesh,
            )
            recovered_events = server.poll(force=True)
            recordings = [
                rec[server.watermark(i):] if i in server._sessions else rec
                for i, rec in enumerate(recordings)
            ]
        else:
            server = FleetServer(
                model,
                window=window,
                channels=channels,
                hop=args.hop,
                smoothing=args.smoothing,
                class_names=class_names,
                config=FleetConfig.for_sessions(
                    args.sessions,
                    target_batch=args.target_batch,
                    max_delay_ms=args.max_delay_ms,
                    pipeline_depth=args.pipeline_depth,
                    fused=args.fused,
                    profile_host=args.profile_host,
                ),
                fault_hook=fault_hook,
                journal=args.journal,
                journal_config=journal_cfg,
                mesh=mesh,
            )
            from har_tpu.monitoring import DriftMonitor

            # --adapt tightens the monitor (faster EWMA, shorter
            # debounce) so the demo loop closes within a short
            # synthetic drive; plain --monitor keeps the r7 defaults
            # (20 s halflife, patience 3)
            mon_kwargs = (
                {"halflife": 100.0, "patience": 2} if args.adapt else {}
            )
            for i in range(args.sessions):
                server.add_session(
                    i,
                    monitor=(
                        DriftMonitor(*monitor_ref, **mon_kwargs)
                        if monitor_ref is not None
                        else None
                    ),
                )
        engine = None
        registry_tmp = None
        try:
            if args.adapt:
                import tempfile

                from har_tpu.adapt import (
                    AdaptationConfig,
                    AdaptationEngine,
                    ModelRegistry,
                    ShadowConfig,
                    TriggerConfig,
                )

                registry_root = args.registry
                if registry_root is None:
                    registry_tmp = registry_root = tempfile.mkdtemp(
                        prefix="har_registry_"
                    )

                # demo retrainer: a deterministic same-family refit —
                # the loop's plumbing (trigger → shadow → swap →
                # probation) is what this subcommand demonstrates; a
                # real deployment passes a retrainer that fits on
                # job.replay + its seed set
                def retrainer(job):
                    return (
                        AnalyticDemoModel()
                        if args.checkpoint is None
                        else model
                    )

                engine = AdaptationEngine(
                    server,
                    ModelRegistry(registry_root),
                    retrainer,
                    config=AdaptationConfig(probation_dispatches=2),
                    trigger_config=TriggerConfig(
                        min_sessions=(
                            max(2, n_drifted // 2) if n_drifted else 3
                        ),
                        window_s=1e9,
                        cooldown_s=1e9,
                    ),
                    shadow_config=ShadowConfig(
                        sample_every=1, min_windows=16
                    ),
                    resume=args.resume,
                    loader=(lambda ver: retrainer(None)),
                )
            polls = {"n": 0}

            def on_poll(srv, rnd):
                if engine is not None:
                    engine.step()
                polls["n"] += 1
                if (
                    args.kill_after_polls
                    and polls["n"] >= args.kill_after_polls
                ):
                    # SIGKILL stand-in: no flush, no cleanup — only
                    # what the journal already fsynced survives
                    import os as _os

                    print(
                        f"kill-after-polls: exiting hard at poll "
                        f"{polls['n']}",
                        file=sys.stderr,
                    )
                    _os._exit(17)

            events, report = drive_fleet(
                server,
                recordings,
                seed=args.seed,
                faults=DeliveryFaults(
                    drop_prob=args.inject_drop,
                    delay_prob=args.inject_delay,
                ),
                on_poll=(
                    on_poll
                    if (engine is not None or args.kill_after_polls)
                    else None
                ),
            )
            events = recovered_events + events
            if args.calibrate_device:
                try:
                    server.calibrate_device()
                except ValueError as e:
                    print(f"warning: device calibration skipped: {e}",
                          file=sys.stderr)
            snap = server.stats_snapshot()
            acct = snap["accounting"]
            print(
                json.dumps(
                    {
                        "sessions": args.sessions,
                        "n_events": len(events),
                        "enqueued": acct["enqueued"],
                        "scored": acct["scored"],
                        "dropped": acct["dropped"],
                        "windows_per_sec": (
                            round(acct["scored"] / report.duration_s, 1)
                            if report.duration_s
                            else None
                        ),
                        "event_p50_ms": snap["stages"]["event_ms"].get(
                            "p50_ms"
                        ),
                        "event_p99_ms": snap["stages"]["event_ms"].get(
                            "p99_ms"
                        ),
                        "degraded_events": snap["degraded_events"],
                        "pipeline_depth": snap["pipeline_depth"],
                        "devices": snap["devices"],
                        "overlap_pct": snap["overlap_pct"],
                        "drift_events": sum(
                            1 for ev in events if ev.event.drift
                        ),
                        "adapt": (
                            None if engine is None else engine.status()
                        ),
                        "journal": args.journal,
                        "resumed": bool(args.resume),
                        "recoveries": snap["recoveries"],
                        "lost_in_crash": acct["lost_in_crash"],
                        # per-poll host-time breakdown (--profile-host:
                        # ingest / due-select / gather / retire /
                        # journal stage histograms) — the host-plane
                        # observability hook the ceiling curve reads
                        "host_profile": snap.get("host_profile"),
                        "load": dataclasses.asdict(report),
                        "stats": snap,
                    }
                )
            )
        finally:
            # the throwaway registry must not survive a failed drive
            # (KeyboardInterrupt included) any more than a clean one
            if registry_tmp is not None:
                import shutil

                shutil.rmtree(registry_tmp, ignore_errors=True)
        return 0

    if args.command == "stream":
        import numpy as np

        from har_tpu.serving import StreamingClassifier

        try:
            sc = StreamingClassifier.from_checkpoint(
                args.checkpoint,
                window=args.window,
                hop=args.hop,
                smoothing=args.smoothing,
                monitor="auto" if args.monitor else None,
            )
        except ValueError as e:
            raise SystemExit(str(e))  # clean message, not a traceback
        if args.input is not None:
            rec = np.loadtxt(args.input, delimiter=",", dtype=np.float32)
        else:
            # synthetic demo: three activity stretches from the
            # calibrated generator's class family
            from har_tpu.data.raw_windows import synthetic_raw_stream

            raw = synthetic_raw_stream(n_windows=24, seed=0)
            thirds = [
                raw.windows[raw.labels == c][:4].reshape(-1, 3)
                for c in (0, 1, 0)
            ]
            rec = np.concatenate(thirds)
        # live cadence + device-vs-tunnel latency split: see
        # StreamingClassifier.replay
        events = sc.replay(rec)
        if args.events_csv:
            import csv as _csv

            with open(args.events_csv, "w", newline="") as f:
                w = _csv.writer(f)
                n_probs = len(events[0].probability) if events else 0
                w.writerow(
                    ["t_index", "label", "raw_label", "latency_ms"]
                    + [f"p{i}" for i in range(n_probs)]
                )
                for e in events:
                    w.writerow(
                        [e.t_index, e.label, e.raw_label,
                         round(e.latency_ms, 3)]
                        + [round(float(p), 6) for p in e.probability]
                    )
        from har_tpu.serving import SessionResult

        # one run-length merge implementation for both surfaces: build a
        # SessionResult over the (smoothed) event labels and reuse it
        sr = SessionResult(
            t_index=np.array([e.t_index for e in events], np.int64),
            labels=np.array([e.label for e in events], np.int32),
            probability=(
                np.stack([e.probability for e in events])
                if events
                else np.zeros((0, 0), np.float64)
            ),
        )
        timeline = [
            {"from_t": a, "to_t": b, "label": lab}
            for a, b, lab in sr.segments()
        ]
        drift = None
        if args.monitor and sc.drift_report is not None:
            rep = sc.drift_report
            drift = {
                "drifting": rep.drifting,
                "events_flagged": sum(1 for e in events if e.drift),
                "location_z": [round(float(z), 3) for z in rep.location_z],
                "scale_log_ratio": [
                    round(float(r), 3) for r in rep.scale_log_ratio
                ],
            }
        print(
            json.dumps(
                {
                    "n_samples": int(len(rec)),
                    "n_events": len(events),
                    "timeline": timeline,
                    "latency": sc.latency_stats(),
                    "drift": drift,
                    "events_csv": args.events_csv,
                }
            )
        )
        return 0

    if args.command == "evaluate":
        if args.artifact is not None:
            from har_tpu.export import evaluate_artifact

            out = evaluate_artifact(
                args.artifact,
                args.data_path,
                dataset=args.dataset,
                train_fraction=args.train_fraction,
                seed=args.seed,
            )
        else:
            from har_tpu.checkpoint import evaluate_checkpoint

            out = evaluate_checkpoint(
                args.checkpoint,
                args.data_path,
                dataset=args.dataset,
                train_fraction=args.train_fraction,
                seed=args.seed,
            )
        print(json.dumps(out))
        return 0

    # train
    if args.validation_fraction is not None and not args.early_stop_patience:
        raise SystemExit(
            "--validation-fraction only takes effect with "
            "--early-stop-patience; set both or neither"
        )
    from har_tpu.config import MeshConfig
    from har_tpu.runner import canonical_model_name

    if getattr(args, "distributed", False):
        # must run before the first jax device query on every host
        from har_tpu.parallel.mesh import initialize_distributed

        initialize_distributed(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    elif any(
        getattr(args, k) is not None
        for k in ("coordinator", "num_processes", "process_id")
    ):
        raise SystemExit(
            "--coordinator/--num-processes/--process-id require "
            "--distributed"
        )

    models = [canonical_model_name(m) for m in args.models]
    neural_params = {}
    for k in ("epochs", "batch_size", "learning_rate",
              "checkpoint_dir", "save_every_epochs",
              "early_stop_patience", "validation_fraction", "augment",
              "class_weight"):
        v = getattr(args, k)
        if v is not None:
            neural_params[k] = v
    config = RunConfig(
        data=DataConfig(
            dataset=args.dataset,
            path=args.data_path,
            drop_binned=not args.keep_binned,
            train_fraction=args.train_fraction,
            seed=args.seed,
            split_method=args.split_method,
        ),
        model=ModelConfig(name=models[0], params=neural_params),
        mesh=MeshConfig(dp=args.dp, tp=args.tp),
        tuning=TuningConfig(selection_metric=args.cv_metric),
        output_dir=args.output_dir,
    )
    from har_tpu.runner import run
    from har_tpu.utils.profiling import trace

    with trace(args.trace_dir):
        outcome = run(
            config, models=models, with_cv=not args.no_cv, with_eda=args.eda,
            save_models_dir=args.save_models_dir,
        )
    print(json.dumps({"accuracies": outcome.accuracies,
                      "artifacts": outcome.report_paths}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
