"""Orbax checkpointing: params, optimizer state, pipeline vocabularies.

The reference persists nothing but metrics — models live and die in-process
(SURVEY §5.4: the only persistence gesture is a commented-out to_csv).  A
real framework needs restartable training and servable artifacts, so:

  - :func:`save_model` / :func:`load_model` — a trained NeuralClassifier
    (Flax params + module config + feature scaler) as one checkpoint dir.
  - :class:`TrainCheckpointer` — mid-training (params, opt_state, epoch)
    snapshots for resume; the optimizer state carries the LR-schedule
    step, so a resumed cosine schedule continues where it stopped.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from har_tpu.features.scaler import FittedScaler
from har_tpu.models.neural import build_model
from har_tpu.models.neural_classifier import NeuralClassifierModel
from har_tpu.train.trainer import NeuralModel

_META = "har_meta.json"


def _abspath(path: str) -> str:
    return os.path.abspath(os.path.expanduser(path))


def save_model(path: str, model: NeuralClassifierModel, model_name: str,
               model_kwargs: dict | None = None,
               dataset: str | None = None,
               synthetic_rows: int | None = None) -> str:
    """Persist a trained neural classifier (params + scaler + config).

    ``dataset`` (and ``synthetic_rows`` for synthetic fallbacks) records
    what the model was trained on, so `evaluate_checkpoint` can re-derive
    the matching test features without the caller re-stating it.
    """
    path = _abspath(path)
    os.makedirs(path, exist_ok=True)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(
            os.path.join(path, "params"),
            jax.device_get(model.inner.params),
            force=True,
        )
    meta: dict[str, Any] = {
        "model_name": model_name,
        "model_kwargs": model_kwargs or {},
        "num_classes": model.num_classes,
    }
    if dataset is not None:
        meta["dataset"] = dataset
    if synthetic_rows is not None:
        meta["synthetic_rows"] = synthetic_rows
    if model.scaler is not None:
        meta["scaler"] = {
            "mean": np.asarray(model.scaler.mean).tolist(),
            "std": np.asarray(model.scaler.std).tolist(),
        }
    with open(os.path.join(path, _META), "w") as f:
        json.dump(meta, f)
    return path


def load_model(path: str) -> NeuralClassifierModel:
    path = _abspath(path)
    with open(os.path.join(path, _META)) as f:
        meta = json.load(f)
    with ocp.PyTreeCheckpointer() as ckptr:
        params = ckptr.restore(os.path.join(path, "params"))
    module = build_model(
        meta["model_name"],
        num_classes=meta["num_classes"],
        **{
            k: (tuple(v) if isinstance(v, list) else v)
            for k, v in meta["model_kwargs"].items()
        },
    )
    scaler = None
    if "scaler" in meta:
        scaler = FittedScaler(
            mean=np.asarray(meta["scaler"]["mean"], np.float32),
            std=np.asarray(meta["scaler"]["std"], np.float32),
        )
    inner = NeuralModel(
        module=module, params=params, num_classes=meta["num_classes"]
    )
    return NeuralClassifierModel(
        inner=inner, scaler=scaler, num_classes=meta["num_classes"]
    )


@dataclasses.dataclass
class TrainCheckpointer:
    """Mid-training snapshots: (params, opt_state, epoch) for resume."""

    directory: str
    keep: int = 3

    def __post_init__(self):
        self.directory = _abspath(self.directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=self.keep),
        )

    def save(self, epoch: int, params, opt_state) -> None:
        state = {
            "params": jax.device_get(params),
            "opt_state": jax.device_get(opt_state),
        }
        self._mgr.save(epoch, args=ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()

    def latest_epoch(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, epoch: int | None = None, template=None):
        epoch = epoch if epoch is not None else self.latest_epoch()
        if epoch is None:
            return None
        if template is not None:
            restored = self._mgr.restore(
                epoch, args=ocp.args.StandardRestore(template)
            )
        else:
            restored = self._mgr.restore(epoch)
        return epoch, restored["params"], restored["opt_state"]

    def close(self) -> None:
        self._mgr.close()


def evaluate_checkpoint(
    path: str,
    data_path: str | None = None,
    dataset: str | None = None,
    train_fraction: float = 0.7,
    seed: int = 2018,
    synthetic_rows: int | None = None,
) -> dict:
    """CLI `evaluate` backend: load a checkpoint, score it on held-out data.

    ``train_fraction``/``seed`` must match the values the checkpoint was
    trained with — the test partition is re-derived from them, so a
    mismatch would leak training rows into the score.  The feature view
    is re-derived from the checkpoint's saved model name + dataset
    through the same runner logic that trained it; ``dataset=None``
    uses the recorded one, and an explicit value that contradicts the
    recording is refused (the features would not match the params).
    """
    from har_tpu.config import DataConfig, ModelConfig, RunConfig
    from har_tpu.ops.metrics import evaluate
    from har_tpu.runner import featurize, load_dataset

    model = load_model(path)
    with open(os.path.join(_abspath(path), _META)) as f:
        meta = json.load(f)
    model_name = meta["model_name"]
    saved_dataset = meta.get("dataset")
    if dataset is None:
        dataset = saved_dataset or "wisdm"
    elif saved_dataset is not None and dataset != saved_dataset:
        raise ValueError(
            f"checkpoint was trained on dataset {saved_dataset!r}; "
            f"evaluating against {dataset!r} would derive a different "
            "feature view than the saved parameters expect"
        )
    saved_rows = meta.get("synthetic_rows")
    if synthetic_rows is None:
        synthetic_rows = saved_rows
    elif saved_rows is not None and synthetic_rows != saved_rows:
        raise ValueError(
            f"checkpoint was trained with synthetic_rows={saved_rows}; "
            f"evaluating against synthetic_rows={synthetic_rows} would "
            "regenerate different data than the saved parameters saw"
        )
    config = RunConfig(
        data=DataConfig(
            dataset=dataset,
            path=data_path,
            train_fraction=train_fraction,
            seed=seed,
            synthetic_rows=synthetic_rows,
        ),
        model=ModelConfig(name=model_name),
    )
    _, test, _ = featurize(config, load_dataset(config))
    preds = model.transform(test)
    rep = evaluate(test.label, preds.raw, model.num_classes)
    return {
        "accuracy": rep["accuracy"],
        "f1": rep["f1"],
        "n_test": int(len(test)),
    }
