"""Orbax checkpointing: params, optimizer state, pipeline vocabularies.

The reference persists nothing but metrics — models live and die in-process
(SURVEY §5.4: the only persistence gesture is a commented-out to_csv).  A
real framework needs restartable training and servable artifacts, so:

  - :func:`save_model` / :func:`load_model` — a trained NeuralClassifier
    (Flax params + module config + feature scaler) as one checkpoint dir.
  - :func:`save_classical_model` / :func:`load_classical_model` — the
    classical families (LR coefficients, DT/RF tree arrays, GBDT
    ensembles) as npz + JSON; optionally bundling the fitted feature
    pipeline's vocabularies so the artifact can featurize raw tables.
  - :func:`save_pipeline_model` / :func:`load_pipeline_model` — a fitted
    feature Pipeline (StringIndexer vocabs, one-hot cardinalities,
    assembler layout) as JSON.
  - :class:`TrainCheckpointer` — mid-training (params, opt_state, epoch)
    snapshots for resume; the optimizer state carries the LR-schedule
    step, so a resumed cosine schedule continues where it stopped.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from har_tpu.features.scaler import FittedScaler
from har_tpu.models.neural import build_model
from har_tpu.models.neural_classifier import NeuralClassifierModel
from har_tpu.train.trainer import NeuralModel

_META = "har_meta.json"


def _abspath(path: str) -> str:
    return os.path.abspath(os.path.expanduser(path))


def version_info(meta: dict) -> dict:
    """Lineage fields from checkpoint meta, ``None``-defaulted so
    checkpoints saved before the adapt subsystem (no version/parent/
    created stamps) load identically — the one accessor every lineage
    consumer (the model registry, `har serve --adapt`) reads through."""
    return {
        "version": meta.get("version"),
        "parent_sha256": meta.get("parent_sha256"),
        "created_unix": meta.get("created_unix"),
    }


def _stamp_lineage(meta: dict, version, parent_sha256, created_unix) -> None:
    """version / parent_sha256 / created_unix into meta (shared by both
    save paths).  created_unix defaults to now — every new checkpoint is
    lineage-dateable even outside a registry."""
    if version is not None:
        meta["version"] = int(version)
    if parent_sha256 is not None:
        meta["parent_sha256"] = str(parent_sha256)
    meta["created_unix"] = (
        int(time.time()) if created_unix is None else int(created_unix)
    )


def save_model(path: str, model: NeuralClassifierModel, model_name: str,
               model_kwargs: dict | None = None,
               dataset: str | None = None,
               synthetic_rows: int | None = None,
               drop_binned: bool | None = None,
               split_method: str | None = None,
               input_shape: tuple | None = None,
               split_seed: int | None = None,
               train_fraction: float | None = None,
               version: int | None = None,
               parent_sha256: str | None = None,
               created_unix: int | None = None) -> str:
    """Persist a trained neural classifier (params + scaler + config).

    ``dataset`` (and ``synthetic_rows`` for synthetic fallbacks,
    ``drop_binned`` for the feature-view width, ``split_method`` for the
    train/test draw) records what the model was trained on, so
    `evaluate_checkpoint` can re-derive the matching test features without
    the caller re-stating it.  ``version``/``parent_sha256``/
    ``created_unix`` are the adapt registry's lineage stamps (see
    har_tpu.adapt.registry); old checkpoints without them load unchanged
    (``version_info`` defaults the missing fields to None).
    """
    path = _abspath(path)
    os.makedirs(path, exist_ok=True)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(
            os.path.join(path, "params"),
            jax.device_get(model.inner.params),
            force=True,
        )
    meta: dict[str, Any] = {
        "model_name": model_name,
        "model_kwargs": model_kwargs or {},
        "num_classes": model.num_classes,
    }
    _stamp_lineage(meta, version, parent_sha256, created_unix)
    if dataset is not None:
        meta["dataset"] = dataset
    if synthetic_rows is not None:
        meta["synthetic_rows"] = synthetic_rows
    if drop_binned is not None:
        meta["drop_binned"] = drop_binned
    if split_method is not None:
        meta["split_method"] = split_method
    if input_shape is not None:
        # per-example feature shape the params were trained on — e.g.
        # (200, 3) for raw windows; serving validates its window/channel
        # geometry against this (a pooled CNN would otherwise accept any
        # window length and silently emit distribution-shifted output)
        meta["input_shape"] = [int(d) for d in input_shape]
    if split_seed is not None:
        # train/test draw provenance: lets `har finetune` (and future
        # consumers) re-derive the checkpoint's OWN held-out rows
        # instead of measuring "held-out" accuracy on training rows
        meta["split_seed"] = int(split_seed)
    if train_fraction is not None:
        meta["train_fraction"] = float(train_fraction)
    if model.scaler is not None:
        meta["scaler"] = {
            "mean": np.asarray(model.scaler.mean).tolist(),
            "std": np.asarray(model.scaler.std).tolist(),
        }
    with open(os.path.join(path, _META), "w") as f:
        json.dump(meta, f)
    return path


def load_model_meta(path: str) -> dict:
    """The checkpoint's recorded provenance (model name/kwargs, dataset,
    input_shape, ...) without restoring the parameters."""
    with open(os.path.join(_abspath(path), _META)) as f:
        return json.load(f)


def load_model(path: str) -> NeuralClassifierModel:
    meta = load_model_meta(path)
    path = _abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        params = ckptr.restore(os.path.join(path, "params"))
    module = build_model(
        meta["model_name"],
        num_classes=meta["num_classes"],
        **{
            k: (tuple(v) if isinstance(v, list) else v)
            for k, v in meta["model_kwargs"].items()
        },
    )
    scaler = None
    if "scaler" in meta:
        scaler = FittedScaler(
            mean=np.asarray(meta["scaler"]["mean"], np.float32),
            std=np.asarray(meta["scaler"]["std"], np.float32),
        )
    inner = NeuralModel(
        module=module, params=params, num_classes=meta["num_classes"]
    )
    return NeuralClassifierModel(
        inner=inner, scaler=scaler, num_classes=meta["num_classes"]
    )


# ---------------------------------------------------------------------------
# Classical models (LR / DT / RF / GBDT) + pipeline vocabularies
# ---------------------------------------------------------------------------

_ARRAYS = "arrays.npz"
_PIPELINE = "pipeline.json"


def _classical_registry():
    """kind -> (canonical model name, extractor, builder).

    ``extractor(model) -> (arrays, scalars)`` and
    ``builder(arrays, scalars) -> model`` are each other's inverses, so
    every field's save/load mapping lives in exactly this one place.
    Arrays are stored in ``arrays.npz``; scalars go in the JSON metadata.
    """
    from har_tpu.models.forest import RandomForestModel
    from har_tpu.models.gbdt import GradientBoostedTreesModel
    from har_tpu.models.logistic_regression import LogisticRegressionModel
    from har_tpu.models.tree import DecisionTreeModel, TreeArrays

    def flat_extractor(array_fields, scalar_fields):
        def extract(model):
            return (
                {f: np.asarray(getattr(model, f)) for f in array_fields},
                {f: getattr(model, f) for f in scalar_fields},
            )

        return extract

    def extract_tree(model):
        t = model.tree
        arrays = {
            "tree_feature": t.feature,
            "tree_threshold": t.threshold,
            "tree_leaf_class": t.leaf_class,
            "tree_leaf_probs": t.leaf_probs,
        }
        if t.leaf_counts is not None:
            arrays["tree_leaf_counts"] = t.leaf_counts
        return (
            arrays,
            {"max_depth": t.max_depth, "num_classes": model.num_classes},
        )

    def build_tree(arrays, scalars):
        return DecisionTreeModel(
            tree=TreeArrays(
                feature=arrays["tree_feature"],
                threshold=arrays["tree_threshold"],
                leaf_class=arrays["tree_leaf_class"],
                leaf_probs=arrays["tree_leaf_probs"],
                max_depth=scalars["max_depth"],
                # checkpoints predating the raw-counts field fall back to
                # probabilities at transform time
                leaf_counts=arrays.get("tree_leaf_counts"),
            ),
            num_classes=scalars["num_classes"],
        )

    return {
        "LogisticRegressionModel": (
            "logistic_regression",
            flat_extractor(("coefficients", "intercept"), ("num_classes",)),
            lambda a, s: LogisticRegressionModel(
                coefficients=a["coefficients"],
                intercept=a["intercept"],
                num_classes=s["num_classes"],
            ),
        ),
        "DecisionTreeModel": (
            "decision_tree",
            extract_tree,
            build_tree,
        ),
        "RandomForestModel": (
            "random_forest",
            flat_extractor(
                ("feature", "threshold", "leaf_probs"),
                ("max_depth", "num_classes"),
            ),
            lambda a, s: RandomForestModel(
                feature=a["feature"],
                threshold=a["threshold"],
                leaf_probs=a["leaf_probs"],
                max_depth=s["max_depth"],
                num_classes=s["num_classes"],
            ),
        ),
        "GradientBoostedTreesModel": (
            "gbdt",
            flat_extractor(
                ("feature", "split_bin", "leaf_value", "thresholds"),
                ("learning_rate", "max_depth", "num_classes"),
            ),
            lambda a, s: GradientBoostedTreesModel(
                feature=a["feature"],
                split_bin=a["split_bin"],
                leaf_value=a["leaf_value"],
                thresholds=a["thresholds"],
                learning_rate=s["learning_rate"],
                max_depth=s["max_depth"],
                num_classes=s["num_classes"],
            ),
        ),
    }


def _classical_arrays_scalars(model) -> tuple[dict, dict, str, str]:
    """Split a classical model into (arrays, scalars, kind, model_name)."""
    kind = type(model).__name__
    registry = _classical_registry()
    if kind not in registry:
        raise TypeError(
            f"{kind} is not a persistable classical model "
            f"(expected one of {sorted(registry)})"
        )
    model_name, extract, _ = registry[kind]
    arrays, scalars = extract(model)
    return arrays, scalars, kind, model_name


def save_classical_model(
    path: str,
    model,
    dataset: str | None = None,
    synthetic_rows: int | None = None,
    drop_binned: bool | None = None,
    split_method: str | None = None,
    pipeline=None,
    split_seed: int | None = None,
    train_fraction: float | None = None,
    version: int | None = None,
    parent_sha256: str | None = None,
    created_unix: int | None = None,
) -> str:
    """Persist a classical model (and optionally its feature pipeline).

    The reference never saves models (SURVEY §5.4); here every family is a
    servable artifact.  ``pipeline`` — the fitted PipelineModel whose
    vocabularies produced the model's design matrix — is bundled so the
    checkpoint can featurize raw tables without refitting.  Lineage
    stamps (``version``/``parent_sha256``/``created_unix``) follow the
    same contract as :func:`save_model`.
    """
    path = _abspath(path)
    os.makedirs(path, exist_ok=True)
    arrays, scalars, kind, model_name = _classical_arrays_scalars(model)
    np.savez_compressed(os.path.join(path, _ARRAYS), **arrays)
    meta: dict[str, Any] = {
        "format": "classical",
        "kind": kind,
        "model_name": model_name,
        "scalars": {
            k: (v.item() if isinstance(v, np.generic) else v)
            for k, v in scalars.items()
        },
    }
    _stamp_lineage(meta, version, parent_sha256, created_unix)
    if dataset is not None:
        meta["dataset"] = dataset
    if synthetic_rows is not None:
        meta["synthetic_rows"] = synthetic_rows
    if drop_binned is not None:
        meta["drop_binned"] = drop_binned
    if split_method is not None:
        meta["split_method"] = split_method
    if split_seed is not None:
        # same provenance contract as save_model: scoring backends
        # default to the RECORDED split, so a non-default training seed
        # never leaks training rows into the "held-out" score
        meta["split_seed"] = int(split_seed)
    if train_fraction is not None:
        meta["train_fraction"] = float(train_fraction)
    with open(os.path.join(path, _META), "w") as f:
        json.dump(meta, f)
    pipe_path = os.path.join(path, _PIPELINE)
    if pipeline is not None:
        save_pipeline_model(pipe_path, pipeline)
    elif os.path.exists(pipe_path):
        # re-saving a pipeline-less model into an existing dir must not
        # leave a stale vocabulary for evaluate_checkpoint to trust
        os.remove(pipe_path)
    return path


def load_classical_model(path: str):
    path = _abspath(path)
    with open(os.path.join(path, _META)) as f:
        meta = json.load(f)
    if meta.get("format") != "classical":
        raise ValueError(
            f"{path} is not a classical-model checkpoint "
            f"(format={meta.get('format')!r}); use load_model"
        )
    registry = _classical_registry()
    kind = meta["kind"]
    if kind not in registry:
        raise ValueError(f"unknown classical model kind {kind!r}")
    with np.load(os.path.join(path, _ARRAYS)) as npz:
        arrays = {k: npz[k] for k in npz.files}
    return registry[kind][2](arrays, meta["scalars"])


def save_pipeline_model(path: str, pipeline) -> str:
    """Fitted feature pipeline → JSON (vocabularies, cardinalities, layout)."""
    from har_tpu.features.assembler import VectorAssembler
    from har_tpu.features.one_hot import OneHotEncoderModel
    from har_tpu.features.string_indexer import StringIndexerModel

    stages = []
    for stage in pipeline.stages:
        if isinstance(stage, StringIndexerModel):
            stages.append({
                "kind": "StringIndexerModel",
                "input_col": stage.input_col,
                "output_col": stage.output_col,
                "vocab": list(stage.vocab),
                "handle_invalid": stage.handle_invalid,
            })
        elif isinstance(stage, OneHotEncoderModel):
            stages.append({
                "kind": "OneHotEncoderModel",
                "input_col": stage.input_col,
                "output_col": stage.output_col,
                "cardinality": stage.cardinality,
                "drop_last": stage.drop_last,
            })
        elif isinstance(stage, VectorAssembler):
            stages.append({
                "kind": "VectorAssembler",
                "input_cols": list(stage.input_cols),
                "output_col": stage.output_col,
            })
        else:
            raise TypeError(
                f"cannot serialize pipeline stage {type(stage).__name__}"
            )
    path = _abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump({"stages": stages}, f)
    return path


def load_pipeline_model(path: str):
    from har_tpu.features.assembler import VectorAssembler
    from har_tpu.features.one_hot import OneHotEncoderModel
    from har_tpu.features.pipeline import PipelineModel
    from har_tpu.features.string_indexer import StringIndexerModel

    with open(_abspath(path)) as f:
        spec = json.load(f)
    stages = []
    for s in spec["stages"]:
        kind = s["kind"]
        if kind == "StringIndexerModel":
            stages.append(
                StringIndexerModel(
                    s["input_col"], s["output_col"], tuple(s["vocab"]),
                    s["handle_invalid"],
                )
            )
        elif kind == "OneHotEncoderModel":
            stages.append(
                OneHotEncoderModel(
                    s["input_col"], s["output_col"], s["cardinality"],
                    s["drop_last"],
                )
            )
        elif kind == "VectorAssembler":
            stages.append(VectorAssembler(s["input_cols"], s["output_col"]))
        else:
            raise ValueError(f"unknown pipeline stage kind {kind!r}")
    return PipelineModel(stages)


@dataclasses.dataclass
class TrainCheckpointer:
    """Mid-training snapshots: (params, opt_state, epoch) for resume."""

    directory: str
    keep: int = 3

    def __post_init__(self):
        self.directory = _abspath(self.directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=self.keep),
        )

    def save(self, epoch: int, params, opt_state, extra=None) -> None:
        """``extra``: optional pytree snapshotted alongside (the early-
        stopping loop stores its best-iterate state there)."""
        state = {
            "params": jax.device_get(params),
            "opt_state": jax.device_get(opt_state),
        }
        if extra is not None:
            state["extra"] = jax.device_get(extra)
        self._mgr.save(epoch, args=ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()

    def latest_epoch(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, epoch: int | None = None, template=None,
                with_extra: bool = False):
        epoch = epoch if epoch is not None else self.latest_epoch()
        if epoch is None:
            return None
        if template is not None:
            restored = self._mgr.restore(
                epoch, args=ocp.args.StandardRestore(template)
            )
        else:
            restored = self._mgr.restore(epoch)
        if with_extra:
            return (
                epoch,
                restored["params"],
                restored["opt_state"],
                restored.get("extra"),
            )
        return epoch, restored["params"], restored["opt_state"]

    def close(self) -> None:
        self._mgr.close()


def scoring_config_from_meta(
    meta: dict,
    data_path: str | None = None,
    dataset: str | None = None,
    train_fraction: float | None = None,
    seed: int | None = None,
    synthetic_rows: int | None = None,
    what: str = "checkpoint",
):
    """Saved provenance → the RunConfig that re-derives the held-out
    partition.  The ONE derivation for every scoring backend — evaluate/
    predict on checkpoints AND evaluate on exported artifacts — so the
    split semantics cannot drift between them.

    ``None`` for dataset/train_fraction/seed/synthetic_rows means "use
    the recorded value" (falling back to wisdm / 0.7 / 2018 for
    pre-provenance saves); an explicit value that CONTRADICTS a
    recording is refused where it would silently change the feature
    view or regenerate different data.  seed/train_fraction overrides
    are accepted (scoring against a different draw is a legitimate ask)
    but default to the recorded split so a non-default training seed
    never leaks training rows into the "held-out" score.
    """
    from har_tpu.config import DataConfig, ModelConfig, RunConfig

    saved_dataset = meta.get("dataset")
    if dataset is None:
        dataset = saved_dataset or "wisdm"
    elif saved_dataset is not None and dataset != saved_dataset:
        raise ValueError(
            f"{what} was trained on dataset {saved_dataset!r}; "
            f"evaluating against {dataset!r} would derive a different "
            "feature view than the saved parameters expect"
        )
    saved_rows = meta.get("synthetic_rows")
    if synthetic_rows is None:
        synthetic_rows = saved_rows
    elif saved_rows is not None and synthetic_rows != saved_rows:
        raise ValueError(
            f"{what} was trained with synthetic_rows={saved_rows}; "
            f"evaluating against synthetic_rows={synthetic_rows} would "
            "regenerate different data than the saved parameters saw"
        )
    if seed is None:
        seed = meta.get("split_seed", 2018)
    if train_fraction is None:
        train_fraction = meta.get("train_fraction", 0.7)
    return RunConfig(
        data=DataConfig(
            dataset=dataset,
            path=data_path,
            train_fraction=train_fraction,
            seed=seed,
            synthetic_rows=synthetic_rows,
            drop_binned=meta.get("drop_binned", True),
            # checkpoints predating the spark-exact split were held out
            # under the bernoulli draw; honor their provenance
            split_method=meta.get("split_method", "bernoulli"),
        ),
        model=ModelConfig(name=meta.get("model_name", "cnn1d")),
    )


def _load_checkpoint_for_scoring(
    path: str,
    data_path: str | None,
    dataset: str | None,
    train_fraction: float | None,
    seed: int | None,
    synthetic_rows: int | None,
):
    """Load a checkpoint (either format) + the data it should be scored on.

    Returns (model, test FeatureSet).  Shared by the evaluate and predict
    backends so both load identically and derive the identical test
    partition — through the checkpoint's bundled pipeline vocabularies
    when present, through runner.featurize otherwise.
    """
    from har_tpu.runner import featurize, load_dataset

    with open(os.path.join(_abspath(path), _META)) as f:
        meta = json.load(f)
    is_classical = meta.get("format") == "classical"
    model = load_classical_model(path) if is_classical else load_model(path)
    config = scoring_config_from_meta(
        meta, data_path, dataset, train_fraction, seed, synthetic_rows
    )
    table = load_dataset(config)
    pipe_path = os.path.join(_abspath(path), _PIPELINE)
    if is_classical and os.path.exists(pipe_path):
        # featurize through the checkpoint's own saved vocabularies — no
        # refit; new rows with unseen categories fail or bucket per the
        # indexer's handle_invalid, exactly as the training-time pipeline
        from har_tpu.features.wisdm_pipeline import make_feature_set
        from har_tpu.runner import derive_split

        pipe = load_pipeline_model(pipe_path)
        full = make_feature_set(pipe.transform(table))
        _, test = derive_split(full, table, config.data)
    else:
        _, test, _ = featurize(config, table)
    return model, test


def write_predictions_csv(model, test, output_csv: str) -> dict:
    """One CSV row per window: UID (when the view carries one), the true
    label, the predicted class, per-class probabilities.  The ONE writer
    for every predict backend (checkpoint and exported-artifact)."""
    import csv

    preds = model.transform(test)
    probs = np.asarray(preds.probability)
    output_csv = _abspath(output_csv)
    parent = os.path.dirname(output_csv)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(output_csv, "w", newline="") as f:
        w = csv.writer(f)
        prob_cols = [f"prob_{k}" for k in range(probs.shape[1])]
        w.writerow(["UID", "label", "prediction"] + prob_cols)
        for i in range(len(preds)):
            uid = int(test.uid[i]) if test.uid is not None else i
            w.writerow(
                [uid, int(test.label[i]), int(preds.prediction[i])]
                + [f"{p:.6g}" for p in probs[i]]
            )
    return {
        "output": output_csv,
        "n_rows": int(len(preds)),
        "num_classes": int(probs.shape[1]),
    }


def predict_checkpoint(
    path: str,
    output_csv: str,
    data_path: str | None = None,
    dataset: str | None = None,
    train_fraction: float | None = None,
    seed: int | None = None,
    synthetic_rows: int | None = None,
) -> dict:
    """CLI `predict` backend: batch inference from a saved checkpoint.

    Scores the held-out partition (same derivation as `evaluate`) and
    writes the predictions CSV (write_predictions_csv)."""
    model, test = _load_checkpoint_for_scoring(
        path, data_path, dataset, train_fraction, seed, synthetic_rows
    )
    return write_predictions_csv(model, test, output_csv)


def evaluate_checkpoint(
    path: str,
    data_path: str | None = None,
    dataset: str | None = None,
    train_fraction: float | None = None,
    seed: int | None = None,
    synthetic_rows: int | None = None,
) -> dict:
    """CLI `evaluate` backend: load a checkpoint, score it on held-out data.

    ``train_fraction``/``seed`` default to the values recorded in the
    checkpoint metadata (falling back to 0.7/2018 for pre-provenance
    saves) — the test partition is re-derived from them, so an explicit
    mismatched value would leak training rows into the score.  The feature view
    is re-derived from the checkpoint's saved model name + dataset
    through the same runner logic that trained it; ``dataset=None``
    uses the recorded one, and an explicit value that contradicts the
    recording is refused (the features would not match the params).
    """
    from har_tpu.ops.metrics import evaluate

    model, test = _load_checkpoint_for_scoring(
        path, data_path, dataset, train_fraction, seed, synthetic_rows
    )
    preds = model.transform(test)
    rep = evaluate(test.label, preds.raw, model.num_classes)
    return {
        "accuracy": rep["accuracy"],
        "f1": rep["f1"],
        "weightedPrecision": rep["weightedPrecision"],
        "weightedRecall": rep["weightedRecall"],
        "count_correct": int(rep["count_correct"]),
        "count_wrong": int(rep["count_wrong"]),
        "n_test": int(len(test)),
    }
