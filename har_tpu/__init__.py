"""har_tpu — TPU-native human-activity-recognition framework.

A ground-up JAX/XLA re-design of the capabilities of
Lohitanvita/Activity-Recognition-Using-Apache-Spark (a PySpark/MLlib batch
pipeline, see reference Main/main.py): columnar ingestion with spark-csv
schema-inference semantics, a composable feature pipeline
(StringIndexer/OneHotEncoder/VectorAssembler), classical models (multinomial
logistic regression, histogram decision trees, random forests), neural models
(MLP / 1D-CNN / BiLSTM in Flax), k-fold cross-validation with grid search,
one-pass jitted metrics, SPMD data parallelism over a `jax.sharding.Mesh`,
orbax checkpointing, and report/CSV artifact writers matching the reference's
output formats.

Nothing here is a translation of the Spark driver/executor architecture:
compute is a single SPMD program — host-side columnar prep, then jitted XLA
computations sharded over the device mesh.
"""

__version__ = "0.1.0"

from har_tpu.config import DataConfig, ModelConfig, TrainConfig, MeshConfig

__all__ = [
    "DataConfig",
    "ModelConfig",
    "TrainConfig",
    "MeshConfig",
    "__version__",
]
