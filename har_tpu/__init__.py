"""har_tpu — TPU-native human-activity-recognition framework.

A ground-up JAX/XLA re-design of the capabilities of
Lohitanvita/Activity-Recognition-Using-Apache-Spark (a PySpark/MLlib batch
pipeline, see reference Main/main.py): columnar ingestion with spark-csv
schema-inference semantics, a composable feature pipeline
(StringIndexer/OneHotEncoder/VectorAssembler), classical models (multinomial
logistic regression, histogram decision trees, random forests), neural models
(MLP / 1D-CNN / BiLSTM in Flax), k-fold cross-validation with grid search,
one-pass jitted metrics, SPMD data parallelism over a `jax.sharding.Mesh`,
orbax checkpointing, and report/CSV artifact writers matching the reference's
output formats.

Nothing here is a translation of the Spark driver/executor architecture:
compute is a single SPMD program — host-side columnar prep, then jitted XLA
computations sharded over the device mesh.
"""

__version__ = "0.1.0"

try:
    import jax as _jax
except ImportError:  # pragma: no cover - jax-less environments
    # the shim below is moot without jax, and the jax-free surfaces
    # (`har lint` / har_tpu.analyze, the config dataclasses) must stay
    # importable — anything that actually needs jax fails at its own
    # import with the real error
    _jax = None

if _jax is not None and not hasattr(_jax, "shard_map"):
    # Older jax (< 0.5): shard_map lives in jax.experimental and the
    # replication-check kwarg is named check_rep, not check_vma.  The
    # codebase targets the new spelling; shim the old runtime up to it
    # so one tree runs on both sides of the rename.
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def _shard_map_compat(
        f, *, mesh, in_specs, out_specs, check_vma=None, **kw
    ):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _shard_map_old(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )

    _jax.shard_map = _shard_map_compat

from har_tpu.config import DataConfig, ModelConfig, TrainConfig, MeshConfig

__all__ = [
    "DataConfig",
    "ModelConfig",
    "TrainConfig",
    "MeshConfig",
    "__version__",
]
