"""Input-drift monitoring for deployed streaming inference.

The reference's stated use case is continuous monitoring of elderly
people from a worn accelerometer (paper §1; the pipeline itself is a
one-shot batch script, `Main/main.py`).  A deployed recognizer fails
silently when its INPUT distribution moves — a re-mounted sensor, a
changed orientation, gain drift, a different wearer — while the model
keeps emitting confident labels.  This module watches for exactly that:

  ``DriftMonitor`` — per-channel exponentially-weighted running
    mean/std over the sample stream, compared against the training
    distribution (taken from a fitted scaler, training windows, or
    explicit stats).  ``update(samples)`` returns a ``DriftReport``
    with per-channel z-scores (location) and log-scale ratios (spread),
    plus a debounced ``drifting`` verdict.

  ``StreamingClassifier(..., monitor=...)`` feeds it automatically:
    every ``StreamEvent`` then carries ``drift=True`` while the stream
    is out of distribution, so a timeline consumer can grey out
    decisions it should not trust.

Host-side numpy by design: the statistics are O(channels) EWMAs over
samples already in host memory for the ring buffer — putting them on
the TPU would cost a dispatch round-trip per chunk to accelerate
nine multiply-adds.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np


@dataclasses.dataclass(frozen=True)
class DriftReport:
    """One update()'s verdict."""

    drifting: bool  # debounced out-of-distribution verdict
    location_z: np.ndarray  # (C,) |ewma_mean - ref_mean| / ref_std
    scale_log_ratio: np.ndarray  # (C,) log(ewma_std / ref_std)
    n_samples: int  # total samples absorbed so far
    onset: int | None = None  # sample index (n_samples at the flip) of
    #   the CURRENT drift episode's onset; None while not drifting.  A
    #   stable episode id: every report of one uninterrupted episode
    #   carries the same onset, so an alert consumer (the adapt
    #   trigger) can de-duplicate per episode — and a reset() re-arm
    #   after a model swap starts a fresh episode by construction.
    generation: int = 0  # reset() count of the emitting monitor: onset
    #   indices restart at every reset, so (generation, onset) — not
    #   onset alone — is the globally unambiguous episode id (a post-
    #   reset episode can land on a numerically equal onset).

    @property
    def worst_channel(self) -> int:
        return int(
            np.argmax(
                np.maximum(self.location_z, np.abs(self.scale_log_ratio))
            )
        )


class DriftMonitor:
    """EWMA location/scale drift detector against training statistics.

    Parameters
    ----------
    ref_mean, ref_std:
        Per-channel training-distribution statistics, shape ``(C,)``.
    halflife:
        EWMA halflife in samples (default 400 = 20 s at 20 Hz): the
    	window over which old evidence decays to half weight.
    z_threshold:
        Location shift (in training standard deviations) or scale
        log-ratio magnitude (``|log(std_new/std_ref)|``; 0.69 = 2x)
        that counts as drifted.
    patience:
        Consecutive over-threshold updates before ``drifting`` flips
        (debounce: one noisy chunk is not a re-mounted sensor).
    """

    def __init__(
        self,
        ref_mean,
        ref_std,
        *,
        halflife: float = 400.0,
        z_threshold: float = 3.0,
        scale_threshold: float = 0.69,
        patience: int = 3,
    ):
        self.ref_mean = np.asarray(ref_mean, np.float64).reshape(-1)
        self.ref_std = np.asarray(ref_std, np.float64).reshape(-1)
        if self.ref_mean.shape != self.ref_std.shape:
            raise ValueError("ref_mean and ref_std must have equal shape")
        self.ref_std = np.where(self.ref_std > 0, self.ref_std, 1.0)
        if halflife <= 0:
            raise ValueError("halflife must be positive")
        self.halflife = float(halflife)
        self.z_threshold = float(z_threshold)
        self.scale_threshold = float(scale_threshold)
        self.patience = int(patience)
        self.reset()

    @classmethod
    def from_model(cls, model, **kwargs) -> "DriftMonitor":
        """Training stats from a fitted model's scaler.

        Raw-window scalers carry (window, C) statistics — collapsed to
        per-channel by averaging the location and RMS-averaging the
        spread over the window axis.
        """
        scaler = getattr(model, "scaler", None)
        if scaler is None:
            raise ValueError(
                "model has no fitted scaler; use from_windows or pass "
                "ref_mean/ref_std explicitly"
            )
        mean = np.asarray(scaler.mean, np.float64)
        std = np.asarray(scaler.std, np.float64)
        if mean.ndim == 2:  # (window, C) raw-window statistics
            mean = mean.mean(axis=0)
            std = np.sqrt((std**2).mean(axis=0))
        return cls(mean, std, **kwargs)

    @classmethod
    def from_windows(cls, windows, **kwargs) -> "DriftMonitor":
        """Training stats from raw ``(n, T, C)`` (or ``(n, C)``) data."""
        w = np.asarray(windows, np.float64)
        flat = w.reshape(-1, w.shape[-1])
        return cls(flat.mean(axis=0), flat.std(axis=0), **kwargs)

    def reset(self) -> None:
        """Re-arm: back to the reference state, debounce cleared, any
        current drift episode ended (the next episode gets a fresh
        ``onset``).  Called after a stream restart or a model swap —
        the new model was trained on the drifted data, so the old
        episode's evidence must not re-alert against it."""
        self._mean = self.ref_mean.copy()
        self._var = self.ref_std.copy() ** 2
        self._n = 0
        self._over = 0
        self._drifting = False
        self._onset: int | None = None
        # 0 on construction, +1 per re-arm: reports stamp it so episode
        # ids (generation, onset) never collide across resets
        self._generation = getattr(self, "_generation", -1) + 1

    def state(self) -> dict:
        """Full JSON-serializable state — knobs, reference stats, EWMA
        state and the live episode (onset/generation) — so a recovered
        stream's drift verdicts continue the pre-crash episode instead
        of restarting cold.  Serialization lives HERE, next to the
        fields it depends on: a representation change must update both
        sides in one place (the fleet journal snapshots call this)."""
        return {
            "ref_mean": [float(v) for v in self.ref_mean],
            "ref_std": [float(v) for v in self.ref_std],
            "halflife": self.halflife,
            "z_threshold": self.z_threshold,
            "scale_threshold": self.scale_threshold,
            "patience": self.patience,
            "mean": [float(v) for v in self._mean],
            "var": [float(v) for v in self._var],
            "n": self._n,
            "over": self._over,
            "drifting": self._drifting,
            "onset": self._onset,
            "generation": self._generation,
        }

    @classmethod
    def from_state(cls, state: dict) -> "DriftMonitor":
        """Rebuild a monitor from ``state()`` output."""
        m = cls(
            state["ref_mean"],
            state["ref_std"],
            halflife=state.get("halflife", 400.0),
            z_threshold=state.get("z_threshold", 3.0),
            scale_threshold=state.get("scale_threshold", 0.69),
            patience=state.get("patience", 3),
        )
        m._mean = np.asarray(state["mean"], np.float64)
        m._var = np.asarray(state["var"], np.float64)
        m._n = int(state.get("n", 0))
        m._over = int(state.get("over", 0))
        m._drifting = bool(state.get("drifting", False))
        onset = state.get("onset")
        m._onset = None if onset is None else int(onset)
        m._generation = int(state.get("generation", 0))
        return m

    @staticmethod
    def update_many(monitors, block) -> list["DriftReport | None"]:
        """Batched EWMA step: one ``(m, n, C)`` block of same-length
        chunks, one monitor per row — the fleet engine's SoA ingest
        path (``FleetServer.push_many``) updates a whole delivery
        round's monitors in five vectorized reductions instead of m
        Python ``update`` calls.

        Bit-identity by construction: every recurrence below is the
        elementwise float64 expression ``update`` evaluates per
        monitor (same ``keep`` power, same total-variance identity,
        same verdict thresholds), just broadcast over the row axis —
        so a monitored session's drift verdicts are identical whether
        its chunk rode the batched path or the sequential one
        (test-pinned).  Rows whose monitor is None get None back;
        monitors must share ``halflife`` only per distinct chunk
        length (``keep`` is scalar per call because the block rows are
        equal length; heterogeneous halflives are gathered per row).
        """
        idx = [i for i, mon in enumerate(monitors) if mon is not None]
        out: list[DriftReport | None] = [None] * len(monitors)
        if not idx:
            return out
        mons = [monitors[i] for i in idx]
        x = np.asarray(block, np.float64)[idx]
        n = x.shape[1]
        # math.pow per row, not np.power: ``update`` computes keep with
        # the C-library pow, and the two can differ in the last ulp —
        # the batched step must be BIT-identical to the sequential one
        # (journal replay re-runs updates sequentially; an ulp of EWMA
        # drift there could flip a borderline verdict post-recovery)
        keep = np.asarray(
            [math.pow(0.5, n / m.halflife) for m in mons], np.float64
        )[:, None]
        cm = x.mean(axis=1)
        cv = x.var(axis=1)
        mean = np.stack([m._mean for m in mons])
        var = np.stack([m._var for m in mons])
        var = keep * (var + (mean - cm) ** 2 * (1 - keep)) + (
            1 - keep
        ) * cv
        mean = keep * mean + (1 - keep) * cm
        ref_mean = np.stack([m.ref_mean for m in mons])
        ref_std = np.stack([m.ref_std for m in mons])
        z = np.abs(mean - ref_mean) / ref_std
        ratio = np.log(np.sqrt(np.maximum(var, 1e-12)) / ref_std)
        over_rows = (
            (z > np.asarray([m.z_threshold for m in mons])[:, None]).any(
                axis=1
            )
            | (
                np.abs(ratio)
                > np.asarray([m.scale_threshold for m in mons])[:, None]
            ).any(axis=1)
        )
        for j, mon in enumerate(mons):
            mon._mean = mean[j]
            mon._var = var[j]
            mon._n += n
            over = bool(over_rows[j])
            mon._over = mon._over + 1 if over else 0
            if mon._over >= mon.patience:
                if not mon._drifting:
                    mon._onset = mon._n
                mon._drifting = True
            elif not over:
                mon._drifting = False
                mon._onset = None
            out[idx[j]] = DriftReport(
                drifting=mon._drifting,
                location_z=z[j],
                scale_log_ratio=ratio[j],
                n_samples=mon._n,
                onset=mon._onset,
                generation=mon._generation,
            )
        return out

    def update(self, samples) -> DriftReport:
        """Absorb ``(n, C)`` samples; return the current verdict."""
        x = np.atleast_2d(np.asarray(samples, np.float64))
        if x.shape[-1] != self.ref_mean.shape[0]:
            raise ValueError(
                f"expected (n, {self.ref_mean.shape[0]}) samples, got "
                f"{x.shape}"
            )
        n = len(x)
        if n:
            # chunk-sized EWMA step: weight of the old state after n
            # samples is (1/2)^(n/halflife) — order-insensitive within
            # a chunk, equivalent to per-sample EWMA in the aggregate
            keep = math.pow(0.5, n / self.halflife)
            cm = x.mean(axis=0)
            cv = x.var(axis=0)
            # total variance: within-chunk + between-means
            self._var = keep * (
                self._var + (self._mean - cm) ** 2 * (1 - keep)
            ) + (1 - keep) * cv
            self._mean = keep * self._mean + (1 - keep) * cm
            self._n += n

        z = np.abs(self._mean - self.ref_mean) / self.ref_std
        ratio = np.log(
            np.sqrt(np.maximum(self._var, 1e-12)) / self.ref_std
        )
        over = bool(
            (z > self.z_threshold).any()
            or (np.abs(ratio) > self.scale_threshold).any()
        )
        self._over = self._over + 1 if over else 0
        if self._over >= self.patience:
            if not self._drifting:
                self._onset = self._n  # episode starts at THIS flip
            self._drifting = True
        elif not over:
            self._drifting = False
            self._onset = None  # recovery ends the episode
        return DriftReport(
            drifting=self._drifting,
            location_z=z,
            scale_log_ratio=ratio,
            n_samples=self._n,
            onset=self._onset,
            generation=self._generation,
        )
