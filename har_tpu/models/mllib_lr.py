"""Bit-exact replay of MLlib's LogisticRegression training (Spark 2.3).

The reference fits ``LogisticRegression(maxIter=20, regParam=0.3,
elasticNetParam=0)`` (Main/main.py:115) and its published numbers — LR
accuracy 0.6148, the CV headline 0.7145 — are the 20th Breeze iterate of
MLlib's standardized multinomial objective, not an optimum.  This module
reproduces that trajectory exactly:

  1. ``MultivariateOnlineSummarizer`` / ``MultiClassSummarizer``: Welford
     feature statistics and label histogram, folded over the train rows in
     partition order (the captured run used one partition — established by
     the round-2 split replay).
  2. Intercept initialization at the smoothed log class priors
     (log(count+1), mean-centered).
  3. The cost function: ``LogisticAggregator`` (multinomial, standardized,
     guarded divisions) + ``L2Regularization`` on the coefficient entries,
     evaluated sequentially in C++ with fdlibm (JDK StrictMath) exp/log —
     see native/mllibmath.cpp.
  4. ``breeze.optimize.LBFGS`` (elasticNet == 0) or ``OWLQN`` (> 0) with
     m=10 and MLlib's convergence checks — har_tpu.models.breeze_optimize.
  5. Back-transformation ``coef / featuresStd`` and the model's
     gemv + pivoted-softmax transform (native ``lr_predict``).

The TPU-native fast lane lives in har_tpu.models.logistic_regression; this
is the parity lane that makes the LR/LR-CV report blocks reproducible
byte-for-byte rather than "explained divergences".
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import numpy as np

from har_tpu.models import _jvm_native
from har_tpu.models._jvm_native import CsrMatrix
from har_tpu.models.breeze_optimize import LBFGS, OWLQN


def prepare_design(table) -> tuple[CsrMatrix, "AssembledRows"]:
    """Assemble the MLlib pipeline's sparse design matrix for a Table.

    Returns (full-table CSR in float64, AssembledRows with labels/uids);
    split paths index into it with spark_split_indices row ids.
    """
    from har_tpu.data.spark_split import assemble_rows

    rows = assemble_rows(table)
    return CsrMatrix.from_rows(rows.sparse, rows.num_features), rows


def summarizer_statistics(
    x: CsrMatrix, labels: np.ndarray, num_classes: int
) -> tuple[np.ndarray, np.ndarray]:
    """(featuresStd, label histogram) via MultivariateOnlineSummarizer /
    MultiClassSummarizer semantics: per-active Welford updates in row
    order, sample variance with the nnz mean-correction term.
    """
    d = x.n_cols
    curr_mean = np.zeros(d)
    curr_m2n = np.zeros(d)
    weight_sum = np.zeros(d)  # per-feature nnz weight
    total_weight = 0.0
    weight_square = 0.0
    indices, values, indptr = x.indices, x.values, x.indptr
    for row in range(x.n_rows):
        for p in range(int(indptr[row]), int(indptr[row + 1])):
            value = float(values[p])
            if value != 0.0:
                idx = int(indices[p])
                prev_mean = curr_mean[idx]
                diff = value - prev_mean
                # weight * diff / (weightSum + weight), weight = 1.0
                new_mean = prev_mean + 1.0 * diff / (weight_sum[idx] + 1.0)
                curr_mean[idx] = new_mean
                curr_m2n[idx] += 1.0 * (value - new_mean) * diff
                weight_sum[idx] += 1.0
        total_weight += 1.0
        weight_square += 1.0 * 1.0

    variance = np.zeros(d)
    denominator = total_weight - (weight_square / total_weight)
    if denominator > 0.0:
        for i in range(d):
            variance[i] = max(
                (
                    curr_m2n[i]
                    + curr_mean[i]
                    * curr_mean[i]
                    * weight_sum[i]
                    * (total_weight - weight_sum[i])
                    / total_weight
                )
                / denominator,
                0.0,
            )
    std = np.sqrt(variance)

    histogram = np.zeros(num_classes)
    for lab in labels:
        histogram[int(lab)] += 1.0
    return std, histogram


@dataclasses.dataclass(frozen=True)
class MLlibLRModel:
    """Original-space model, transform semantics per
    ProbabilisticClassificationModel (raw margins via gemv, pivoted
    softmax, prediction = probability argmax)."""

    coefficient_matrix: np.ndarray  # (k, d) row-major
    intercepts: np.ndarray  # (k,)
    objective_history: tuple[float, ...]

    def transform(self, x: CsrMatrix):
        raw, prob = _jvm_native.lr_predict(
            self.coefficient_matrix, self.intercepts, x
        )
        prediction = np.argmax(prob, axis=1).astype(np.float64)
        return raw, prob, prediction


def fit_mllib_lr(
    x: CsrMatrix,
    labels: np.ndarray,
    num_classes: int = 6,
    max_iter: int = 20,
    reg_param: float = 0.3,
    elastic_net_param: float = 0.0,
    fit_intercept: bool = True,
    tol: float = 1e-6,
) -> MLlibLRModel:
    """LogisticRegression.train (multinomial, standardization=true)."""
    d = x.n_cols
    k = num_classes
    labels = np.ascontiguousarray(labels, np.float64)
    feat_std, histogram = summarizer_statistics(x, labels, k)

    if not 1 <= k <= 64:
        raise ValueError(f"num_classes={k} outside the native kernel's 1..64")
    reg_l1 = elastic_net_param * reg_param
    reg_l2 = (1.0 - elastic_net_param) * reg_param

    size = k * d + (k if fit_intercept else 0)

    # Breeze wraps the MLlib cost in a CachedDiffFunction: the line
    # search's last evaluation IS the accepted iterate, so the state
    # update re-requests the identical x.  Caching the last (x, value,
    # grad) halves the native passes without touching the trajectory.
    last: list = [None, None, None]

    def cost(coef: np.ndarray):
        coef = np.ascontiguousarray(coef)
        if last[0] is not None and np.array_equal(last[0], coef):
            return last[1], last[2]
        grad = np.empty(size)
        loss = _jvm_native.lr_loss_grad(
            coef, x, labels, feat_std, k, fit_intercept, reg_l2, grad
        )
        last[0], last[1], last[2] = coef.copy(), loss, grad
        return loss, grad

    init = np.zeros(size)
    if fit_intercept:
        # rawIntercepts = histogram.map(c => math.log(c + 1)); mean-centered
        raw = [_jvm_native.jvm_log(c + 1) for c in histogram.tolist()]
        raw_sum = 0.0
        for v in raw:
            raw_sum += v
        raw_mean = raw_sum / len(raw)
        for i in range(k):
            init[k * d + i] = raw[i] - raw_mean

    if elastic_net_param == 0.0 or reg_param == 0.0:
        optimizer = LBFGS(max_iter=max_iter, m=10, tolerance=tol)
    else:
        l1 = np.zeros(size)
        l1[: k * d] = reg_l1  # intercepts unpenalized
        optimizer = OWLQN(max_iter=max_iter, m=10, l1reg=l1, tolerance=tol)

    history: list[float] = []
    state = None
    for state in optimizer.iterations(cost, init):
        history.append(state.adjusted_value)
    raw_coef = state.x

    coef_matrix = np.zeros((k, d))
    for j in range(d):
        sj = feat_std[j]
        if sj != 0.0:
            for c in range(k):
                coef_matrix[c, j] = raw_coef[j * k + c] / sj
    if fit_intercept:
        intercepts = raw_coef[k * d :].copy()
        # "The intercepts are never regularized, so we always center the
        # mean" — Spark 2.3 LogisticRegression.train mean-centers the
        # multinomial intercept vector in the final model.  Softmax is
        # shift-invariant, so predictions are unchanged, but rawPrediction
        # and the probability bits match the reference only with this.
        intercept_sum = 0.0
        for v in intercepts.tolist():
            intercept_sum += v
        intercept_mean = intercept_sum / len(intercepts)
        for i in range(k):
            intercepts[i] -= intercept_mean
    else:
        intercepts = np.zeros(k)
    return MLlibLRModel(
        coefficient_matrix=coef_matrix,
        intercepts=intercepts,
        objective_history=tuple(history),
    )
