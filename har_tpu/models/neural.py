"""Flax neural classifiers: MLP, 1D-CNN, BiLSTM.

These are the north-star models from BASELINE.json — the reference tops out
at 73% WISDM accuracy with MLlib classical models (BASELINE.md); the neural
configs (MLP on transformed features, CNN/BiLSTM on raw tri-axial windows)
are where ≥97% accuracy comes from.

TPU design notes:
  - compute dtype bfloat16 (MXU-native), parameters float32; logits are
    cast back to float32 before the softmax/loss for stable reductions.
  - CNN uses channels-last (N, T, C) 1-D convs — XLA maps these onto the
    MXU as implicit GEMMs; channel widths are multiples of 8 to tile well.
  - BiLSTM is a custom fused layer (FusedBiLSTMLayer): input projections
    for all timesteps hoisted into one matmul, both directions stacked
    into a single `lax.scan` whose per-step recurrence is one
    direction-batched matmul — half the serial chain of two stock RNNs.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class MLP(nn.Module):
    """3-layer perceptron over transformed feature vectors (BASELINE.json
    config 2, the Flax re-design of MLlib's MultilayerPerceptronClassifier)."""

    num_classes: int = 6
    hidden: Sequence[int] = (256, 128)
    dropout_rate: float = 0.2
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.astype(self.dtype)
        for width in self.hidden:
            x = nn.Dense(width, dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return logits.astype(jnp.float32)


class ConvBlock(nn.Module):
    features: int
    kernel: int
    dtype: jnp.dtype
    # TPU knobs (measured in scripts/mfu_tune.py): "max" pool is a pure
    # bandwidth pass over the full (B,T,C) activation; "stride" folds
    # the 2x downsample into the conv itself (stride-2), removing that
    # pass.  LayerNorm is two more bandwidth passes; "rms" halves its
    # reductions, "none" removes them (relu-only).
    pool: str = "max"        # "max" | "stride"
    norm: str = "layer"      # "layer" | "rms" | "none"

    @nn.compact
    def __call__(self, x):
        # refuse typo'd knobs loudly: a fall-through would silently run
        # a different architecture (no downsample / no norm) and record
        # mislabeled bench numbers
        if self.pool not in ("max", "stride"):
            raise ValueError(f"pool={self.pool!r}; use 'max' or 'stride'")
        if self.norm not in ("layer", "rms", "none"):
            raise ValueError(
                f"norm={self.norm!r}; use 'layer', 'rms' or 'none'"
            )
        stride = 2 if self.pool == "stride" else 1
        x = nn.Conv(
            self.features, (self.kernel,), strides=(stride,),
            dtype=self.dtype,
        )(x)
        if self.norm == "layer":
            x = nn.LayerNorm(dtype=self.dtype)(x)
        elif self.norm == "rms":
            x = nn.RMSNorm(dtype=self.dtype)(x)
        x = nn.relu(x)
        if self.pool == "max":
            x = nn.max_pool(x, (2,), strides=(2,))
        return x


class CNN1D(nn.Module):
    """1-D CNN over raw (T, 3) accelerometer windows (BASELINE.json
    config 3). Three conv/pool stages then global average pooling."""

    num_classes: int = 6
    channels: Sequence[int] = (64, 128, 128)
    kernel: int = 5
    dropout_rate: float = 0.3
    dtype: jnp.dtype = jnp.bfloat16
    pool: str = "max"
    norm: str = "layer"

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.astype(self.dtype)
        for ch in self.channels:
            x = ConvBlock(
                ch, self.kernel, self.dtype,
                pool=self.pool, norm=self.norm,
            )(x)
        x = x.mean(axis=-2)  # global average pool over time
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return logits.astype(jnp.float32)


class FusedBiLSTMLayer(nn.Module):
    """Both LSTM directions as ONE `lax.scan` (TPU-first re-design).

    flax's ``nn.Bidirectional(nn.RNN, nn.RNN)`` issues two sequential
    T-step scans whose per-step matmuls are too small to feed the MXU.
    Here (a) the input projections for every timestep and BOTH directions
    are hoisted out of the loop into a single (2, B, T, 4H) matmul, and
    (b) the serial recurrence stacks the directions — the backward pass
    runs on the time-reversed sequence — so each scan step is one
    direction-batched (2, B, H)·(2, H, 4H) matmul: half the serial
    dependency chain and twice the arithmetic per step of the stock
    layout.  Gate math runs in f32 (bf16 cell-state accumulation drifts
    over hundreds of steps); matmul inputs stay in ``dtype``.
    """

    hidden: int
    dtype: jnp.dtype = jnp.bfloat16
    # Store the hoisted input projections and the scanned step in bf16
    # instead of f32.  The recurrence is HBM-traffic bound (each of the
    # T steps streams its (2,B,4H) xproj slice plus saved residuals for
    # the backward pass), so halving those bytes buys throughput; cell
    # state c and the gate nonlinearity stay f32 either way, which keeps
    # the drift over 200 steps in the noise (test_neural pins fwd/bwd
    # agreement).  Off by default: parity-era checkpoints and the exact
    # fwd/bwd-equivalence tests predate it.
    bf16_stream: bool = False
    # jax.checkpoint the scan step: the backward pass recomputes the
    # gate preactivations from (hprev, xt) instead of streaming T saved
    # (2,B,4H) gate tensors back from HBM — trades one small matmul per
    # step for 4H of saved residual bandwidth.
    remat: bool = False

    @nn.compact
    def __call__(self, x):  # (B, T, I) -> (B, T, 2H)
        b, t, i = x.shape
        h = self.hidden
        stream_dtype = self.dtype if self.bf16_stream else jnp.float32
        wx = self.param(
            "wx", nn.initializers.lecun_normal(), (2, i, 4 * h), jnp.float32
        )
        wh = self.param(
            "wh", nn.initializers.orthogonal(), (2, h, 4 * h), jnp.float32
        )
        bias = self.param("bias", nn.initializers.zeros, (2, 4 * h), jnp.float32)

        xs = jnp.stack([x, x[:, ::-1, :]], axis=0)  # (2, B, T, I)
        xproj = (
            jnp.einsum(
                "dbti,dig->dbtg",
                xs.astype(self.dtype),
                wx.astype(self.dtype),
                preferred_element_type=jnp.float32,
            )
            + bias[:, None, None, :]
        ).astype(stream_dtype)
        # (2, B, T, 4H), one MXU pass for all steps x directions

        def step(carry, xt):  # xt: (2, B, 4H)
            hprev, cprev = carry
            gates = xt.astype(jnp.float32) + jnp.einsum(
                "dbh,dhg->dbg",
                hprev.astype(self.dtype),
                wh.astype(self.dtype),
                preferred_element_type=jnp.float32,
            )
            gi, gf, gg, go = jnp.split(gates, 4, axis=-1)
            c = jax.nn.sigmoid(gf) * cprev + jax.nn.sigmoid(gi) * jnp.tanh(gg)
            hnew = jax.nn.sigmoid(go) * jnp.tanh(c)
            return (hnew.astype(stream_dtype), c), hnew.astype(stream_dtype)

        if self.remat:
            step = jax.checkpoint(step)

        init = (
            jnp.zeros((2, b, h), stream_dtype),
            jnp.zeros((2, b, h), jnp.float32),
        )
        # unroll factors 2-8 were measured and don't beat the plain loop
        # (the serial dependency, not loop-trip overhead, is the bound —
        # docs/bilstm_profile.md has the arithmetic)
        _, hs = jax.lax.scan(step, init, xproj.transpose(2, 0, 1, 3))
        # (T, 2, B, H): undo the backward direction's time reversal
        fwd = hs[:, 0].transpose(1, 0, 2)
        bwd = hs[::-1, 1].transpose(1, 0, 2)
        return jnp.concatenate([fwd, bwd], axis=-1).astype(self.dtype)


class BiLSTM(nn.Module):
    """Bidirectional LSTM over raw windows (BASELINE.json config 5)."""

    num_classes: int = 6
    hidden: int = 128
    num_layers: int = 1
    dropout_rate: float = 0.2
    dtype: jnp.dtype = jnp.bfloat16
    bf16_stream: bool = False  # see FusedBiLSTMLayer
    remat: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.astype(self.dtype)
        for _ in range(self.num_layers):
            x = FusedBiLSTMLayer(
                self.hidden, self.dtype,
                bf16_stream=self.bf16_stream, remat=self.remat,
            )(x)
        # mean-pool the concatenated fwd/bwd features over time
        x = x.mean(axis=-2)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return logits.astype(jnp.float32)


def _transformer(**kwargs):
    from har_tpu.models.transformer import Transformer1D

    return Transformer1D(**kwargs)


MODEL_REGISTRY = {
    "mlp": MLP,
    "cnn1d": CNN1D,
    "bilstm": BiLSTM,
    "transformer": _transformer,
}


def build_model(name: str, num_classes: int, **kwargs) -> nn.Module:
    try:
        cls = MODEL_REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown neural model {name!r}; have {sorted(MODEL_REGISTRY)}"
        ) from None
    return cls(num_classes=num_classes, **kwargs)
