"""Flax neural classifiers: MLP, 1D-CNN, BiLSTM.

These are the north-star models from BASELINE.json — the reference tops out
at 73% WISDM accuracy with MLlib classical models (BASELINE.md); the neural
configs (MLP on transformed features, CNN/BiLSTM on raw tri-axial windows)
are where ≥97% accuracy comes from.

TPU design notes:
  - compute dtype bfloat16 (MXU-native), parameters float32; logits are
    cast back to float32 before the softmax/loss for stable reductions.
  - CNN uses channels-last (N, T, C) 1-D convs — XLA maps these onto the
    MXU as implicit GEMMs; channel widths are multiples of 8 to tile well.
  - BiLSTM uses `nn.RNN` over `nn.OptimizedLSTMCell` (a fused-gate cell:
    one (x,h)→4H matmul per step) wrapped in `nn.Bidirectional`; the time
    loop is a `lax.scan`, so the whole unrolled program is one XLA while
    loop with static shapes.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """3-layer perceptron over transformed feature vectors (BASELINE.json
    config 2, the Flax re-design of MLlib's MultilayerPerceptronClassifier)."""

    num_classes: int = 6
    hidden: Sequence[int] = (256, 128)
    dropout_rate: float = 0.2
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.astype(self.dtype)
        for width in self.hidden:
            x = nn.Dense(width, dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return logits.astype(jnp.float32)


class ConvBlock(nn.Module):
    features: int
    kernel: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.features, (self.kernel,), dtype=self.dtype)(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = nn.relu(x)
        return nn.max_pool(x, (2,), strides=(2,))


class CNN1D(nn.Module):
    """1-D CNN over raw (T, 3) accelerometer windows (BASELINE.json
    config 3). Three conv/pool stages then global average pooling."""

    num_classes: int = 6
    channels: Sequence[int] = (64, 128, 128)
    kernel: int = 5
    dropout_rate: float = 0.3
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.astype(self.dtype)
        for ch in self.channels:
            x = ConvBlock(ch, self.kernel, self.dtype)(x)
        x = x.mean(axis=-2)  # global average pool over time
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return logits.astype(jnp.float32)


class BiLSTM(nn.Module):
    """Bidirectional LSTM over raw windows (BASELINE.json config 5)."""

    num_classes: int = 6
    hidden: int = 128
    num_layers: int = 1
    dropout_rate: float = 0.2
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.astype(self.dtype)
        for _ in range(self.num_layers):
            bidi = nn.Bidirectional(
                nn.RNN(nn.OptimizedLSTMCell(self.hidden, dtype=self.dtype)),
                nn.RNN(nn.OptimizedLSTMCell(self.hidden, dtype=self.dtype)),
            )
            x = bidi(x)
        # mean-pool the concatenated fwd/bwd features over time
        x = x.mean(axis=-2)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return logits.astype(jnp.float32)


def _transformer(**kwargs):
    from har_tpu.models.transformer import Transformer1D

    return Transformer1D(**kwargs)


MODEL_REGISTRY = {
    "mlp": MLP,
    "cnn1d": CNN1D,
    "bilstm": BiLSTM,
    "transformer": _transformer,
}


def build_model(name: str, num_classes: int, **kwargs) -> nn.Module:
    try:
        cls = MODEL_REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown neural model {name!r}; have {sorted(MODEL_REGISTRY)}"
        ) from None
    return cls(num_classes=num_classes, **kwargs)
