"""ctypes bridge to the JVM-parity math kernels (native/mllibmath.cpp).

Compiled with ``-ffp-contract=off``: the JVM never fuses a*b+c into an FMA,
and GCC's default contraction would silently fork the bit-exact L-BFGS
trajectory the MLlib LogisticRegression replay reproduces.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from har_tpu.data._native_build import NativeLib

_NATIVE_DIR = os.path.join(
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ),
    "native",
)

_F64P = ctypes.POINTER(ctypes.c_double)
_I32P = ctypes.POINTER(ctypes.c_int32)
_I64P = ctypes.POINTER(ctypes.c_int64)


def _configure(lib: ctypes.CDLL) -> None:
    lib.set_math_backend.restype = None
    lib.set_math_backend.argtypes = [ctypes.c_int]
    lib.dnrm2_f2j.restype = ctypes.c_double
    lib.dnrm2_f2j.argtypes = [_F64P, ctypes.c_int64]
    lib.jvm_exp.restype = ctypes.c_double
    lib.jvm_exp.argtypes = [ctypes.c_double]
    lib.jvm_log.restype = ctypes.c_double
    lib.jvm_log.argtypes = [ctypes.c_double]
    lib.ddot_seq.restype = ctypes.c_double
    lib.ddot_seq.argtypes = [_F64P, _F64P, ctypes.c_int64]
    lib.lr_loss_grad.restype = ctypes.c_double
    lib.lr_loss_grad.argtypes = [
        _F64P, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int, _I32P, _F64P, _I64P, _F64P, _F64P,
        ctypes.c_double, _F64P,
    ]
    lib.lr_predict.restype = None
    lib.lr_predict.argtypes = [
        _F64P, _F64P, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        _I32P, _F64P, _I64P, _F64P, _F64P,
    ]
    lib.rf_poisson_weights.restype = None
    lib.rf_poisson_weights.argtypes = [
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_double, _F64P,
    ]
    lib.reservoir_sample_range.restype = None
    lib.reservoir_sample_range.argtypes = [
        ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64, _I32P,
    ]


_LIB = NativeLib(
    src=os.path.join(_NATIVE_DIR, "mllibmath.cpp"),
    so=os.path.join(_NATIVE_DIR, "libharjvm.so"),
    configure=_configure,
    extra_flags=("-ffp-contract=off",),
)


def load():
    lib = _LIB.load()
    if lib is None:
        raise RuntimeError(
            f"JVM-parity native kernel unavailable: {_LIB.build_error}"
        )
    return lib


def available() -> bool:
    return _LIB.available()


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctype)


def set_math_backend(backend: int) -> None:
    """Transcendental family for the replay kernels; oracle arbiter.

    0 = fdlibm (JDK StrictMath — the production default), 1 = platform
    libm, 2 = long-double round-trip (x87-style double rounding on x86
    only).  Anything else clamps to 0."""
    load().set_math_backend(int(backend))


def dnrm2_f2j(a: np.ndarray) -> float:
    assert a.dtype == np.float64 and a.flags.c_contiguous
    return load().dnrm2_f2j(_ptr(a, _F64P), a.size)


def jvm_exp(x: float) -> float:
    return load().jvm_exp(float(x))


def jvm_log(x: float) -> float:
    return load().jvm_log(float(x))


def ddot(a: np.ndarray, b: np.ndarray) -> float:
    """Strict left-to-right dot (F2J ddot order; Breeze norm = sqrt of it)."""
    assert a.dtype == np.float64 and b.dtype == np.float64
    assert a.flags.c_contiguous and b.flags.c_contiguous
    return load().ddot_seq(_ptr(a, _F64P), _ptr(b, _F64P), a.size)


def rf_poisson_weights(
    seed: int, n_rows: int, num_trees: int, subsample: float = 1.0
) -> np.ndarray:
    """(n_rows, num_trees) BaggedPoint bootstrap counts; pass the already
    partition-adjusted seed (seed + partitionIndex + 1)."""
    out = np.empty((n_rows, num_trees), np.float64)
    load().rf_poisson_weights(
        int(seed), n_rows, num_trees, float(subsample), _ptr(out, _F64P)
    )
    return out


def reservoir_sample_range(
    xorshift_state: int, n_items: int, k: int
) -> np.ndarray:
    """SamplingUtils.reservoirSampleAndCount over range(n_items)."""
    out = np.empty(k, np.int32)
    load().reservoir_sample_range(
        int(xorshift_state) & (2**64 - 1), n_items, k, _ptr(out, _I32P)
    )
    return out


class CsrMatrix:
    """Row-major sparse matrix in MLlib active-iteration order."""

    def __init__(
        self,
        indices: np.ndarray,
        values: np.ndarray,
        indptr: np.ndarray,
        n_cols: int,
    ):
        self.indices = np.ascontiguousarray(indices, np.int32)
        self.values = np.ascontiguousarray(values, np.float64)
        self.indptr = np.ascontiguousarray(indptr, np.int64)
        self.n_cols = int(n_cols)
        self.n_rows = len(self.indptr) - 1

    @classmethod
    def from_rows(cls, rows, n_cols: int) -> "CsrMatrix":
        """rows: iterable of (indices, values) pairs, active order."""
        indptr = [0]
        idx: list[int] = []
        val: list[float] = []
        for ri, rv in rows:
            idx.extend(int(i) for i in ri)
            val.extend(float(v) for v in rv)
            indptr.append(len(idx))
        return cls(
            np.asarray(idx, np.int32),
            np.asarray(val, np.float64),
            np.asarray(indptr, np.int64),
            n_cols,
        )

    def take(self, row_ids) -> "CsrMatrix":
        indptr = [0]
        idx: list[np.ndarray] = []
        val: list[np.ndarray] = []
        total = 0
        for r in row_ids:
            lo, hi = int(self.indptr[r]), int(self.indptr[r + 1])
            idx.append(self.indices[lo:hi])
            val.append(self.values[lo:hi])
            total += hi - lo
            indptr.append(total)
        return CsrMatrix(
            np.concatenate(idx) if idx else np.empty(0, np.int32),
            np.concatenate(val) if val else np.empty(0, np.float64),
            np.asarray(indptr, np.int64),
            self.n_cols,
        )


def lr_loss_grad(
    coef: np.ndarray,
    x: CsrMatrix,
    labels: np.ndarray,
    feat_std: np.ndarray,
    num_classes: int,
    fit_intercept: bool,
    reg_l2: float,
    grad_out: np.ndarray,
) -> float:
    lib = load()
    return lib.lr_loss_grad(
        _ptr(coef, _F64P),
        x.n_rows,
        x.n_cols,
        num_classes,
        1 if fit_intercept else 0,
        _ptr(x.indices, _I32P),
        _ptr(x.values, _F64P),
        _ptr(x.indptr, _I64P),
        _ptr(labels, _F64P),
        _ptr(feat_std, _F64P),
        float(reg_l2),
        _ptr(grad_out, _F64P),
    )


def lr_predict(
    coef_matrix: np.ndarray,  # (k, d) row-major, original feature space
    intercepts: np.ndarray,  # (k,)
    x: CsrMatrix,
) -> tuple[np.ndarray, np.ndarray]:
    lib = load()
    k, d = coef_matrix.shape
    raw = np.empty((x.n_rows, k), np.float64)
    prob = np.empty((x.n_rows, k), np.float64)
    lib.lr_predict(
        _ptr(np.ascontiguousarray(coef_matrix, np.float64), _F64P),
        _ptr(np.ascontiguousarray(intercepts, np.float64), _F64P),
        x.n_rows,
        d,
        k,
        _ptr(x.indices, _I32P),
        _ptr(x.values, _F64P),
        _ptr(x.indptr, _I64P),
        _ptr(raw, _F64P),
        _ptr(prob, _F64P),
    )
    return raw, prob
