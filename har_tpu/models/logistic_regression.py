"""Multinomial logistic regression, trained full-batch on TPU.

Replaces MLlib's LogisticRegression (reference Main/main.py:115-117), which
runs L-BFGS/OWL-QN with per-partition gradient ``treeAggregate`` on the JVM.
Here the whole dataset is a device array and each optimizer iteration is one
fused XLA computation — the matmuls land on the MXU and the "aggregation" is
just a reduction inside the same program (on a sharded mesh it becomes a
psum over ICI; see har_tpu.parallel).

Objective (matching MLlib's docs/defaults):
    (1/n) Σ softmax-cross-entropy
  + reg_param * [ (1-α)/2 ||W||₂² + α ||W||₁ ]
with features standardized to unit variance internally (MLlib default
``standardization=true``), the intercept unregularized, and coefficients
returned in the original feature space.  α = elastic_net_param.

Solver: optax L-BFGS under `lax.scan` for the smooth case; proximal
gradient (FISTA) when α > 0 so the L1 term is handled exactly.

This is the TPU-native FAST lane.  The reference's published numbers
(LR 0.6148, CV 0.7145) are the maxIter=20 Breeze trajectory, which this
converged solver intentionally does not chase — the bit-exact replay
lane (har_tpu.models.mllib_lr: Breeze L-BFGS/OWL-QN ports over MLlib's
standardized objective with fdlibm transcendentals) reproduces them
exactly.  Analysis note that still holds: with standardization the
effective penalty on an original-space coefficient is ∝ its feature's
variance, so the 3,090 rare one-hot dims are nearly unregularized and
the CONVERGED optimum of MLlib's objective scores only ~0.633;
`standardize=False` (uniform penalty) converges to 0.72+ and beats the
reference's CV headline with a single fit (see bench.py).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from har_tpu.features.wisdm_pipeline import FeatureSet
from har_tpu.models.base import Predictions


def _train_core(
    x: jax.Array,
    y: jax.Array,
    row_w: jax.Array,  # (n,) per-row weights: 0.0 = padding; CV fold
    # masks are 1/0, class_weight="balanced" passes arbitrary positive
    # weights (which also enter the standardization statistics)
    num_classes: int,
    max_iter: int,
    reg_param: jax.Array,  # traced → one compilation serves a whole grid
    elastic_net_param: float,
    fit_intercept: bool,
    standardize: bool,
):
    """Weighted trainer body; traced under jit (and vmap for CV sweeps).

    Matmuls run at HIGHEST precision: TPU's default f32 matmul is a
    bf16-pass approximation whose rounding perturbs the maxIter=20
    L-BFGS trajectory enough to flip test rows vs the published numbers.
    Full-precision passes keep the TPU trajectory close to CPU's — the
    cutoff iterate is still arithmetic-order-sensitive (no
    reimplementation lands it bit-exactly; see the parity test), but
    with MLlib's log-prior intercept init it stays at or above the
    reference's accuracy on every backend.  The model is tiny — the 6x
    matmul cost is noise next to dispatch latency.
    """
    with jax.default_matmul_precision("highest"):
        return _train_core_impl(
            x, y, row_w, num_classes, max_iter, reg_param,
            elastic_net_param, fit_intercept, standardize,
        )


def _train_core_impl(
    x, y, row_w, num_classes, max_iter, reg_param,
    elastic_net_param, fit_intercept, standardize,
):
    n, d = x.shape
    y1h = jax.nn.one_hot(y, num_classes, dtype=x.dtype)
    n_eff = jnp.maximum(row_w.sum(), 1.0)

    if standardize:
        # weighted mean/var with Bessel correction — equals np.std(ddof=1)
        # on unit weights, ignores zero-weight padding rows, and under
        # class weighting computes class-balanced statistics
        mean = (x * row_w[:, None]).sum(0) / n_eff
        var = ((x - mean) ** 2 * row_w[:, None]).sum(0) / jnp.maximum(
            n_eff - 1.0, 1.0
        )
        std = jnp.sqrt(var)
        inv_std = jnp.where(std > 0, 1.0 / jnp.maximum(std, 1e-30), 0.0)
    else:
        inv_std = jnp.ones((d,), x.dtype)
    xs = x * inv_std  # scaled design matrix; reg applies in this space

    l2 = reg_param * (1.0 - elastic_net_param)
    l1 = reg_param * elastic_net_param

    def smooth_loss(params):
        w, b = params
        logits = xs @ w + b
        ce = optax.softmax_cross_entropy(logits, y1h)
        return (ce * row_w).sum() / n_eff + 0.5 * l2 * jnp.sum(w * w)

    w0 = jnp.zeros((d, num_classes), x.dtype)
    # MLlib starts the intercepts at the log of the class priors
    # (LogisticRegression.scala "initialCoefWithInterceptMatrix": the
    # optimal intercept for zero coefficients); zeros otherwise.  This
    # shapes the early L-BFGS trajectory the reference's maxIter=20
    # numbers were captured on.
    if fit_intercept:
        prior = (y1h * row_w[:, None]).sum(0) / n_eff
        b0 = jnp.log(jnp.maximum(prior, 1e-12))
    else:
        b0 = jnp.zeros((num_classes,), x.dtype)

    # Both solvers are non-monotone (L-BFGS line searches can overshoot,
    # FISTA momentum oscillates), so each carries its best-seen iterate
    # and returns it at cutoff rather than whatever the last step left.
    def best_init():
        return jnp.asarray(jnp.inf, x.dtype), (w0, b0)

    def best_update(best, value, params):
        best_loss, best_params = best
        improved = value < best_loss
        return (
            jnp.where(improved, value, best_loss),
            jax.tree.map(
                lambda new, old: jnp.where(improved, new, old),
                params,
                best_params,
            ),
        )

    if elastic_net_param == 0.0:  # static → no L1 term, smooth solver
        opt = optax.lbfgs()
        state = opt.init((w0, b0))
        value_and_grad = optax.value_and_grad_from_state(smooth_loss)

        def step(carry, _):
            params, st, best = carry
            value, grad = value_and_grad(params, state=st)
            best = best_update(best, value, params)
            updates, st = opt.update(
                grad, st, params, value=value, grad=grad, value_fn=smooth_loss
            )
            params = optax.apply_updates(params, updates)
            return (params, st, best), value

        (params, _, best), losses = jax.lax.scan(
            step, ((w0, b0), state, best_init()), length=max_iter
        )
        # final iterate vs best-seen: keep whichever scores lower
        final_loss = smooth_loss(params)
        best_loss, best_params = best
        take_final = final_loss <= best_loss
        params = jax.tree.map(
            lambda f, b: jnp.where(take_final, f, b), params, best_params
        )
    else:
        # FISTA: accelerated proximal gradient with soft-threshold prox.
        # Lipschitz bound for softmax CE + L2: ||Xs||² / (2n) * 1 + l2.
        lip = (
            jnp.sum(xs * xs * row_w[:, None]) / n_eff
        ) * 0.5 + l2 + 1e-6
        lr = 1.0 / lip

        def prox(w):
            return jnp.sign(w) * jnp.maximum(jnp.abs(w) - lr * l1, 0.0)

        def step(carry, t):
            (w, b), (zw, zb), t_prev, best = carry
            g_w, g_b = jax.grad(smooth_loss)((zw, zb))
            w_new = prox(zw - lr * g_w)
            b_new = zb - lr * g_b
            t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t_prev**2))
            beta = (t_prev - 1.0) / t_new
            zw_new = w_new + beta * (w_new - w)
            zb_new = b_new + beta * (b_new - b)
            value = smooth_loss((w_new, b_new)) + l1 * jnp.sum(
                jnp.abs(w_new)
            )
            best = best_update(best, value, (w_new, b_new))
            return ((w_new, b_new), (zw_new, zb_new), t_new, best), value

        init = ((w0, b0), (w0, b0), jnp.array(1.0, x.dtype), best_init())
        (params, _, _, best), losses = jax.lax.scan(
            step, init, jnp.arange(max_iter)
        )
        # the best carry already includes every iterate (value computed
        # at the accepted point), so just take it
        params = best[1]

    w, b = params
    if not fit_intercept:
        b = jnp.zeros_like(b)
    # map coefficients back to the un-standardized feature space
    w = w * inv_std[:, None]
    return w, b, losses


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_classes",
        "max_iter",
        "elastic_net_param",
        "fit_intercept",
        "standardize",
    ),
)
def _train_weighted(
    x: jax.Array,
    y: jax.Array,
    row_w: jax.Array,
    num_classes: int,
    max_iter: int,
    reg_param: float,
    elastic_net_param: float,
    fit_intercept: bool,
    standardize: bool,
):
    return _train_core(
        x,
        y,
        row_w,
        num_classes,
        max_iter,
        jnp.asarray(reg_param, x.dtype),
        elastic_net_param,
        fit_intercept,
        standardize,
    )


# in-graph validation metrics available to the vectorized CV sweep; the
# quirky reference metrics (SURVEY §2 N: MAE over label indices) included
_CV_METRICS = ("accuracy", "mae", "mse", "rmse")


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_classes",
        "max_iter",
        "elastic_net_param",
        "fit_intercept",
        "standardize",
        "metric",
    ),
)
def _cv_scores_group(
    x: jax.Array,  # (n, d) the FULL training set, device-resident once
    y: jax.Array,  # (n,)
    train_idx: jax.Array,  # (F, m) fold train rows, padded
    train_w: jax.Array,  # (F, m) 1/0 padding mask
    val_idx: jax.Array,  # (F, v) fold val rows, padded
    val_w: jax.Array,  # (F, v)
    reg_params: jax.Array,  # (R,) traced grid values
    num_classes: int,
    max_iter: int,
    elastic_net_param: float,
    fit_intercept: bool,
    standardize: bool,
    metric: str,
):
    """(R, F) validation scores — the whole fold×reg sweep in ONE program.

    Spark's CrossValidator schedules 45 independent distributed jobs
    (reference Main/main.py:209-222); here the independent fits are a
    `vmap` over (reg_param, fold) so the sweep costs one dispatch per
    elastic_net group instead of one per fit — the dominant cost at
    remote-dispatch latencies, and XLA batches the matmuls on the MXU.
    """

    def fit_and_score(reg, tidx, tw, vidx, vw):
        w, b, _ = _train_core(
            x[tidx], y[tidx], tw, num_classes, max_iter, reg,
            elastic_net_param, fit_intercept, standardize,
        )
        with jax.default_matmul_precision("highest"):
            logits = x[vidx] @ w + b
        pred = jnp.argmax(logits, axis=-1).astype(jnp.float32)
        yv = y[vidx].astype(jnp.float32)
        n_eff = jnp.maximum(vw.sum(), 1.0)
        if metric == "accuracy":
            return ((pred == yv) * vw).sum() / n_eff
        err = (yv - pred) * vw
        if metric == "mae":
            return jnp.abs(err).sum() / n_eff
        mse = (err * err).sum() / n_eff
        return jnp.sqrt(mse) if metric == "rmse" else mse

    per_fold = jax.vmap(fit_and_score, in_axes=(None, 0, 0, 0, 0))
    return jax.vmap(per_fold, in_axes=(0, None, None, None, None))(
        reg_params, train_idx, train_w, val_idx, val_w
    )


def _pad_fold_indices(folds):
    """Equal-length index/mask arrays from ragged (train, val) folds."""
    tmax = max(len(t) for t, _ in folds)
    vmax = max(len(v) for _, v in folds)

    def pad(idx, m):
        out = np.zeros((len(folds), m), np.int32)
        w = np.zeros((len(folds), m), np.float32)
        for i, a in enumerate(idx):
            out[i, : len(a)] = a
            w[i, : len(a)] = 1.0
        return out, w

    tidx, tw = pad([t for t, _ in folds], tmax)
    vidx, vw = pad([v for _, v in folds], vmax)
    return tidx, tw, vidx, vw


@functools.partial(jax.jit, static_argnames=())
def _forward(w: jax.Array, b: jax.Array, x: jax.Array):
    with jax.default_matmul_precision("highest"):
        logits = x @ w + b
    return logits, jax.nn.softmax(logits, axis=-1)


@dataclasses.dataclass(frozen=True)
class LogisticRegression:
    """Estimator with the reference's default hyperparameters
    (maxIter=20, regParam=0.3, elasticNetParam=0 — Main/main.py:115)."""

    max_iter: int = 20
    reg_param: float = 0.3
    elastic_net_param: float = 0.0
    fit_intercept: bool = True
    standardize: bool = True
    # None → every row weighs 1 (MLlib default); "balanced" reweighs
    # rows by n / (num_classes * count(class)) so minority activities
    # (WISDM: Standing 246 vs Walking 2081) pull equally on the loss
    class_weight: str | None = None
    num_classes: int | None = None  # inferred from labels when None
    # optional jax.sharding.Mesh: cv_scores shards the grid axis over
    # its data axis so independent (reg × fold) fits train on separate
    # devices — SURVEY §2c.2's task parallelism ACROSS devices, not
    # just vmapped on one.  fit() ignores it (one fit = one program).
    mesh: object | None = dataclasses.field(
        default=None, compare=False, repr=False
    )

    def copy_with(self, **params) -> "LogisticRegression":
        return dataclasses.replace(self, **params)

    def cv_scores(self, data: FeatureSet, folds, grid, metric: str):
        """Vectorized grid×fold sweep; (len(grid), len(folds)) scores.

        Returns None when a grid key or the metric falls outside the
        vectorizable set — the CrossValidator then takes its generic
        fit-per-cell path.
        """
        allowed = {"reg_param", "elastic_net_param"}
        if (
            metric not in _CV_METRICS
            or any(set(g) - allowed for g in grid)
            # the vectorized sweep weighs rows only with fold padding
            # masks; class-weighted selection must use the generic
            # fit-per-cell path so every CV fit matches fit()'s objective
            or self.class_weight is not None
        ):
            return None
        num_classes = self.num_classes or int(data.label.max()) + 1
        x = jnp.asarray(data.features, jnp.float32)
        y = jnp.asarray(data.label)
        tidx, tw, vidx, vw = _pad_fold_indices(folds)

        # group grid points by the static elastic_net_param (it selects
        # the solver — L-BFGS vs FISTA); reg_param is traced, so each
        # group is one compilation + one dispatch
        scores = np.zeros((len(grid), len(folds)), np.float64)
        by_enp: dict[float, list[int]] = {}
        for i, g in enumerate(grid):
            enp = float(g.get("elastic_net_param", self.elastic_net_param))
            by_enp.setdefault(enp, []).append(i)
        for enp, idxs in by_enp.items():
            reg_vals = [
                float(grid[i].get("reg_param", self.reg_param))
                for i in idxs
            ]
            n_real = len(reg_vals)
            regs = jnp.asarray(reg_vals, jnp.float32)
            axes = self._mesh_data_axes()
            if axes:
                # shard the grid axis over the mesh's data axis: each
                # device trains its slice of the (reg × fold) matrix —
                # GSPMD partitions the vmap lanes, which are independent
                # fits.  Pad to a multiple of the shard count (padding
                # lanes repeat the last reg; dropped below).  Single-
                # process meshes only: the host gathers the score matrix
                # with np.asarray below.
                from jax.sharding import NamedSharding, PartitionSpec

                from har_tpu.parallel.mesh import data_shard_count

                if jax.process_count() > 1:
                    raise ValueError(
                        "mesh-sharded cv_scores supports single-process "
                        "meshes; drop the mesh (or gather externally) "
                        "for multi-host sweeps"
                    )
                shards = data_shard_count(self.mesh)
                pad = (-n_real) % shards
                if pad:
                    regs = jnp.concatenate(
                        [regs, jnp.repeat(regs[-1:], pad)]
                    )
                regs = jax.device_put(
                    regs, NamedSharding(self.mesh, PartitionSpec(axes))
                )
            out = _cv_scores_group(
                x, y, jnp.asarray(tidx), jnp.asarray(tw),
                jnp.asarray(vidx), jnp.asarray(vw), regs,
                num_classes=num_classes,
                max_iter=self.max_iter,
                elastic_net_param=enp,
                fit_intercept=self.fit_intercept,
                standardize=self.standardize,
                metric=metric,
            )
            scores[idxs] = np.asarray(out, np.float64)[:n_real]
        return scores

    def _mesh_data_axes(self) -> tuple:
        """Data axes of the attached mesh ('dp' [+ 'dp_dcn']), or ()."""
        if self.mesh is None:
            return ()
        from har_tpu.parallel.mesh import data_axes

        return data_axes(self.mesh)

    def fit(self, data: FeatureSet) -> "LogisticRegressionModel":
        if self.class_weight not in (None, "balanced"):
            raise ValueError(
                f"class_weight={self.class_weight!r}; use None or "
                "'balanced'"
            )
        num_classes = self.num_classes or int(data.label.max()) + 1
        y_np = np.asarray(data.label)
        if self.class_weight == "balanced":
            counts = np.bincount(y_np, minlength=num_classes).astype(
                np.float32
            )
            per_class = len(y_np) / (
                num_classes * np.maximum(counts, 1.0)
            )
            row_w = jnp.asarray(per_class[y_np])
        else:
            row_w = jnp.ones((len(y_np),), jnp.float32)
        w, b, losses = _train_weighted(
            jnp.asarray(data.features, dtype=jnp.float32),
            jnp.asarray(data.label),
            row_w,
            num_classes=num_classes,
            max_iter=self.max_iter,
            reg_param=float(self.reg_param),
            elastic_net_param=float(self.elastic_net_param),
            fit_intercept=self.fit_intercept,
            standardize=self.standardize,
        )
        return LogisticRegressionModel(
            coefficients=np.asarray(w),
            intercept=np.asarray(b),
            num_classes=num_classes,
            losses=np.asarray(losses),
        )


@dataclasses.dataclass(frozen=True)
class LogisticRegressionModel:
    coefficients: np.ndarray  # (d, C)
    intercept: np.ndarray  # (C,)
    num_classes: int
    # per-iteration loss trajectory (each entry is the loss at that
    # step's accepted point for FISTA / pre-update point for L-BFGS).
    # The returned coefficients are the best point seen — the final
    # iterate when it is at least as good — so the model's own loss can
    # sit at or below min(losses); use the trajectory for convergence
    # shape, not as the trained model's exact loss.
    losses: np.ndarray | None = None

    def transform(self, data: FeatureSet) -> Predictions:
        logits, probs = _forward(
            jnp.asarray(self.coefficients),
            jnp.asarray(self.intercept),
            jnp.asarray(data.features, dtype=jnp.float32),
        )
        return Predictions.from_raw(logits, probs)
