"""Soft-voting ensembles over any Classifier estimators.

MLlib (and hence the reference, Main/main.py:103-106) has no model-
combination layer; the framework adds one.  Measured on WISDM-43: a
5-seed GBDT ensemble gains ~0.4 accuracy points on a held-out validation
split but not on the reference's 70/30 test split (the single seed-0
model is already at the summary-feature ceiling there) — voting is a
variance tool, not a guaranteed win; validate per dataset.

Members train independently — each ``fit`` is its own XLA program, so a
multi-chip deployment can train members concurrently (one per device) —
and predict by weighted-average probability.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from har_tpu.features.wisdm_pipeline import FeatureSet
from har_tpu.models.base import Predictions


@dataclasses.dataclass(frozen=True)
class VotingClassifier:
    """Weighted soft-voting over heterogeneous member estimators."""

    estimators: tuple
    weights: tuple | None = None  # None → uniform

    def __post_init__(self):
        if not self.estimators:
            raise ValueError("VotingClassifier needs at least one estimator")
        if self.weights is not None:
            if len(self.weights) != len(self.estimators):
                raise ValueError(
                    f"{len(self.weights)} weights for "
                    f"{len(self.estimators)} estimators"
                )
            if not all(w >= 0 for w in self.weights) or not any(
                w > 0 for w in self.weights
            ):
                raise ValueError("weights must be >= 0 with a positive sum")

    def copy_with(self, **params) -> "VotingClassifier":
        """Grid-search support: a param broadcast onto every member."""
        own = {f.name for f in dataclasses.fields(self)}
        direct = {k: v for k, v in params.items() if k in own}
        member = {k: v for k, v in params.items() if k not in own}
        new = dataclasses.replace(self, **direct)
        if member:
            new = dataclasses.replace(
                new,
                estimators=tuple(
                    e.copy_with(**member) for e in new.estimators
                ),
            )
        return new

    def fit(self, data: FeatureSet) -> "VotingModel":
        models = tuple(e.fit(data) for e in self.estimators)
        return VotingModel(
            models=models,
            weights=self.weights,
            num_classes=models[0].num_classes,
        )


def seed_ensemble(estimator, n: int, base_seed: int = 0) -> VotingClassifier:
    """n copies of one estimator differing only in ``seed`` — the cheapest
    decorrelation for subsampling learners (GBDT/RF)."""
    if n < 1:
        raise ValueError("seed_ensemble needs n >= 1")
    return VotingClassifier(
        estimators=tuple(
            estimator.copy_with(seed=base_seed + i) for i in range(n)
        )
    )


@dataclasses.dataclass(frozen=True)
class VotingModel:
    models: tuple
    weights: tuple | None
    num_classes: int

    def transform(self, data: FeatureSet) -> Predictions:
        w = (
            np.asarray(self.weights, np.float64)
            if self.weights is not None
            else np.ones(len(self.models))
        )
        w = w / w.sum()
        prob = None
        for wi, m in zip(w, self.models):
            p = np.asarray(m.transform(data).probability, np.float64)
            prob = wi * p if prob is None else prob + wi * p
        prob = prob.astype(np.float32)
        # averaged probabilities are the ensemble's raw scores too: every
        # metric (incl. threshold sweeps) sees the actual voting output
        return Predictions.from_raw(prob, prob)
