"""Gradient-boosted trees on TPU — the whole boosting run as one XLA program.

The reference's strongest classical model is an MLlib RandomForest
(Main/main.py:478; best committed accuracy 0.7305 from the depth-3
DecisionTree, additional_param.csv:3).  Boosted trees are the natural
upgrade for this tabular workload, and the TPU re-design makes the *entire*
training run — `lax.scan` over boosting rounds, `vmap` over the K class-wise
regression trees per round, MXU-matmul histograms per level — a single
compiled program with static shapes throughout.  No per-round host
round-trips: Spark's driver↔executor histogram aggregation loop
(SURVEY §3.3 DT/RF variant) becomes one XLA dispatch.

Algorithm: second-order multiclass boosting (XGBoost-style).  Per round,
softmax gradients ``g = p − onehot(y)`` and hessians ``h = p·(1−p)`` are
computed from the running raw scores F; one regression tree per class fits
(g_k, h_k) with gain

    0.5·[G_L²/(H_L+λ) + G_R²/(H_R+λ) − G²/(H+λ)]

and leaf value ``−G/(H+λ)``, scaled by the learning rate into F.  Histograms
of (g, h) per (node, feature, bin) are built as one f32 matmul per level —
the same one-hot-matmul trick as tree.py, with the two statistics interleaved
on the output axis so a single dot covers both.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from har_tpu.features.wisdm_pipeline import FeatureSet
from har_tpu.models.base import Predictions
from har_tpu.models.tree import binize, quantile_thresholds


def _split_gain(gl, hl, gr, hr, lam):
    """XGBoost structure-score gain (without the constant parent term)."""

    def score(g, h):
        return (g * g) / (h + lam)

    return 0.5 * (score(gl, hl) + score(gr, hr))


@functools.partial(
    jax.jit,
    static_argnames=("max_depth", "max_bins", "num_rounds", "num_classes"),
)
def _gbdt_fit(
    bins: jax.Array,  # (n, d) int32 bin ids
    y: jax.Array,  # (n,) int32
    rng: jax.Array,
    num_classes: int,
    num_rounds: int,
    max_depth: int,
    max_bins: int,
    learning_rate: float,
    lam: float,
    min_child_weight: float,
    subsample: float,
):
    n, d = bins.shape
    n_nodes = 2 ** (max_depth + 1) - 1
    n_internal = 2**max_depth - 1
    level_width = 2**max_depth
    y1h = jax.nn.one_hot(y, num_classes, dtype=jnp.float32)

    # (n, d*B) one-hot of bin ids — shared by every level of every tree of
    # every round (depends only on the data).  f32: gradient histograms need
    # more mantissa than class counts, and XLA still tiles this onto the MXU.
    bins_onehot = jax.nn.one_hot(bins, max_bins, dtype=jnp.float32).reshape(
        n, d * max_bins
    )

    def grow_reg_tree(g, h):
        """One second-order regression tree on (g, h); all shapes static.

        Returns (feature, split_bin, threshold-slot placeholder, leaf_value):
        feature[node] (-1 → leaf), split_bin[node] (bin id; row goes left if
        bin <= split_bin), leaf_value[node].
        """
        feature = jnp.full((n_nodes,), -1, jnp.int32)
        split_bin = jnp.zeros((n_nodes,), jnp.int32)
        node_g = jnp.zeros((n_nodes,), jnp.float32).at[0].set(g.sum())
        node_h = jnp.zeros((n_nodes,), jnp.float32).at[0].set(h.sum())
        node_of_row = jnp.zeros((n,), jnp.int32)

        def grow_level(level, carry):
            feature, split_bin, node_g, node_h, node_of_row = carry
            first = 2**level - 1
            local = node_of_row - first
            valid = (local >= 0) & (local < level_width)
            local = jnp.clip(local, 0, level_width - 1)

            # (n, 2W): columns 2w / 2w+1 hold g / h of rows in node w
            base = jax.nn.one_hot(
                local * 2, 2 * level_width, dtype=jnp.float32
            )
            gh = jnp.where(valid, g, 0.0)[:, None] * base + jnp.where(
                valid, h, 0.0
            )[:, None] * jnp.roll(base, 1, axis=1)
            hist = jax.lax.dot_general(
                gh,
                bins_onehot,
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).reshape(level_width, 2, d, max_bins)
            ghist, hhist = hist[:, 0], hist[:, 1]  # (W, d, B)

            gcum = jnp.cumsum(ghist, axis=2)
            hcum = jnp.cumsum(hhist, axis=2)
            gl, hl = gcum[:, :, : max_bins - 1], hcum[:, :, : max_bins - 1]
            gt = gcum[:, :, -1][:, :, None]
            ht = hcum[:, :, -1][:, :, None]
            gr, hr = gt - gl, ht - hl

            gain = _split_gain(gl, hl, gr, hr, lam)
            parent = 0.5 * (gt * gt) / (ht + lam)
            gain = gain - parent
            ok = (hl >= min_child_weight) & (hr >= min_child_weight)
            gain = jnp.where(ok, gain, -jnp.inf)

            flat = gain.reshape(level_width, -1)
            best = jnp.argmax(flat, axis=1)
            best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
            best_feat = (best // (max_bins - 1)).astype(jnp.int32)
            best_bin = (best % (max_bins - 1)).astype(jnp.int32)
            splittable = jnp.isfinite(best_gain) & (best_gain > 1e-12)

            node_ids = first + jnp.arange(level_width)
            is_internal = splittable & (node_ids < n_internal)

            feat_upd = jnp.where(is_internal, best_feat, -1)
            feature = feature.at[node_ids].set(feat_upd, mode="drop")
            split_bin = split_bin.at[node_ids].set(
                jnp.where(is_internal, best_bin, 0), mode="drop"
            )

            lw = jnp.arange(level_width)
            glc = gl[lw, best_feat, best_bin]
            hlc = hl[lw, best_feat, best_bin]
            lids, rids = 2 * node_ids + 1, 2 * node_ids + 2
            keep = is_internal
            node_g = node_g.at[lids].set(jnp.where(keep, glc, 0.0), mode="drop")
            node_h = node_h.at[lids].set(jnp.where(keep, hlc, 0.0), mode="drop")
            node_g = node_g.at[rids].set(
                jnp.where(keep, gt[:, 0, 0] - glc, 0.0), mode="drop"
            )
            node_h = node_h.at[rids].set(
                jnp.where(keep, ht[:, 0, 0] - hlc, 0.0), mode="drop"
            )

            row_feat = feat_upd[local]
            row_bin = best_bin[local]
            goes_left = bins[jnp.arange(n), jnp.maximum(row_feat, 0)] <= row_bin
            split_here = valid & (row_feat >= 0)
            child = 2 * node_of_row + jnp.where(goes_left, 1, 2)
            node_of_row = jnp.where(split_here, child, node_of_row)
            return feature, split_bin, node_g, node_h, node_of_row

        feature, split_bin, node_g, node_h, node_of_row = jax.lax.fori_loop(
            0,
            max_depth,
            grow_level,
            (feature, split_bin, node_g, node_h, node_of_row),
        )
        leaf_value = -node_g / (node_h + lam)
        # each row's training-time contribution comes from the node it
        # landed in (its leaf): no second tree walk needed
        return feature, split_bin, leaf_value, leaf_value[node_of_row]

    def round_step(carry, round_rng):
        raw = carry  # (n, K) running scores
        p = jax.nn.softmax(raw, axis=-1)
        g = p - y1h  # (n, K)
        h = jnp.maximum(p * (1.0 - p), 1e-6)
        # subsample=1.0 makes the mask all-ones (uniform() < 1.0 is certain)
        mask = (
            jax.random.uniform(round_rng, (n,)) < subsample
        ).astype(jnp.float32)[:, None]
        g, h = g * mask, h * mask
        feature, split_bin, leaf_value, contrib = jax.vmap(
            grow_reg_tree, in_axes=(1, 1), out_axes=(0, 0, 0, 1)
        )(g, h)  # trees: (K, nodes); contrib: (n, K)
        raw = raw + learning_rate * contrib
        return raw, (feature, split_bin, leaf_value)

    raw0 = jnp.zeros((n, num_classes), jnp.float32)
    raw, trees = jax.lax.scan(
        round_step, raw0, jax.random.split(rng, num_rounds)
    )
    return trees  # each (rounds, K, nodes)


@functools.partial(jax.jit, static_argnames=("max_depth",))
def _gbdt_predict(
    feature: jax.Array,  # (R, K, nodes)
    split_bin: jax.Array,  # (R, K, nodes)
    leaf_value: jax.Array,  # (R, K, nodes)
    bins: jax.Array,  # (n, d)
    learning_rate: float,
    max_depth: int,
):
    n = bins.shape[0]

    def walk_one(feat, sbin, leaf):
        def walk(node, _):
            f = feat[node]
            is_leaf = f < 0
            val = bins[jnp.arange(n), jnp.maximum(f, 0)]
            child = 2 * node + jnp.where(val <= sbin[node], 1, 2)
            return jnp.where(is_leaf, node, child), None

        node, _ = jax.lax.scan(
            walk, jnp.zeros((n,), jnp.int32), None, length=max_depth
        )
        return leaf[node]  # (n,)

    # (R, K, n) leaf contributions, summed over rounds
    contrib = jax.vmap(jax.vmap(walk_one))(feature, split_bin, leaf_value)
    return learning_rate * contrib.sum(0).T  # (n, K) raw scores


@dataclasses.dataclass(frozen=True)
class GradientBoostedTreesClassifier:
    """Multiclass second-order boosted trees (TPU-native; see module doc)."""

    num_rounds: int = 100
    max_depth: int = 5
    max_bins: int = 32
    learning_rate: float = 0.2
    reg_lambda: float = 1.0
    min_child_weight: float = 1e-3
    subsample: float = 1.0
    seed: int = 0
    num_classes: int | None = None

    def copy_with(self, **params) -> "GradientBoostedTreesClassifier":
        return dataclasses.replace(self, **params)

    def fit(self, data: FeatureSet) -> "GradientBoostedTreesModel":
        x = jnp.asarray(data.features, jnp.float32)
        y = jnp.asarray(data.label, jnp.int32)
        num_classes = self.num_classes or int(data.label.max()) + 1
        thresholds = quantile_thresholds(x, self.max_bins)
        bins = binize(x, thresholds)
        feature, split_bin, leaf_value = _gbdt_fit(
            bins,
            y,
            jax.random.PRNGKey(self.seed),
            num_classes=num_classes,
            num_rounds=self.num_rounds,
            max_depth=self.max_depth,
            max_bins=self.max_bins,
            learning_rate=self.learning_rate,
            lam=self.reg_lambda,
            min_child_weight=self.min_child_weight,
            subsample=self.subsample,
        )
        return GradientBoostedTreesModel(
            feature=np.asarray(feature),
            split_bin=np.asarray(split_bin),
            leaf_value=np.asarray(leaf_value),
            thresholds=np.asarray(thresholds),
            learning_rate=self.learning_rate,
            max_depth=self.max_depth,
            num_classes=num_classes,
        )


@dataclasses.dataclass(frozen=True)
class GradientBoostedTreesModel:
    feature: np.ndarray
    split_bin: np.ndarray
    leaf_value: np.ndarray
    thresholds: np.ndarray
    learning_rate: float
    max_depth: int
    num_classes: int

    def predict_raw(self, x: np.ndarray) -> np.ndarray:
        bins = binize(
            jnp.asarray(x, jnp.float32), jnp.asarray(self.thresholds)
        )
        raw = _gbdt_predict(
            jnp.asarray(self.feature),
            jnp.asarray(self.split_bin),
            jnp.asarray(self.leaf_value),
            bins,
            self.learning_rate,
            max_depth=self.max_depth,
        )
        return np.asarray(raw)

    def transform(self, data: FeatureSet) -> Predictions:
        raw = self.predict_raw(np.asarray(data.features, np.float32))
        probs = np.asarray(jax.nn.softmax(jnp.asarray(raw), axis=-1))
        return Predictions.from_raw(raw, probs)
