"""Clean-room port of Breeze 0.13.2's LBFGS / OWLQN optimizer stack.

MLlib's LogisticRegression (the engine behind reference Main/main.py:115,
202-222) optimizes with ``breeze.optimize.LBFGS`` (elasticNetParam == 0) or
``breeze.optimize.OWLQN`` (elasticNet > 0), both built on
``FirstOrderMinimizer``.  The reference's published numbers are the iterate
these optimizers reach at maxIter=20 — far from the optimum — so matching
them requires replaying the exact trajectory: the same two-loop recursion,
the same Strong Wolfe / backtracking line searches, the same convergence
checks, the same failure/retry semantics, in the same IEEE-754 operation
order.

Bit-exactness notes (each deliberate, each breaks the replay if "fixed"):
  - All dot products (and the norms derived from them — Breeze's
    ``InnerProductModule`` defines norm(v) = sqrt(v dot v)) go through a
    strict left-to-right accumulator (`_jvm_native.ddot`), the order
    netlib-java's F2J ``ddot`` reduces in.  numpy.dot's pairwise/BLAS
    orders differ in the last ulp.
  - Elementwise vector arithmetic uses numpy float64, which matches the
    JVM's per-element semantics exactly (no FMA, no reassociation).
  - Scalar arithmetic happens in Python floats = IEEE doubles, written in
    the same association order as the Scala source.

The port covers exactly what MLlib exercises; it is not a general Breeze
replacement.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Sequence

import numpy as np

from har_tpu.models._jvm_native import ddot


class FirstOrderException(Exception):
    """breeze.optimize.FirstOrderException and subclasses."""


def _norm(v: np.ndarray) -> float:
    """Breeze norm(v) via InnerProductModule: sqrt(v dot v), F2J order."""
    return math.sqrt(ddot(v, v))


# ---------------------------------------------------------------------------
# Line searches
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Bracket:
    t: float
    dd: float
    fval: float


class StrongWolfeLineSearch:
    """breeze.optimize.StrongWolfeLineSearch (c1=1e-4, c2=0.9)."""

    def __init__(self, max_zoom_iter: int = 10, max_line_search_iter: int = 10):
        self.max_zoom_iter = max_zoom_iter
        self.max_line_search_iter = max_line_search_iter
        self.c1 = 1e-4
        self.c2 = 0.9

    @staticmethod
    def _interp(l: _Bracket, r: _Bracket) -> float:
        # CubicLineSearch.interp (N&W p57), incl. the 10%/90% clamping
        d1 = l.dd + r.dd - 3 * (l.fval - r.fval) / (l.t - r.t)
        d2 = math.sqrt(d1 * d1 - l.dd * r.dd) if d1 * d1 - l.dd * r.dd >= 0 else float("nan")
        multipler = r.t - l.t
        t = r.t - multipler * (r.dd + d2 - d1) / (r.dd - l.dd + 2 * d2)
        lw_bound = l.t + 0.1 * (r.t - l.t)
        up_bound = l.t + 0.9 * (r.t - l.t)
        if t < lw_bound:
            return lw_bound
        if t > up_bound:
            return up_bound
        return t

    def minimize(self, f: Callable[[float], tuple[float, float]], init: float) -> float:
        def phi(t: float) -> _Bracket:
            pval, pdd = f(t)
            return _Bracket(t=t, dd=pdd, fval=pval)

        t = init
        low = phi(0.0)
        fval = low.fval
        dd = low.dd

        if dd > 0:
            raise FirstOrderException(
                "Line search invoked with non-descent direction: " + str(dd)
            )

        c1, c2 = self.c1, self.c2

        def zoom(linit: _Bracket, rinit: _Bracket) -> float:
            lo = linit
            hi = rinit
            for _ in range(self.max_zoom_iter):
                # Interp assumes left less than right in t value; flip if needed
                if lo.t > hi.t:
                    t = self._interp(hi, lo)
                else:
                    t = self._interp(lo, hi)
                c = phi(t)
                if c.fval > fval + c1 * c.t * dd or c.fval >= lo.fval:
                    # sufficient decrease not satisfied: shrink at right
                    hi = c
                else:
                    if abs(c.dd) <= c2 * abs(dd):
                        return c.t
                    if c.dd * (hi.t - lo.t) >= 0:
                        hi = lo
                    lo = c
            raise FirstOrderException("Line search zoom failed")

        for i in range(self.max_line_search_iter):
            c = phi(t)
            if math.isinf(c.fval) or math.isnan(c.fval):
                t /= 2.0
            else:
                # Zoom if "sufficient decrease" condition is not satisfied
                if (c.fval > fval + c1 * t * dd) or (c.fval >= low.fval and i > 0):
                    return zoom(low, c)
                # No zoom needed if the strong wolfe condition already holds
                if abs(c.dd) <= c2 * abs(dd):
                    return c.t
                # If c.dd is positive, zoom on the inverted interval
                if c.dd >= 0:
                    return zoom(c, low)
                low = c
                t *= 1.5
        raise FirstOrderException("Line search failed")


class BacktrackingLineSearch:
    """breeze.optimize.BacktrackingLineSearch with OWLQN's parameters
    (enforce[Strong]WolfeConditions = true)."""

    def __init__(
        self,
        max_iterations: int = 20,
        shrink_step: float = 0.5,
        grow_step: float = 2.1,
        c_armijo: float = 1e-4,
        c_wolfe: float = 0.9,
        min_alpha: float = 1e-10,
        max_alpha: float = 1e10,
    ):
        self.max_iterations = max_iterations
        self.shrink_step = shrink_step
        self.grow_step = grow_step
        self.c_armijo = c_armijo
        self.c_wolfe = c_wolfe
        self.min_alpha = min_alpha
        self.max_alpha = max_alpha

    def minimize(self, f: Callable[[float], tuple[float, float]], init: float) -> float:
        f0, df0 = f(0.0)
        alpha = init
        fval, fderiv = f(init)
        it = 0
        while True:
            if fval > f0 + alpha * df0 * self.c_armijo:
                multiplier = self.shrink_step
            elif fderiv < self.c_wolfe * df0:
                multiplier = self.grow_step
            elif fderiv > -self.c_wolfe * df0:
                multiplier = self.shrink_step
            else:
                multiplier = 1.0
            if multiplier == 1.0:
                return alpha
            new_alpha = alpha * multiplier
            if it >= self.max_iterations:
                raise FirstOrderException("Too many iterations.")
            if new_alpha < self.min_alpha:
                raise FirstOrderException("Step size underflow")
            if new_alpha > self.max_alpha:
                raise FirstOrderException("Step size overflow")
            alpha = new_alpha
            fval, fderiv = f(alpha)
            it += 1


# ---------------------------------------------------------------------------
# L-BFGS history (two-loop recursion)
# ---------------------------------------------------------------------------


class _History:
    """LBFGS.ApproximateInverseHessian: memStep/memGradDelta deques
    (newest first), * = two-loop recursion returning the NEGATED direction."""

    def __init__(self, m: int, mem_step=None, mem_grad_delta=None):
        self.m = m
        self.mem_step: list[np.ndarray] = mem_step or []
        self.mem_grad_delta: list[np.ndarray] = mem_grad_delta or []

    def updated(self, step: np.ndarray, grad_delta: np.ndarray) -> "_History":
        return _History(
            self.m,
            ([step] + self.mem_step)[: self.m],
            ([grad_delta] + self.mem_grad_delta)[: self.m],
        )

    @property
    def history_length(self) -> int:
        return len(self.mem_step)

    def times(self, grad: np.ndarray) -> np.ndarray:
        hl = self.history_length
        if hl > 0:
            prev_step = self.mem_step[0]
            prev_grad_step = self.mem_grad_delta[0]
            sy = ddot(prev_step, prev_grad_step)
            yy = ddot(prev_grad_step, prev_grad_step)
            if sy < 0 or math.isnan(sy):
                raise FirstOrderException("NaN history")
            diag = sy / yy
        else:
            diag = 1.0

        dir = grad.copy()
        as_ = [0.0] * self.m
        rho = [0.0] * self.m
        for i in range(hl):
            rho[i] = ddot(self.mem_step[i], self.mem_grad_delta[i])
            as_[i] = ddot(self.mem_step[i], dir) / rho[i]
            if math.isnan(as_[i]):
                raise FirstOrderException("NaN history")
            # axpy(-as(i), memGradDelta(i), dir)
            dir += (-as_[i]) * self.mem_grad_delta[i]
        dir *= diag
        for i in range(hl - 1, -1, -1):
            beta = ddot(self.mem_grad_delta[i], dir) / rho[i]
            dir += (as_[i] - beta) * self.mem_step[i]
        dir *= -1.0
        return dir


# ---------------------------------------------------------------------------
# FirstOrderMinimizer state machine
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class State:
    x: np.ndarray
    value: float
    grad: np.ndarray
    adjusted_value: float
    adjusted_gradient: np.ndarray
    iter: int
    initial_adj_val: float
    history: _History
    fval_info: tuple[float, ...]  # FunctionValuesConverged window
    search_failed: bool = False
    converged_reason: str | None = None


class LBFGS:
    """breeze.optimize.LBFGS with MLlib's construction
    (maxIter, m=10, tolerance) → defaultConvergenceCheck(maxIter, tol)
    [relative=false, fvalMemory=20]."""

    FVAL_MEMORY = 20

    def __init__(self, max_iter: int, m: int = 10, tolerance: float = 1e-6):
        self.max_iter = max_iter
        self.m = m
        self.tolerance = tolerance

    # --- hooks the OWLQN subclass overrides --------------------------------

    def adjust(
        self, new_x: np.ndarray, new_grad: np.ndarray, new_val: float
    ) -> tuple[float, np.ndarray]:
        return new_val, new_grad

    def choose_descent_direction(self, state: State) -> np.ndarray:
        return state.history.times(state.grad)

    def take_step(self, state: State, dir: np.ndarray, step_size: float) -> np.ndarray:
        return state.x + dir * step_size

    def determine_step_size(self, state: State, f, dir: np.ndarray) -> float:
        x = state.x
        grad = state.grad

        def ff(alpha: float) -> tuple[float, float]:
            v, g = f(x + dir * alpha)
            return v, ddot(g, dir)

        search = StrongWolfeLineSearch(max_zoom_iter=10, max_line_search_iter=10)
        alpha = search.minimize(ff, 1.0 / _norm(dir) if state.iter == 0.0 else 1.0)
        if alpha * _norm(grad) < 1e-10:
            raise FirstOrderException("Step size underflow")
        return alpha

    def update_history(
        self,
        new_x: np.ndarray,
        new_grad: np.ndarray,
        new_val: float,
        old_state: State,
    ) -> _History:
        return old_state.history.updated(
            new_x - old_state.x, new_grad - old_state.grad
        )

    # --- convergence (FirstOrderMinimizer.defaultConvergenceCheck) ---------

    def _converged(self, state: State) -> str | None:
        if state.iter >= self.max_iter and self.max_iter >= 0:
            return "max iterations"
        info = state.fval_info
        if len(info) >= 2 and abs(state.adjusted_value - max(info)) <= self.tolerance:
            return "function values converged"
        if _norm(state.adjusted_gradient) <= max(self.tolerance, 1e-8):
            return "gradient converged"
        if state.search_failed:
            return "line search failed"
        return None

    # --- driver ------------------------------------------------------------

    def _initial_state(self, f, init: np.ndarray) -> State:
        x = init
        history = _History(self.m)
        value, grad = f(x)
        adj_value, adj_grad = self.adjust(x, grad, value)
        return State(
            x=x,
            value=value,
            grad=grad,
            adjusted_value=adj_value,
            adjusted_gradient=adj_grad,
            iter=0,
            initial_adj_val=adj_value,
            history=history,
            fval_info=(),
        )

    def iterations(self, f, init: np.ndarray):
        """Yields the State sequence (initial state first), stopping
        inclusively at the first converged state — Breeze's
        ``iterations(...).takeUpToWhere`` consumed the way MLlib does
        (`while (states.hasNext) state = states.next()`)."""
        state = self._initial_state(f, init)
        failed_once = False
        while True:
            reason = self._converged(state)
            if reason is not None:
                state.converged_reason = reason
                yield state
                return
            yield state
            try:
                dir = self.choose_descent_direction(state)
                step_size = self.determine_step_size(state, f, dir)
                x = self.take_step(state, dir, step_size)
                value, grad = f(x)
                adj_value, adj_grad = self.adjust(x, grad, value)
                history = self.update_history(x, grad, value, state)
                new_info = (state.fval_info + (adj_value,))[-self.FVAL_MEMORY:]
                state = State(
                    x=x,
                    value=value,
                    grad=grad,
                    adjusted_value=adj_value,
                    adjusted_gradient=adj_grad,
                    iter=state.iter + 1,
                    initial_adj_val=state.initial_adj_val,
                    history=history,
                    fval_info=new_info,
                )
                failed_once = False
            except FirstOrderException:
                if not failed_once:
                    # "Failure! Resetting history"
                    failed_once = True
                    state = dataclasses.replace(
                        state, history=_History(self.m)
                    )
                else:
                    # "Failure again! Giving up and returning."
                    state = dataclasses.replace(state, search_failed=True)

    def minimize_state(self, f, init: np.ndarray) -> State:
        state = None
        for state in self.iterations(f, init):
            pass
        return state

    def minimize(self, f, init: np.ndarray) -> np.ndarray:
        return self.minimize_state(f, init).x


def _signum(x: float) -> float:
    if x > 0:
        return 1.0
    if x < 0:
        return -1.0
    return x  # preserves ±0.0 / NaN like scala math.signum


class OWLQN(LBFGS):
    """breeze.optimize.OWLQN[Int, DenseVector[Double]] as MLlib builds it:
    l1reg(index) = regParamL1 for coefficient entries, 0.0 for intercepts
    (standardization=true path)."""

    def __init__(
        self,
        max_iter: int,
        m: int,
        l1reg: np.ndarray,  # per-index L1 weight (>= 0)
        tolerance: float = 1e-6,
    ):
        super().__init__(max_iter, m, tolerance)
        self.l1reg = np.ascontiguousarray(l1reg, np.float64)

    def choose_descent_direction(self, state: State) -> np.ndarray:
        # super's two-loop, run on the ADJUSTED gradient
        pseudo_state = dataclasses.replace(state, grad=state.adjusted_gradient)
        descent_dir = super().choose_descent_direction(pseudo_state)
        # correct the direction into the same orthant as the adjusted grad
        d, g = descent_dir, state.adjusted_gradient
        return np.where(d * g < 0, d, 0.0)

    def determine_step_size(self, state: State, f, dir: np.ndarray) -> float:
        it = state.iter

        def ff(alpha: float) -> tuple[float, float]:
            new_x = self.take_step(state, dir, alpha)
            v, new_g = f(new_x)
            adj_v, adj_g = self.adjust(new_x, new_g, v)
            return adj_v, ddot(adj_g, dir)

        search = BacktrackingLineSearch(
            shrink_step=0.1 if it < 1 else 0.5
        )
        return search.minimize(ff, 0.5 / _norm(state.grad) if it < 1 else 1.0)

    def take_step(self, state: State, dir: np.ndarray, step_size: float) -> np.ndarray:
        stepped = state.x + dir * step_size
        # computeOrthant(x, adjustedGradient)
        x, g = state.x, state.adjusted_gradient
        orthant = np.where(x != 0, np.sign(x), -np.sign(g))
        # v * I(signum(v) == signum(orthant)); ±0.0 compare equal, NaN never
        sv = np.sign(stepped)
        keep = sv == orthant
        nan_mask = np.isnan(sv) | np.isnan(orthant)
        return stepped * np.where(keep & ~nan_mask, 1.0, 0.0)

    def adjust(
        self, new_x: np.ndarray, new_grad: np.ndarray, new_val: float
    ) -> tuple[float, np.ndarray]:
        l1 = self.l1reg
        x, v = new_x, new_grad
        # adjValue += Σ |l1reg(i) * x(i)| over active entries, index order —
        # a strict sequential accumulation (mapActive walks ascending)
        contrib = np.abs(l1 * x)
        mask = l1 != 0.0
        # Breeze folds each |l1*x_i| into an accumulator INITIALIZED at
        # newVal ((newVal+c0)+c1...), not newVal + (0+c0+c1...): start the
        # sequential fold at new_val so the FP association matches exactly.
        # Zero contributions leave the accumulator bit-identical (x+0.0==x
        # for any finite non-negative x; new_val is a loss, never -0.0), so
        # only nonzeros are folded — in index order, like mapActive's walk.
        nz = contrib[mask]
        adj_value = _sequential_sum(nz[nz != 0.0], init=new_val)
        delta_plus = v + l1
        delta_minus = v - l1
        at_zero = np.where(
            delta_minus > 0,
            delta_minus,
            np.where(delta_plus < 0, delta_plus, 0.0),
        )
        sgn = np.sign(x)
        nonzero = v + sgn * l1
        res = np.where(mask, np.where(x == 0.0, at_zero, nonzero), v)
        return adj_value, res


def _sequential_sum(values: np.ndarray, init: float = 0.0) -> float:
    """Strict left-to-right sum starting at init (JVM accumulation order)."""
    acc = float(init)
    for v in values:
        acc += float(v)
    return acc
