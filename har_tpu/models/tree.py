"""Histogram-based decision trees on TPU.

Replaces MLlib's distributed tree induction (reference Main/main.py:297 —
DecisionTreeClassifier(maxDepth=3); SURVEY §3.3: executors build per-feature
histograms with maxBins quantization, the driver picks splits level by
level).  The TPU re-design keeps the same algorithm family — quantized
features + class histograms + level-wise growth — but as static-shape XLA:

  - **Binning**: per-feature quantile thresholds (≤ max_bins-1 of them),
    features quantized once to int8 bin ids.  (MLlib: approximate quantile
    sketch per feature.)
  - **Level-wise growth**: one `segment_sum` scatter per level builds the
    (nodes, features, bins, classes) histogram in a single fused program —
    the "executors aggregate histograms" step becomes one XLA reduction
    (and a psum over `dp` when row-sharded).
  - **Split selection**: cumulative sums over the bin axis give left/right
    class counts for every candidate split simultaneously; weighted Gini
    gain, argmax over (feature, bin).  No data-dependent control flow —
    nodes that shouldn't split (pure / too small / no gain) emit a
    sentinel and their rows keep routing to the same side.
  - The tree is a complete binary array of depth ``max_depth``:
    feature[node], threshold[node], leaf_class[node], is_leaf[node].
    Prediction walks it with a `lax.scan` over depth (vmapped over rows).

Per-row sample weights are first-class so RandomForest can reuse this
builder with bootstrap counts as weights.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from har_tpu.features.wisdm_pipeline import FeatureSet
from har_tpu.models.base import Predictions


@functools.lru_cache(maxsize=1)
def _hist_bench_prefers_pallas() -> bool | None:
    """artifacts/hist_bench.json's measured verdict, or None when absent."""
    from har_tpu.utils.artifacts import load_artifact

    doc = load_artifact("hist_bench.json")
    policy = (doc or {}).get("auto_policy", "")
    return policy.startswith("pallas") if policy else None


def auto_pallas_hist(flag: bool | None, max_bins: int = 32) -> bool:
    """Resolve a use_pallas_hist tri-state to a concrete choice.

    Explicit True/False wins (an explicit True outside the kernel's
    validated envelope then fails loudly in hist_matmul).  Auto (None)
    consults the measured comparison in artifacts/hist_bench.json
    (scripts/hist_bench.py, VERDICT r3 #6b: "a kernel nobody measures is
    a liability") — and never selects the kernel beyond its validated
    ``MAX_BINS_SUPPORTED`` envelope, where larger bin counts exceed the
    per-tile VMEM budget (the bins=128 workload crashed the TPU
    compiler; see pallas_hist.py).  Off-TPU the kernel would run in
    interpret mode, so auto is always False there.  No evidence →
    matmul: the committed measurement has the kernel losing 0.96-0.98x,
    so the safe default and the measured default coincide.
    """
    if flag is not None:
        return flag
    if jax.default_backend() != "tpu":
        return False
    from har_tpu.ops.pallas_hist import MAX_BINS_SUPPORTED

    if max_bins > MAX_BINS_SUPPORTED:
        return False
    return _hist_bench_prefers_pallas() is True


def quantile_thresholds(
    x: jax.Array, max_bins: int
) -> jax.Array:
    """(d, max_bins-1) per-feature candidate split thresholds.

    Evenly spaced quantiles of each feature.  Repeated thresholds are
    harmless: they yield empty bins and zero-gain splits.  The parity
    default is :func:`mllib_split_candidates`; this stays as the cheap
    on-device alternative for wide synthetic sweeps.
    """
    qs = jnp.linspace(0.0, 1.0, max_bins + 1)[1:-1]
    return jnp.quantile(x, qs, axis=0).T  # (d, B-1)


def mllib_split_candidates(x: np.ndarray, max_bins: int) -> np.ndarray:
    """(d, max_bins-1) thresholds, faithful to MLlib's findSplits.

    Spark's ``RandomForest.findSplitsForContinuousFeature``: when a feature
    has ``<= max_bins`` distinct values the candidates are the midpoints
    between every pair of adjacent distinct values (exact for the 3,090
    one-hot dims — a single 0.5 threshold); otherwise a stride walk over
    the distinct-value histogram places ``max_bins - 1`` thresholds at
    (approximately) equal-count boundaries, each again a midpoint of
    adjacent distinct values.  This is the split-candidate set the
    reference's DT/RF searched (Main/main.py:297,478), so gains — and
    trees — line up with the captured run.

    Parity scope (ADVICE r2): Spark computes candidates on a SAMPLE when
    n > max(maxBins², 10000); WISDM's 3,793 rows are below that
    threshold, so this unsampled walk is exact here, but above it the
    candidate set (and the parity claim) diverges — and the host-side
    per-feature np.unique loop is also slower than the on-device
    "quantile" method for large non-binary data.  Prefer
    split_candidates="quantile" off the WISDM parity lanes.

    Unused candidate slots are padded with ``+inf``: their "splits" route
    every row left and are rejected by the min-instances guard.
    """
    x = np.asarray(x, np.float64)
    n, d = x.shape
    num_splits = max_bins - 1
    out = np.full((d, num_splits), np.inf, np.float64)
    # vectorized fast path: {0,1}-valued columns (the one-hot block)
    is01 = ((x == 0.0) | (x == 1.0)).all(axis=0)
    binary = is01 & (x == 0.0).any(axis=0) & (x == 1.0).any(axis=0)
    out[binary, 0] = 0.5
    for j in np.nonzero(~binary)[0]:
        vals, counts = np.unique(x[:, j], return_counts=True)
        possible = len(vals) - 1
        if possible == 0:
            continue  # constant feature: no candidates
        mids = (vals[:-1] + vals[1:]) / 2.0
        if possible <= num_splits:
            out[j, :possible] = mids
            continue
        stride = n / (num_splits + 1)
        chosen: list[float] = []
        current = int(counts[0])
        target = stride
        for idx in range(1, len(vals)):
            prev = current
            current += int(counts[idx])
            if abs(prev - target) < abs(current - target):
                chosen.append(mids[idx - 1])
                target += stride
        out[j, : len(chosen)] = chosen[:num_splits]
    return out.astype(np.float32)


def split_thresholds(
    features: np.ndarray, max_bins: int, method: str
) -> jax.Array:
    """Resolve a split-candidate method name to a (d, B-1) threshold array."""
    if method == "mllib":
        return jnp.asarray(mllib_split_candidates(features, max_bins))
    if method == "quantile":
        return quantile_thresholds(
            jnp.asarray(features, jnp.float32), max_bins
        )
    raise ValueError(f"unknown split_candidates method {method!r}")


def binize(x: jax.Array, thresholds: jax.Array) -> jax.Array:
    """Quantize features: bin id = number of thresholds strictly below x.

    vmapped searchsorted over the feature axis — O(n·d·log B) and O(n·d)
    memory, so the 3,100-dim one-hot space quantizes without materializing
    an (n, d, B) comparison tensor.
    """
    return jax.vmap(
        lambda t, col: jnp.searchsorted(t, col, side="left"),
        in_axes=(0, 1),
        out_axes=1,
    )(thresholds, x).astype(jnp.int32)


@dataclasses.dataclass(frozen=True)
class TreeArrays:
    """A complete binary tree of depth D as arrays of length 2^(D+1)-1."""

    feature: np.ndarray  # int32, -1 for leaves
    threshold: np.ndarray  # float32 split threshold (x <= t goes left)
    leaf_class: np.ndarray  # int32 argmax class at the node
    leaf_probs: np.ndarray  # (nodes, C) class distribution at the node
    max_depth: int
    # (nodes, C) raw class COUNTS — MLlib's rawPrediction column is the
    # leaf's impurity stats, not the normalized distribution, and the
    # Binary evaluator's threshold sweep ranks by it; None on checkpoints
    # predating the field (transform then falls back to probabilities)
    leaf_counts: np.ndarray | None = None


def _gini(counts: jax.Array) -> jax.Array:
    """Weighted Gini impurity × total weight, per leading index.

    counts: (..., C).  Returns total * (1 - Σ p²) = total - Σ c²/total,
    the 'weighted impurity' formulation that makes gain additive.
    """
    total = counts.sum(-1)
    sq = (counts * counts).sum(-1)
    return total - sq / jnp.maximum(total, 1e-12)


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_classes",
        "max_depth",
        "max_bins",
        "min_instances",
        "features_per_split",
        "use_pallas_hist",
    ),
)
def _grow_tree(
    bins: jax.Array,  # (n, d) int32 bin ids
    thresholds: jax.Array,  # (d, B-1)
    y: jax.Array,  # (n,) int32
    weights: jax.Array,  # (n,) float32 (0 = row not in this tree)
    feature_mask_rng: jax.Array | None,
    num_classes: int,
    max_depth: int,
    max_bins: int,
    min_instances: int = 1,
    features_per_split: int = 0,  # 0 → all features (DT); >0 → RF subset
    use_pallas_hist: bool = False,
):
    n, d = bins.shape
    n_nodes = 2 ** (max_depth + 1) - 1
    n_internal = 2**max_depth - 1

    feature = jnp.full((n_nodes,), -1, jnp.int32)
    threshold = jnp.zeros((n_nodes,), jnp.float32)
    node_counts = jnp.zeros((n_nodes, num_classes), jnp.float32)

    # root class counts
    root = jax.ops.segment_sum(weights, y, num_segments=num_classes)
    node_counts = node_counts.at[0].set(root)

    node_of_row = jnp.zeros((n,), jnp.int32)  # global node id per row

    # One-hot of bin ids, (n, d*B) bf16 — shared across all levels (and all
    # trees when vmapped: it depends only on the data).  This turns the
    # histogram into a single MXU matmul per level instead of a giant
    # scatter-add: 0/1 and small-integer weights are exact in bf16 and the
    # matmul accumulates in f32, so the counts are exact.
    # With use_pallas_hist the indicator is never materialized at all: the
    # fused kernel (har_tpu.ops.pallas_hist) expands bin ids to the
    # indicator tile-by-tile in VMEM — at the reference's 3,100-dim one-hot
    # space the HBM one-hot is ~1 GB, the kernel's working set is ~10 MB.
    bins_onehot = (
        None
        if use_pallas_hist
        else jax.nn.one_hot(bins, max_bins, dtype=jnp.bfloat16).reshape(
            n, d * max_bins
        )
    )

    def grow_level(level, carry):
        feature, threshold, node_counts, node_of_row = carry
        level_width = 2**max_depth  # static upper bound on nodes per level
        first = 2**level - 1  # first node id at this level (traced)

        local = node_of_row - first  # (n,) position within level
        valid = (local >= 0) & (local < level_width)
        local = jnp.clip(local, 0, level_width - 1)

        # histogram: (level_width, d, B, C) as (W*C, n) @ (n, d*B) on the MXU
        w = jnp.where(valid, weights, 0.0)
        m_dtype = jnp.float32 if use_pallas_hist else jnp.bfloat16
        m = (
            jax.nn.one_hot(
                local * num_classes + y,
                level_width * num_classes,
                dtype=m_dtype,
            )
            * w[:, None].astype(m_dtype)
        )
        if use_pallas_hist:
            from har_tpu.ops.pallas_hist import hist_matmul

            hist = hist_matmul(bins, m, max_bins)  # (W*C, d*B)
        else:
            hist = jax.lax.dot_general(
                m,
                bins_onehot,
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (W*C, d*B)
        hist = (
            hist.reshape(level_width, num_classes, d, max_bins)
            .transpose(0, 2, 3, 1)
        )

        # left counts for split at bin b = Σ_{bin<=b} ; candidates are the
        # first B-1 bins (split "x <= threshold[b]")
        cum = jnp.cumsum(hist, axis=2)  # (W, d, B, C)
        left = cum[:, :, : max_bins - 1, :]
        total = cum[:, :, -1, :][:, :, None, :]
        right = total - left

        parent_imp = _gini(total)  # (W, d, 1)
        gain = parent_imp - _gini(left) - _gini(right)  # (W, d, B-1)

        left_n = left.sum(-1)
        right_n = right.sum(-1)
        ok = (left_n >= min_instances) & (right_n >= min_instances)
        if features_per_split:
            # random feature subset per (node, level) — MLlib's per-node
            # featureSubsetStrategy, implemented as top-k of random keys
            rng = jax.random.fold_in(feature_mask_rng, level)
            scores = jax.random.uniform(rng, (level_width, d))
            kth = jnp.sort(scores, axis=1)[:, features_per_split - 1]
            fmask = scores <= kth[:, None]  # (W, d)
            ok = ok & fmask[:, :, None]
        gain = jnp.where(ok, gain, -jnp.inf)

        flat = gain.reshape(level_width, -1)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
        best_feat = (best // (max_bins - 1)).astype(jnp.int32)
        best_bin = (best % (max_bins - 1)).astype(jnp.int32)
        splittable = jnp.isfinite(best_gain) & (best_gain > 1e-12)

        node_ids = first + jnp.arange(level_width)
        in_level = node_ids < first + level_width  # always true; keeps shape
        is_internal = splittable & in_level & (node_ids < n_internal)

        feat_upd = jnp.where(is_internal, best_feat, -1)
        thr_upd = thresholds[best_feat, best_bin]
        feature = feature.at[node_ids].set(feat_upd, mode="drop")
        threshold = threshold.at[node_ids].set(
            jnp.where(is_internal, thr_upd, 0.0), mode="drop"
        )

        # children class counts
        lw = jnp.arange(level_width)
        lcounts = left[lw, best_feat, best_bin]  # (W, C)
        rcounts = total[:, 0, 0, :] - lcounts
        lids, rids = 2 * node_ids + 1, 2 * node_ids + 2
        node_counts = node_counts.at[lids].set(
            jnp.where(is_internal[:, None], lcounts, 0.0), mode="drop"
        )
        node_counts = node_counts.at[rids].set(
            jnp.where(is_internal[:, None], rcounts, 0.0), mode="drop"
        )

        # route rows to children where their node split
        row_feat = feat_upd[local]  # (n,)
        row_thr = thr_upd[local]
        row_bin_thr = best_bin[local]
        goes_left = bins[jnp.arange(n), jnp.maximum(row_feat, 0)] <= row_bin_thr
        split_here = valid & (row_feat >= 0)
        child = 2 * node_of_row + jnp.where(goes_left, 1, 2)
        node_of_row = jnp.where(split_here, child, node_of_row)
        return feature, threshold, node_counts, node_of_row

    feature, threshold, node_counts, _ = jax.lax.fori_loop(
        0,
        max_depth,
        grow_level,
        (feature, threshold, node_counts, node_of_row),
    )

    leaf_class = jnp.argmax(node_counts, axis=1).astype(jnp.int32)
    denom = jnp.maximum(node_counts.sum(-1, keepdims=True), 1e-12)
    leaf_probs = node_counts / denom
    return feature, threshold, leaf_class, leaf_probs, node_counts


@functools.partial(jax.jit, static_argnames=("max_depth",))
def _walk_tree(
    feature: jax.Array,
    threshold: jax.Array,
    x: jax.Array,
    max_depth: int,
):
    """Leaf node id per row (vmapped scan over depth)."""
    n = x.shape[0]

    def walk(node, _):
        feat = feature[node]
        thr = threshold[node]
        is_leaf = feat < 0
        val = x[jnp.arange(n), jnp.maximum(feat, 0)]
        child = 2 * node + jnp.where(val <= thr, 1, 2)
        return jnp.where(is_leaf, node, child), None

    node, _ = jax.lax.scan(
        walk, jnp.zeros((n,), jnp.int32), None, length=max_depth
    )
    return node


def _predict_tree(feature, threshold, leaf_probs, x, max_depth):
    return leaf_probs[_walk_tree(feature, threshold, x, max_depth)]


@dataclasses.dataclass(frozen=True)
class DecisionTreeClassifier:
    """Reference defaults: maxDepth=3 (Main/main.py:297), maxBins=32."""

    max_depth: int = 3
    max_bins: int = 32
    min_instances_per_node: int = 1
    num_classes: int | None = None
    # mllib: exact MLlib split-candidate set (parity default);
    # quantile: evenly spaced on-device quantiles
    split_candidates: str = "mllib"
    # None = auto: evidence-based policy (auto_pallas_hist) — the
    # measured winner from artifacts/hist_bench.json on TPU, the XLA
    # one-hot matmul elsewhere (the kernel would run in slow interpret
    # mode off-TPU)
    use_pallas_hist: bool | None = None

    def copy_with(self, **params) -> "DecisionTreeClassifier":
        return dataclasses.replace(self, **params)

    def fit(
        self, data: FeatureSet, sample_weight: np.ndarray | None = None
    ) -> "DecisionTreeModel":
        x = jnp.asarray(data.features, jnp.float32)
        y = jnp.asarray(data.label, jnp.int32)
        num_classes = self.num_classes or int(data.label.max()) + 1
        w = (
            jnp.ones_like(y, jnp.float32)
            if sample_weight is None
            else jnp.asarray(sample_weight, jnp.float32)
        )
        thresholds = split_thresholds(
            data.features, self.max_bins, self.split_candidates
        )
        bins = binize(x, thresholds)
        feature, threshold, leaf_class, leaf_probs, leaf_counts = _grow_tree(
            bins,
            thresholds,
            y,
            w,
            None,
            num_classes=num_classes,
            max_depth=self.max_depth,
            max_bins=self.max_bins,
            min_instances=self.min_instances_per_node,
            use_pallas_hist=auto_pallas_hist(
                self.use_pallas_hist, self.max_bins
            ),
        )
        return DecisionTreeModel(
            tree=TreeArrays(
                feature=np.asarray(feature),
                threshold=np.asarray(threshold),
                leaf_class=np.asarray(leaf_class),
                leaf_probs=np.asarray(leaf_probs),
                max_depth=self.max_depth,
                leaf_counts=np.asarray(leaf_counts),
            ),
            num_classes=num_classes,
        )


@dataclasses.dataclass(frozen=True)
class DecisionTreeModel:
    tree: TreeArrays
    num_classes: int

    @property
    def num_nodes(self) -> int:
        """Count of reachable decision+leaf nodes (MLlib-style numNodes)."""
        return int(_count_reachable(self.tree))

    def transform(self, data: FeatureSet) -> Predictions:
        node = np.asarray(
            _walk_tree(
                jnp.asarray(self.tree.feature),
                jnp.asarray(self.tree.threshold),
                jnp.asarray(data.features, jnp.float32),
                max_depth=self.tree.max_depth,
            )
        )
        probs = np.asarray(self.tree.leaf_probs)[node]
        # rawPrediction = the leaf's class COUNTS (MLlib semantics: the
        # Binary evaluator ranks its threshold sweep by these, which
        # orders leaves differently than normalized probabilities)
        raw = (
            np.asarray(self.tree.leaf_counts)[node]
            if self.tree.leaf_counts is not None
            else probs
        )
        return Predictions.from_raw(raw, probs)


def _count_reachable(tree: TreeArrays) -> int:
    count = 0
    stack = [0]
    while stack:
        node = stack.pop()
        count += 1
        if node < len(tree.feature) and tree.feature[node] >= 0:
            stack.extend((2 * node + 1, 2 * node + 2))
    return count
