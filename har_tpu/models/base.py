"""Estimator/model protocol shared by every classifier.

Mirrors the shape of the MLlib API the reference drives (estimator.fit →
model.transform, reference Main/main.py:115-130) but over device arrays: a
model's ``transform`` returns raw scores, probabilities and argmax
predictions in one batch, computed inside jit.
"""

from __future__ import annotations

import dataclasses
from typing import Protocol, runtime_checkable

import jax
import numpy as np

from har_tpu.features.wisdm_pipeline import FeatureSet


@dataclasses.dataclass(frozen=True)
class Predictions:
    """Per-row outputs, the analogue of MLlib's prediction columns."""

    raw: np.ndarray  # (n, C) rawPrediction (margins / votes)
    probability: np.ndarray  # (n, C)
    prediction: np.ndarray  # (n,) argmax class

    def __len__(self) -> int:
        return len(self.prediction)

    @staticmethod
    def from_raw(raw: jax.Array, probability: jax.Array) -> "Predictions":
        raw = np.asarray(raw)
        probability = np.asarray(probability)
        return Predictions(
            raw=raw,
            probability=probability,
            prediction=np.asarray(probability.argmax(axis=-1), dtype=np.int32),
        )


@runtime_checkable
class ClassifierModel(Protocol):
    num_classes: int

    def transform(self, data: FeatureSet) -> Predictions: ...


@runtime_checkable
class Classifier(Protocol):
    def fit(self, data: FeatureSet) -> ClassifierModel: ...

    def copy_with(self, **params) -> "Classifier": ...
