"""Classifier-protocol wrappers over the bit-exact MLlib replays.

These adapt :mod:`har_tpu.models.mllib_lr` / :mod:`mllib_rf` /
:mod:`har_tpu.tuning.mllib_cv` to the same estimator interface the rest
of the framework uses, so the parity pipeline (har_tpu.parity) and bench
lanes can drive them interchangeably with the TPU-native lanes.

They train from the float64 sparse design the spark-exact split attaches
to its FeatureSets (``FeatureSet.exact``) — the float32 device features
are fine for the TPU lanes but have already dropped the low bits MLlib's
trajectory depends on.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from har_tpu.features.wisdm_pipeline import FeatureSet
from har_tpu.models._jvm_native import CsrMatrix
from har_tpu.models.base import Predictions


@dataclasses.dataclass(frozen=True)
class ExactDesign:
    """Float64 sparse rows + labels/uids for one split, in split order."""

    x: CsrMatrix
    label: np.ndarray  # (n,) float64
    uid: np.ndarray  # (n,) int64

    @classmethod
    def build(cls, rows, csr: CsrMatrix, idx: np.ndarray) -> "ExactDesign":
        return cls(
            x=csr.take(idx), label=rows.label[idx], uid=rows.uid[idx]
        )


class DeferredExactDesign:
    """ExactDesign materialized on first use.

    The spark-exact split attaches one of these per split so ordinary
    TPU-lane runs never pay the CSR packing; the shared dict caches the
    full-table CSR across the train/test pair."""

    def __init__(self, shared: dict, rows, idx: np.ndarray):
        self._shared = shared
        self._rows = rows
        self._idx = idx
        self._design: ExactDesign | None = None

    def _get(self) -> ExactDesign:
        if self._design is None:
            csr = self._shared.get("csr")
            if csr is None:
                csr = CsrMatrix.from_rows(
                    self._rows.sparse, self._rows.num_features
                )
                self._shared["csr"] = csr
            self._design = ExactDesign.build(self._rows, csr, self._idx)
        return self._design

    @property
    def x(self) -> CsrMatrix:
        return self._get().x

    @property
    def label(self) -> np.ndarray:
        return self._get().label

    @property
    def uid(self) -> np.ndarray:
        return self._get().uid


def require_exact(data: FeatureSet) -> ExactDesign:
    exact = getattr(data, "exact", None)
    if exact is None:
        raise ValueError(
            "this estimator replays MLlib bit-for-bit and needs the "
            "float64 design the spark-exact split attaches "
            "(FeatureSet.exact); use split_method='spark' on the WISDM "
            "one-hot view"
        )
    return exact


@dataclasses.dataclass(frozen=True)
class LogisticRegressionExact:
    """MLlib LogisticRegression, bit-exact (reference Main/main.py:115)."""

    max_iter: int = 20
    reg_param: float = 0.3
    elastic_net_param: float = 0.0
    num_classes: int | None = None

    def copy_with(self, **params) -> "LogisticRegressionExact":
        return dataclasses.replace(self, **params)

    def fit(self, data: FeatureSet) -> "ExactModel":
        from har_tpu.models.mllib_lr import fit_mllib_lr

        design = require_exact(data)
        k = self.num_classes or int(design.label.max()) + 1
        inner = fit_mllib_lr(
            design.x,
            design.label,
            num_classes=k,
            max_iter=self.max_iter,
            reg_param=self.reg_param,
            elastic_net_param=self.elastic_net_param,
        )
        return ExactModel(inner=inner, num_classes=k)


@dataclasses.dataclass(frozen=True)
class RandomForestExact:
    """MLlib RandomForestClassifier, bit-exact (Main/main.py:478).

    The default seed is the one the reference's run effectively used:
    pyspark's HasSeed default ``hash('RandomForestClassifier')`` under
    the Python 2 driver (proven by the bit-equal RF probabilities)."""

    num_trees: int = 100
    max_depth: int = 4
    max_bins: int = 32
    seed: int | None = None
    num_classes: int | None = None

    def copy_with(self, **params) -> "RandomForestExact":
        return dataclasses.replace(self, **params)

    @property
    def effective_seed(self) -> int:
        if self.seed is not None:
            return self.seed
        from har_tpu.models.mllib_rf import default_rf_seed

        return default_rf_seed()

    def fit(self, data: FeatureSet) -> "ExactModel":
        from har_tpu.models.mllib_rf import dense_from_csr, fit_mllib_rf

        design = require_exact(data)
        k = self.num_classes or int(design.label.max()) + 1
        inner = fit_mllib_rf(
            dense_from_csr(design.x),
            design.label,
            num_classes=k,
            num_trees=self.num_trees,
            max_depth=self.max_depth,
            max_bins=self.max_bins,
            seed=self.effective_seed,
        )
        return ExactModel(inner=inner, num_classes=k, dense_input=True)


@dataclasses.dataclass(frozen=True)
class ExactModel:
    inner: object  # MLlibLRModel | MLlibRFModel
    num_classes: int
    dense_input: bool = False
    best_params: dict | None = None  # set by CrossValidatorExact

    @property
    def num_trees(self) -> int:
        return len(getattr(self.inner, "trees", ()))

    def transform(self, data: FeatureSet) -> Predictions:
        design = require_exact(data)
        if self.dense_input:
            from har_tpu.models.mllib_rf import dense_from_csr

            raw, prob, pred = self.inner.transform(dense_from_csr(design.x))
        else:
            raw, prob, pred = self.inner.transform(design.x)
        return Predictions(
            raw=raw,
            probability=prob,
            prediction=pred.astype(np.int32),
        )


@dataclasses.dataclass(frozen=True)
class CrossValidatorExact:
    """PySpark CrossValidator over the exact LR trainer, with the
    reference's MAE-evaluator quirk (SURVEY §2 N) as the default."""

    estimator: LogisticRegressionExact = LogisticRegressionExact()
    num_folds: int = 5
    metric: str = "mae"
    seed: int | None = None

    def fit(self, data: FeatureSet) -> ExactModel:
        from har_tpu.tuning.mllib_cv import mllib_cross_validate

        design = require_exact(data)
        k = self.estimator.num_classes or int(design.label.max()) + 1
        result = mllib_cross_validate(
            design.x,
            design.label,
            num_folds=self.num_folds,
            seed=self.seed,
            metric=self.metric,
            max_iter=self.estimator.max_iter,
        )
        return ExactModel(
            inner=result.model,
            num_classes=k,
            best_params=result.best_params,
        )
