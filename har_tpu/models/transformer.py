"""Transformer encoder classifier over raw accelerometer windows.

A 4th neural family member (beyond MLP/CNN/BiLSTM) and the carrier for
long-context support: constructed with ``sp_axis=None`` it's an ordinary
single-device encoder; constructed with ``sp_axis="sp"`` (inside a
`shard_map` whose inputs shard the sequence dim over that axis) every
attention layer runs ring attention (har_tpu.parallel.ring_attention),
positions are offset by the shard index, and the final mean-pool reduces
over the ring — bit-for-bit the same function, sequence-parallel.

Both constructions share one parameter pytree, so a model trained
single-device serves sequence-parallel and vice versa (tested).

Throughput design (r6, the raw-lane overhaul — docs/roofline.md
"Transformer"):
  - Q/K/V are one fused (E, 3E) projection and the output projection is
    a single Dense — four per-head matmuls never exist separately.
  - ``window_pack=p`` packs p short windows into one attention sequence
    under a block-diagonal mask (ops.flash_attention.segment_*): each
    window still attends only itself (packed-vs-unpacked logits are
    test-pinned equal), but every dense/norm pass sees one long
    (B/p, p·T, E) activation stream and the attention runs either as
    the fused Pallas kernel over the diagonal (scores never leave VMEM)
    or as one large masked GEMM — MXU tiles instead of per-window
    crumbs.
  - Activations stream in bf16 with f32 accumulation everywhere a
    reduction lives (attention scores/softmax, LayerNorm statistics) —
    the same stream-narrow/accumulate-wide pattern as
    FusedBiLSTMLayer's bf16_stream (docs/bilstm_profile.md).
  - ``scan_layers=True`` runs the encoder stack as one ``nn.scan`` over
    stacked per-layer parameters: XLA compiles ONE block body instead
    of unrolling L copies (smaller program, faster compile) and reuses
    the same activation buffers layer to layer instead of materializing
    L distinct intermediates.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from har_tpu.ops.flash_attention import (
    MIN_HEAD_DIM,
    flash_attention,
    pick_block,
    segment_attention,
    segment_flash_attention,
)
from har_tpu.parallel.ring_attention import (
    full_attention,
    ring_attention,
    ring_flash_attention,
)

# sequence length at which the Pallas streaming kernel takes over from
# XLA's fused attention on a single chip.  Measured crossover
# (artifacts/long_context_bench.json, r4): XLA is a few percent faster
# below 8k tokens, the kernel is >=1.0x from 8k and the only path that
# still compiles once the fused attention's working set outgrows HBM
# (attention-only probe: XLA stops at T=16384 x 8 heads; the kernel
# runs to T=65536).
_FLASH_AUTO_T = 8192

# minimum per-window token count for the packed-lane Pallas route: the
# kernel's segment-folded blocks need >= 8 rows AND 8-row (sublane)
# alignment — below/unaligned, the masked-GEMM path runs
_MIN_SEG = 8


def _seg_flash_legal(seg: int, head_dim: int) -> bool:
    """Shapes the segment-folded Pallas route accepts (one kernel block
    per window: >= 8 rows, sublane-aligned, supported head dim)."""
    return head_dim >= MIN_HEAD_DIM and seg >= _MIN_SEG and seg % 8 == 0


class EncoderBlock(nn.Module):
    num_heads: int
    dtype: jnp.dtype
    sp_axis: str | None
    # None = auto: Pallas flash attention for T >= _FLASH_AUTO_T (the
    # measured crossover — see _FLASH_AUTO_T's comment); plain XLA below
    # it (faster at short T, same numerics family).  In packed mode
    # (seg is not None) auto routes the diagonal through the kernel on
    # TPU whenever the shape is legal, the masked GEMM otherwise.
    use_flash: bool | None = None
    # block-diagonal attention segment length (window packing): tokens
    # [i*seg, (i+1)*seg) attend only within their own segment.  None =
    # ordinary full attention over the sequence.
    seg: int | None = None

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        b, t, e = x.shape
        h = self.num_heads
        head_dim = e // h

        y = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * e, dtype=self.dtype, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, h, head_dim)
        k = k.reshape(b, t, h, head_dim)
        v = v.reshape(b, t, h, head_dim)
        if self.seg is not None:
            # packed windows: block-diagonal attention, two exact routes
            # (fused per-window kernel vs one big masked GEMM)
            flash_ok = _seg_flash_legal(self.seg, head_dim)
            if self.use_flash and not flash_ok:
                # same contract as the other paths: an explicit flash
                # request the kernel refuses must fail loudly
                raise ValueError(
                    "use_flash=True with window packing requires "
                    f"head_dim >= {MIN_HEAD_DIM} and per-window tokens "
                    f">= {_MIN_SEG} in multiples of 8; got "
                    f"head_dim={head_dim}, seg={self.seg}"
                )
            # auto: the masked GEMM materializes (T, T) scores for the
            # whole PACKED length, so its cost crosses the kernel's at
            # the same packed-sequence length as unpacked full attention
            # — reuse _FLASH_AUTO_T on t (the packed length), gated on
            # kernel legality for the per-window block
            seg_flash = (
                jax.default_backend() == "tpu"
                and flash_ok
                and t >= _FLASH_AUTO_T
                if self.use_flash is None
                else self.use_flash
            )
            if seg_flash:
                attn = segment_flash_attention(q, k, v, self.seg)
            else:
                attn = segment_attention(q, k, v, self.seg)
        elif self.sp_axis is not None:
            # per-hop local attention: the einsum ring materializes a
            # (B, H, T_local, T_local) score tile per hop; once the
            # local block crosses the same threshold as the single-chip
            # path, run the Pallas kernel per hop instead and merge
            # hops by logaddexp (ring_flash_attention — exact)
            if self.use_flash:
                # same contract as the single-chip path: an explicit
                # flash request for a shape the kernel refuses must fail
                # loudly, not silently run the score-materializing ring
                if head_dim < MIN_HEAD_DIM:
                    raise ValueError(
                        "use_flash=True requires head_dim >= "
                        f"{MIN_HEAD_DIM}, got {head_dim}"
                    )
                if not pick_block(t):
                    raise ValueError(
                        f"use_flash=True: local T={t} has no usable "
                        "flash block (pick_block); pad the sequence or "
                        "drop use_flash"
                    )
            ring_flash = (
                t >= _FLASH_AUTO_T
                and jax.default_backend() == "tpu"
                and head_dim >= MIN_HEAD_DIM
                and pick_block(t) > 0
                if self.use_flash is None
                else self.use_flash
            )
            if ring_flash:
                attn = ring_flash_attention(q, k, v, self.sp_axis)
            else:
                attn = ring_attention(q, k, v, self.sp_axis)
        else:
            flash = (
                # auto mode requires a real TPU (off-TPU the Pallas
                # kernel runs in interpret mode, far slower than XLA's
                # fused attention) and head_dim >= 32 (sub-lane head
                # dims fault the kernel — flash_attention refuses them)
                t >= _FLASH_AUTO_T
                and jax.default_backend() == "tpu"
                and head_dim >= MIN_HEAD_DIM
                if self.use_flash is None
                else self.use_flash
            )
            block = pick_block(t) if flash else 0
            if block:
                attn = flash_attention(
                    q, k, v, block_q=block, block_k=block
                )
            else:
                attn = full_attention(q, k, v)
        attn = attn.reshape(b, t, e)
        x = x + nn.Dense(e, dtype=self.dtype, name="proj")(attn)

        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.Dense(4 * e, dtype=self.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(e, dtype=self.dtype)(y)
        return x + y


class _ScanEncoderBlock(nn.Module):
    """Carry adapter: EncoderBlock under ``nn.scan`` (x is the carry)."""

    num_heads: int
    dtype: jnp.dtype
    sp_axis: str | None
    use_flash: bool | None
    seg: int | None

    @nn.compact
    def __call__(self, x, _):
        x = EncoderBlock(
            self.num_heads, self.dtype, self.sp_axis, self.use_flash,
            seg=self.seg,
        )(x)
        return x, None


class Transformer1D(nn.Module):
    """Encoder classifier: (B, T, C) raw windows → (B, num_classes)."""

    num_classes: int = 6
    embed_dim: int = 64
    num_heads: int = 4
    num_layers: int = 2
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    sp_axis: str | None = None
    use_flash: bool | None = None
    # patch_size > 1 embeds non-overlapping patches with a strided conv
    # (ViT-style) instead of the per-sample Dense: T drops by the patch
    # factor BEFORE attention, cutting the (B, H, T, T) score traffic —
    # the short-T lane's roofline limiter (docs/roofline.md: at T=200
    # attention HBM traffic holds the encoder to ~21% steady MFU) — by
    # patch².  kernel == stride, so a sequence-sharded input needs no
    # halo exchange and the sp ring path works unchanged on patched
    # sequences.
    patch_size: int = 1
    # window_pack > 1 packs that many windows into one block-diagonal
    # attention sequence AFTER patch embedding (see the module
    # docstring).  Batches not divisible by the pack are zero-padded and
    # the padding windows sliced back off — block-diagonality means
    # padding can never leak into real windows.  Mutually exclusive
    # with sp_axis (the ring shards one long sequence; packing glues
    # many short ones).
    window_pack: int = 1
    # scan_layers=True compiles the encoder stack as one nn.scan over
    # stacked per-layer params (one block body, reused buffers) instead
    # of num_layers unrolled copies.  Parameter layout differs (leaves
    # gain a leading layer axis under "blocks"), so it is opt-in; the
    # bench lane uses it, parity-era checkpoints predate it.
    scan_layers: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        if self.window_pack > 1 and self.sp_axis is not None:
            raise ValueError(
                "window_pack and sp_axis are mutually exclusive: the "
                "ring sequence-shards one long window, packing glues "
                "many short ones"
            )
        x = x.astype(self.dtype)
        b, t, _ = x.shape
        if self.patch_size > 1:
            if t % self.patch_size:
                raise ValueError(
                    f"sequence length {t} must be divisible by "
                    f"patch_size {self.patch_size}"
                )
            x = nn.Conv(
                self.embed_dim,
                kernel_size=(self.patch_size,),
                strides=(self.patch_size,),
                padding="VALID",
                dtype=self.dtype,
                name="patch_embed",
            )(x)
            t = t // self.patch_size
        else:
            x = nn.Dense(self.embed_dim, dtype=self.dtype, name="embed")(x)
        if self.sp_axis is None:
            offset = 0.0
        else:  # global position = shard index × local block length
            offset = (jax.lax.axis_index(self.sp_axis) * t).astype(
                jnp.float32
            )
        # positions are per-window and applied BEFORE packing, so every
        # packed window carries the identical encoding it would alone
        x = x + sinusoidal_positions(t, self.embed_dim, offset).astype(
            self.dtype
        )
        seg = None
        pack_pad = 0
        if self.window_pack > 1:
            pack_pad = (-b) % self.window_pack
            if pack_pad:
                x = jnp.concatenate(
                    [x, jnp.zeros((pack_pad, t, self.embed_dim), x.dtype)],
                    axis=0,
                )
            x = x.reshape(
                (b + pack_pad) // self.window_pack,
                self.window_pack * t,
                self.embed_dim,
            )
            seg = t
        if self.scan_layers:
            x, _ = nn.scan(
                _ScanEncoderBlock,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=self.num_layers,
            )(
                self.num_heads, self.dtype, self.sp_axis, self.use_flash,
                seg, name="blocks",
            )(x, None)
        else:
            for _ in range(self.num_layers):
                x = EncoderBlock(
                    self.num_heads, self.dtype, self.sp_axis,
                    self.use_flash, seg=seg,
                )(x, train=train)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        if self.window_pack > 1:
            # per-window mean-pool, then drop the padding windows
            x = x.reshape(-1, self.window_pack, t, self.embed_dim)
            pooled = x.mean(axis=2).reshape(-1, self.embed_dim)[:b]
        else:
            pooled = x.mean(axis=1)
            if self.sp_axis is not None:
                # local mean → global mean (equal-size shards on the ring)
                pooled = jax.lax.pmean(pooled, self.sp_axis)
        pooled = nn.Dropout(self.dropout_rate, deterministic=not train)(
            pooled
        )
        logits = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(
            pooled
        )
        return logits.astype(jnp.float32)


def sinusoidal_positions(t: int, dim: int, offset) -> jax.Array:
    """Standard sin/cos positional encoding, positions offset (traced ok)."""
    pos = jnp.arange(t, dtype=jnp.float32) + offset
    half = dim // 2
    freqs = jnp.exp(
        -jnp.log(10000.0) * jnp.arange(half, dtype=jnp.float32) / half
    )
    angles = pos[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.sin(angles), jnp.cos(angles)], axis=-1)
