"""Transformer encoder classifier over raw accelerometer windows.

A 4th neural family member (beyond MLP/CNN/BiLSTM) and the carrier for
long-context support: constructed with ``sp_axis=None`` it's an ordinary
single-device encoder; constructed with ``sp_axis="sp"`` (inside a
`shard_map` whose inputs shard the sequence dim over that axis) every
attention layer runs ring attention (har_tpu.parallel.ring_attention),
positions are offset by the shard index, and the final mean-pool reduces
over the ring — bit-for-bit the same function, sequence-parallel.

Both constructions share one parameter pytree, so a model trained
single-device serves sequence-parallel and vice versa (tested).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from har_tpu.ops.flash_attention import (
    MIN_HEAD_DIM,
    flash_attention,
    pick_block,
)
from har_tpu.parallel.ring_attention import (
    full_attention,
    ring_attention,
    ring_flash_attention,
)

# sequence length at which the Pallas streaming kernel takes over from
# XLA's fused attention on a single chip.  Measured crossover
# (artifacts/long_context_bench.json, r4): XLA is a few percent faster
# below 8k tokens, the kernel is >=1.0x from 8k and the only path that
# still compiles once the fused attention's working set outgrows HBM
# (attention-only probe: XLA stops at T=16384 x 8 heads; the kernel
# runs to T=65536).
_FLASH_AUTO_T = 8192


def sinusoidal_positions(t: int, dim: int, offset) -> jax.Array:
    """Standard sin/cos positional encoding, positions offset (traced ok)."""
    pos = jnp.arange(t, dtype=jnp.float32) + offset
    half = dim // 2
    freqs = jnp.exp(
        -jnp.log(10000.0) * jnp.arange(half, dtype=jnp.float32) / half
    )
    angles = pos[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.sin(angles), jnp.cos(angles)], axis=-1)


class EncoderBlock(nn.Module):
    num_heads: int
    dtype: jnp.dtype
    sp_axis: str | None
    # None = auto: Pallas flash attention for T >= _FLASH_AUTO_T (the
    # measured crossover — see _FLASH_AUTO_T's comment); plain XLA below
    # it (faster at short T, same numerics family)
    use_flash: bool | None = None

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        b, t, e = x.shape
        h = self.num_heads
        head_dim = e // h

        y = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * e, dtype=self.dtype, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, h, head_dim)
        k = k.reshape(b, t, h, head_dim)
        v = v.reshape(b, t, h, head_dim)
        if self.sp_axis is not None:
            # per-hop local attention: the einsum ring materializes a
            # (B, H, T_local, T_local) score tile per hop; once the
            # local block crosses the same threshold as the single-chip
            # path, run the Pallas kernel per hop instead and merge
            # hops by logaddexp (ring_flash_attention — exact)
            if self.use_flash:
                # same contract as the single-chip path: an explicit
                # flash request for a shape the kernel refuses must fail
                # loudly, not silently run the score-materializing ring
                if head_dim < MIN_HEAD_DIM:
                    raise ValueError(
                        "use_flash=True requires head_dim >= "
                        f"{MIN_HEAD_DIM}, got {head_dim}"
                    )
                if not pick_block(t):
                    raise ValueError(
                        f"use_flash=True: local T={t} has no usable "
                        "flash block (pick_block); pad the sequence or "
                        "drop use_flash"
                    )
            ring_flash = (
                t >= _FLASH_AUTO_T
                and jax.default_backend() == "tpu"
                and head_dim >= MIN_HEAD_DIM
                and pick_block(t) > 0
                if self.use_flash is None
                else self.use_flash
            )
            if ring_flash:
                attn = ring_flash_attention(q, k, v, self.sp_axis)
            else:
                attn = ring_attention(q, k, v, self.sp_axis)
        else:
            flash = (
                # auto mode requires a real TPU (off-TPU the Pallas
                # kernel runs in interpret mode, far slower than XLA's
                # fused attention) and head_dim >= 32 (sub-lane head
                # dims fault the kernel — flash_attention refuses them)
                t >= _FLASH_AUTO_T
                and jax.default_backend() == "tpu"
                and head_dim >= MIN_HEAD_DIM
                if self.use_flash is None
                else self.use_flash
            )
            block = pick_block(t) if flash else 0
            if block:
                attn = flash_attention(
                    q, k, v, block_q=block, block_k=block
                )
            else:
                attn = full_attention(q, k, v)
        attn = attn.reshape(b, t, e)
        x = x + nn.Dense(e, dtype=self.dtype, name="proj")(attn)

        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = nn.Dense(4 * e, dtype=self.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(e, dtype=self.dtype)(y)
        return x + y


class Transformer1D(nn.Module):
    """Encoder classifier: (B, T, C) raw windows → (B, num_classes)."""

    num_classes: int = 6
    embed_dim: int = 64
    num_heads: int = 4
    num_layers: int = 2
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    sp_axis: str | None = None
    use_flash: bool | None = None
    # patch_size > 1 embeds non-overlapping patches with a strided conv
    # (ViT-style) instead of the per-sample Dense: T drops by the patch
    # factor BEFORE attention, cutting the (B, H, T, T) score traffic —
    # the short-T lane's roofline limiter (docs/roofline.md: at T=200
    # attention HBM traffic holds the encoder to ~21% steady MFU) — by
    # patch².  kernel == stride, so a sequence-sharded input needs no
    # halo exchange and the sp ring path works unchanged on patched
    # sequences.
    patch_size: int = 1

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.astype(self.dtype)
        b, t, _ = x.shape
        if self.patch_size > 1:
            if t % self.patch_size:
                raise ValueError(
                    f"sequence length {t} must be divisible by "
                    f"patch_size {self.patch_size}"
                )
            x = nn.Conv(
                self.embed_dim,
                kernel_size=(self.patch_size,),
                strides=(self.patch_size,),
                padding="VALID",
                dtype=self.dtype,
                name="patch_embed",
            )(x)
            t = t // self.patch_size
        else:
            x = nn.Dense(self.embed_dim, dtype=self.dtype, name="embed")(x)
        if self.sp_axis is None:
            offset = 0.0
        else:  # global position = shard index × local block length
            offset = (jax.lax.axis_index(self.sp_axis) * t).astype(
                jnp.float32
            )
        x = x + sinusoidal_positions(t, self.embed_dim, offset).astype(
            self.dtype
        )
        for _ in range(self.num_layers):
            x = EncoderBlock(
                self.num_heads, self.dtype, self.sp_axis, self.use_flash
            )(x, train=train)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        pooled = x.mean(axis=1)
        if self.sp_axis is not None:
            # local mean → global mean (equal-size shards around the ring)
            pooled = jax.lax.pmean(pooled, self.sp_axis)
        pooled = nn.Dropout(self.dropout_rate, deterministic=not train)(
            pooled
        )
        logits = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(
            pooled
        )
        return logits.astype(jnp.float32)
