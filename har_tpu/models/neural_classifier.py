"""Estimator-protocol wrapper around the Flax models + Trainer.

Gives the neural family the same fit/transform surface as the classical
models (har_tpu.models.base), so cross-validation, the report writer, and
the CLI treat an MLP exactly like MLlib's estimators are treated by the
reference script (fit → model.transform, Main/main.py:115-130).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import numpy as np

from har_tpu.features.scaler import FittedScaler, StandardScaler
from har_tpu.features.wisdm_pipeline import FeatureSet
from har_tpu.models.base import Predictions
from har_tpu.models.neural import build_model
from har_tpu.train.trainer import NeuralModel, Trainer, TrainerConfig


@dataclasses.dataclass(frozen=True)
class NeuralClassifier:
    model_name: str = "mlp"
    config: TrainerConfig = dataclasses.field(default_factory=TrainerConfig)
    model_kwargs: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    standardize: bool = True
    num_classes: int | None = None
    mesh: Any = None
    # augmentation policy name (har_tpu.data.augment.build_augment);
    # "raw_windows" enables jitter/scale/rotation/time-mask inside the
    # compiled train step — raw (B, T, 3) window models only
    augment: str | None = None
    # Warm-refit state: repeat ``fit`` calls on the SAME FeatureSet
    # object (a bench lane timing several fits of one workload) reuse
    # the fitted scaler, the standardized feature array, and the same
    # Trainer — whose scan-path cache then skips re-trace and re-upload
    # (train/trainer.py _scan_cache).  Keyed on data identity: the
    # FeatureSet is held strongly here, so its id cannot be recycled
    # while cached.  compare/repr-excluded — the cache is not part of
    # the estimator's value.
    # init=False: copy_with/replace copies start with a fresh cache (a
    # copy may carry a different config, which must not hit this one's)
    _fit_cache: dict = dataclasses.field(
        default_factory=dict, init=False, compare=False, repr=False
    )

    def copy_with(self, **params) -> "NeuralClassifier":
        known = {f.name for f in dataclasses.fields(self)}
        direct = {k: v for k, v in params.items() if k in known}
        extra = {k: v for k, v in params.items() if k not in known}
        if extra:
            direct["config"] = dataclasses.replace(self.config, **extra)
        return dataclasses.replace(self, **direct)

    def fit(self, data: FeatureSet) -> "NeuralClassifierModel":
        cache = self._fit_cache
        if cache.get("data") is data:
            # warm refit: same FeatureSet object — reuse the fitted
            # scaler, the standardized array (same ndarray identity, so
            # the Trainer's scan cache recognizes its device copy), and
            # the same Trainer (whose traced program survives)
            x, y = cache["x"], cache["y"]
            num_classes, scaler = cache["num_classes"], cache["scaler"]
            trainer = cache["trainer"]
        else:
            x = np.asarray(data.features, np.float32)
            y = np.asarray(data.label, np.int32)
            num_classes = self.num_classes or int(y.max()) + 1
            scaler = StandardScaler().fit(x) if self.standardize else None
            if scaler is not None:
                x = scaler.transform(x)
            from har_tpu.data.augment import build_augment

            module = build_model(
                self.model_name, num_classes=num_classes,
                **self.model_kwargs
            )
            trainer = Trainer(
                module, self.config, mesh=self.mesh,
                augment=build_augment(self.augment),
            )
            cache.clear()
            cache.update(
                data=data, x=x, y=y, num_classes=num_classes,
                scaler=scaler, trainer=trainer,
            )
        trained = trainer.fit(x, y, num_classes=num_classes)
        return NeuralClassifierModel(
            inner=trained, scaler=scaler, num_classes=num_classes
        )


@dataclasses.dataclass(frozen=True)
class NeuralClassifierModel:
    inner: NeuralModel
    scaler: FittedScaler | None
    num_classes: int

    @property
    def history(self) -> dict | None:
        return self.inner.history

    def transform(self, data) -> Predictions:
        x = data.features if hasattr(data, "features") else data
        x = np.asarray(x, np.float32)
        if self.scaler is not None:
            x = self.scaler.transform(x)
        return self.inner.transform(x)
